"""Benchmark: dynamic-batching serving (paddle_tpu/serving/) sustained
throughput + latency for two inference endpoints — LeNet (dense vision)
and DeepFM (sparse CTR).

Prints ONE JSON line like bench.py: per-endpoint sustained rows/sec,
request p50/p99 latency, mean batch occupancy, warmup compile count,
and the recompile counter (must stay 0 after warmup — the bucket
ladder's whole point).  Traffic is an open-loop storm of concurrent
submitters with mixed request sizes, so the DynamicBatcher actually
coalesces rather than replaying fixed batches.

Since PR 3 the server worker runs the non-blocking fetch path
(AnalysisPredictor ``return_numpy=False``): batch N's d2h materialize
overlaps batch N+1's merge/pad/dispatch, so the numbers here include
the overlap discipline a production deployment would run with
(``d2h_overlap`` in the line records it).

Env knobs: BENCH_SERVING_THREADS (default 8), BENCH_SERVING_REQUESTS
(per thread, default 100), BENCH_SERVING_MAX_BATCH (default 16),
BENCH_SERVING_TIMEOUT_MS (batch window, default 2),
BENCH_SERVING_TRACE (JSONL trace path, default off).

``--trace-out PATH`` (or $BENCH_SERVING_TRACE_OUT) additionally runs
the storm under a flight recorder and dumps the SLOWEST 1% of bench
requests' full span trees (client -> queue wait -> batch -> executor
phases, one trace id each) to PATH alongside the JSON line — the
latency tail, explained.  Without it the bench asserts the recorder
stays absent and every span gate off: zero recorder overhead on the
measured warm path.

``--wire loopback`` (or $BENCH_SERVING_WIRE=loopback) measures the
WIRE TAX instead: each endpoint is benched in-process AND over
loopback TCP through a launched serving child
(``paddle_tpu.serving.wire``), and the JSON line reports the
client-observed p50/p99 for both plus their delta
(``wire_tax_p50_ms``/``wire_tax_p99_ms``) — the cost of the codec +
HTTP hop as a measured number.  The child warms up through the same
persistent compile cache, and its recompile counter must stay 0
(asserted via ``/statusz`` over the wire).

``--overload`` (or $BENCH_SERVING_OVERLOAD=1) runs the graceful-
degradation sweep instead: measure the endpoint's saturation
throughput closed-loop, then drive OPEN-loop offered load at 1x/2x/3x
saturation with mixed priority classes and record, per stage and per
priority, goodput / shed / expired counts and client-observed p99 —
plus the adaptive admit limit and brownout level the server settled
at, and the median ``retry_after_ms`` hint the sheds carried.  The
headline value is goodput at 3x as a fraction of saturation: a
production edge must keep it flat past the knee (the chaos suite
asserts the >= 0.7 floor; the bench records the curve).
Env knobs: BENCH_OVERLOAD_SECONDS (per stage, default 3),
BENCH_OVERLOAD_MULTIPLIERS (default "1,2,3").

``--decode`` (or $BENCH_SERVING_DECODE=1) benches the CONTINUOUS-
BATCHING decode scheduler (``serving.decode``) on a transformer-LM
endpoint under mixed prompt/decode traffic: the same interleaved
long/short workload is decoded request-at-a-time (admit in groups of
``max_slots``, wait out each group — what the request-batching server
does to an autoregressive endpoint) and continuously (finished
sequences free slots mid-flight, queued prompts join at the next
tick).  The line reports tokens/s for both, their ratio (the
acceptance bar is >= 2x on this mixed workload), streamed-client TTFT
percentiles, the late-arrival drill (a request submitted mid-decode
must reach its first token before the in-flight batch finishes), the
prefill/decode token ratio, and the recompile count (0 after warmup —
the slot pool's bucket ladders keep the compiled-shape set closed).
Env knobs: BENCH_DECODE_REQUESTS (default 24), BENCH_DECODE_SLOTS
(default 8), BENCH_DECODE_STEPS (per tick, default 4).

Since decode tier 2 the ``--decode`` line also carries the three
independently toggleable decode-tier-2 legs, each measured against its
own off-baseline on the same staggered drill:

* ``prefix_cache``: ten requests sharing a 48-token prompt prefix,
  submitted staggered (each waits its result so the freed slot's
  prefix KV is offered before the next probe) against a server with
  and without a :class:`serving.prefix_cache.PrefixKVCache` — the
  prefill-token counter must drop >= 50% with the cache on (asserted),
  and TTFT p50 rides the line for both.
* ``speculative``: the same prompts decoded with and without
  draft-then-verify rounds on ONE server at ``steps_per_tick=1`` (the
  dispatch-bound regime a k-wide accepted run amortizes), using a
  unigram transition-table draft distilled from the baseline pass's
  own greedy rollouts.  Greedy-exact acceptance pins parity — the
  speculative pass must emit bit-identical sequences (asserted) — and
  the line reports tokens/s both ways plus the acceptance telemetry.
* ``affinity``: a REAL 2-child wire fleet hosting one saved decode
  endpoint with per-child prefix caches, driven by returning
  "sessions" (prompts sharing a per-session head) through a
  prefix-affinity balancer and a plain least-loaded one — per-child
  ``/healthz`` prefix-cache hit deltas, fleet ``affinity_hits``, and
  both children's ``/statusz`` jit-cache misses (must be 0; asserted)
  ride the line.

Env knobs: BENCH_DECODE_PREFIX_REQUESTS (default 10),
BENCH_DECODE_SPEC_REQUESTS / BENCH_DECODE_SPEC_GEN /
BENCH_DECODE_SPEC_K (default 8/24/8), BENCH_DECODE_AFFINITY_SESSIONS /
BENCH_DECODE_AFFINITY_ROUNDS (default 4/3).

``--sharded`` (or $BENCH_SERVING_SHARDED=1) benches MODEL-PARALLEL
serving (``paddle_tpu.sharding``): the same transformer-LM endpoint
served replicated vs as a 2-way tp group on the 8-device CPU mesh
(the canonical layout rides the saved model's manifest, so the
predictor reconstructs the placement on load exactly like a serving
child would).  The line reports QPS for both, the post-warmup
recompile count (must stay 0 — sharded out_shardings pin the state
layout, so the jit-cache shape set stays closed), and the per-device
HBM footprint vs the replicated baseline (sharded params hold 1/tp of
their bytes per device — the capacity headroom the layout buys).
Env knobs: BENCH_SHARDED_TP (default 2).

``--long-context`` (or $BENCH_SERVING_LONG_CONTEXT=1) benches
LONG-CONTEXT serving: the fused-attention transformer LM at a sequence
length whose UNSHARDED activations exceed the per-chip budget
(BENCH_LC_CHIP_BUDGET_BYTES, default 16 MiB), served three ways —
unsharded, sp-2, sp-4 (the canonical ``sp`` layout rides the manifest;
attention runs as ring attention over the sp mesh axis) — plus the
same export as a pp-2 ``PipelinePredictor`` micro-batched (M=4) vs
sequential (M=1).  The line reports tokens/s and activation
bytes/device per leg and asserts: sp-4 logits match unsharded at
rtol 2e-4, sp-4 activation bytes/device are exactly 1/4 of unsharded
(and fit the budget the unsharded footprint exceeds), a post-warmup
mixed-length storm never recompiles, pipelined output is exact, and
the executed pp-2/M-4 schedule's bubble ratio is < 0.5 (the
sequential M=1 schedule pins the 0.5 worst case it must beat).
Env knobs: BENCH_LC_SEQ (default 512), BENCH_LC_BATCH (4),
BENCH_LC_REPS (6), BENCH_LC_CHIP_BUDGET_BYTES.

``--precision`` (or $BENCH_SERVING_PRECISION=1) benches MIXED-PRECISION
serving (``contrib/mixed_precision`` pointed at the inference path):
LeNet and DeepFM each served plain fp32 vs under a bf16 precision
policy (the policy rides the saved-model manifest; the loader rebuilds
the rewrite and casts hoisted params to bf16 at placement time).  The
line reports QPS and p99 both ways plus their ratios, the export-time
and runtime parity vs fp32 (both must sit inside the exported rtol
bound), per-endpoint padding waste, and the recompile counters (0
after warmup for BOTH the bf16 default and the per-request fp32
opt-out — warmup compiles every bucket rung for every serving dtype).
The acceptance leg launches a REAL 2-child wire fleet over the bf16
manifest dir: children reconstruct the variant from the manifest,
fleet warmup covers both ladders in both processes, a mixed
bf16/fp32-opt-out storm runs through the balancer, and each child's
``/statusz`` recompile count must stay 0.

NOTE on the CPU backend the qps ratio is typically < 1: CPUs emulate
bf16 (upcast-compute-downcast), so the variant pays cast cost with no
bandwidth win.  The line measures the HARNESS (parity, recompiles,
manifest transport, both ladders warmed); the speedup itself is a TPU
number — bf16 halves the HBM bytes an inference step moves, which is
the binding constraint at MFU 0.13 (BENCH_r05).

``--fleet-obs`` (or $BENCH_SERVING_FLEET_OBS=1) benches the FLEET
OBSERVABILITY control tower: one REAL 2-child wire fleet serving the
LeNet endpoint, driven by the same staggered-arrival storm twice —
once bare, once with the balancer's federated admin tier up, the
scraper riding the health loop, and a latency SLO burn-rate engine
evaluating every 100 ms.  The line asserts the tower's three
contracts: (1) the federated ``/metrics`` carries every child
``serving_*`` counter series verbatim under a distinct ``backend=``
label and ``/statusz``'s fleet aggregate equals the children's sum
exactly; (2) an injected-latency window (``fleet.dispatch`` delay
fault in the balancer) drives the fast-burn pair of the p99 SLO to
fire — visible in ``/sloz`` and as a critical ``slo/fired`` event in
``/eventz`` — and clean traffic clears it again; (3) observability-on
QPS stays within 2% of bare (BENCH_OBS_QPS_FLOOR, default 0.98) and
both children's recompile counters stay 0.
Env knobs: BENCH_OBS_QPS_FLOOR, BENCH_OBS_FAULT_DELAY_S (default 0.6).
"""
import json
import os
import tempfile
import threading
import time

import numpy as np

THREADS = int(os.environ.get("BENCH_SERVING_THREADS", "8"))
REQUESTS = int(os.environ.get("BENCH_SERVING_REQUESTS", "100"))
MAX_BATCH = int(os.environ.get("BENCH_SERVING_MAX_BATCH", "16"))
TIMEOUT_MS = float(os.environ.get("BENCH_SERVING_TIMEOUT_MS", "2"))
# request sizes cycle through this ladder so batches mix row counts
REQ_SIZES = (1, 2, 3, 4)


def _save_lenet(dirname, precision=None):
    import paddle_tpu as fluid
    from paddle_tpu import framework, models

    prog, startup = framework.Program(), framework.Program()
    prog.random_seed = startup.random_seed = 11
    with framework.program_guard(prog, startup):
        img = fluid.layers.data("img", [1, 28, 28])
        lbl = fluid.layers.data("lbl", [1], dtype="int64")
        _, _, pred = models.lenet5(img, lbl)
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.save_inference_model(dirname, ["img"], [pred], exe, prog,
                                   precision_policy=precision)

    def make_rows(n, rng):
        return {"img": rng.uniform(-1, 1, (n, 1, 28, 28)).astype(np.float32)}

    return make_rows


def _save_deepfm(dirname, num_features=10000, num_fields=39, precision=None):
    import paddle_tpu as fluid
    from paddle_tpu import framework, models

    prog, startup = framework.Program(), framework.Program()
    prog.random_seed = startup.random_seed = 13
    with framework.program_guard(prog, startup):
        ids = fluid.layers.data("feat_ids", [num_fields, 1], dtype="int64")
        vals = fluid.layers.data("feat_vals", [num_fields])
        lbl = fluid.layers.data("lbl", [1], dtype="int64")
        _, prob = models.deepfm_ctr(
            ids, vals, lbl, num_features=num_features, num_fields=num_fields,
            embed_dim=8, deep_layers=(64, 64))
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(fluid.Scope()):
        exe.run(startup)
        fluid.save_inference_model(dirname, ["feat_ids", "feat_vals"], [prob],
                                   exe, prog, precision_policy=precision)

    def make_rows(n, rng):
        return {
            "feat_ids": rng.randint(0, num_features, (n, num_fields, 1)).astype(np.int64),
            "feat_vals": rng.uniform(0, 1, (n, num_fields)).astype(np.float32),
        }

    return make_rows


def _trace_out_path(argv=None):
    """Opt-in flight-recorder dump target: ``--trace-out PATH`` /
    ``--trace-out=PATH`` on the command line, or $BENCH_SERVING_TRACE_OUT."""
    import bench_common

    return bench_common.flag_path(
        "--trace-out", "BENCH_SERVING_TRACE_OUT", argv)


def _bench_endpoint(name, save_fn):
    from paddle_tpu import monitor, serving
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor
    from paddle_tpu.monitor import flight as _flight

    with tempfile.TemporaryDirectory() as tmp:
        d = os.path.join(tmp, name)
        make_rows = save_fn(d)
        predictor = create_paddle_predictor(AnalysisConfig(d))
        server = serving.InferenceServer(
            predictor, max_batch_size=MAX_BATCH, batch_timeout_ms=TIMEOUT_MS,
            queue_capacity=max(64, THREADS * 8), name=name)
        t0 = time.perf_counter()
        warmup_compiles = server.warmup()
        warmup_s = time.perf_counter() - t0
        cli = serving.Client(server)
        if _flight.get() is None:
            # recorder at defaults (absent): every span gate the serving
            # and executor hot paths consult must be off, so the number
            # below carries ZERO recorder overhead (the --trace-out mode
            # opts into the capture cost explicitly)
            assert not monitor.recording(), (
                "span recording leaked into the bench warm path")

        total_rows = [0] * THREADS
        shed = [0] * THREADS
        start = threading.Barrier(THREADS + 1)
        # padding-waste accounting around the storm only (warmup pads
        # every rung fully by construction — counting it would dilute
        # the number the ladder autotuner is judged on): the predictor
        # counters have been collected since PR 2; this REPORTS them
        padded0 = monitor.counter_value("predictor_padded_rows_total")
        waste0 = monitor.counter_value("predictor_padding_waste_rows_total")

        def storm(tid):
            rng = np.random.RandomState(100 + tid)
            start.wait()
            for i in range(REQUESTS):
                n = REQ_SIZES[(tid + i) % len(REQ_SIZES)]
                try:
                    cli.infer(make_rows(n, rng))
                    total_rows[tid] += n
                except serving.ServerOverloaded:
                    shed[tid] += 1  # open-loop storm may outrun the queue

        threads = [threading.Thread(target=storm, args=(t,)) for t in range(THREADS)]
        for t in threads:
            t.start()
        start.wait()
        t0 = time.perf_counter()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        # the PR-1 zero-recompile guarantee, enforced IN the bench via
        # the monitor registry (not just tests): after warmup the jit
        # cache must never miss, or the rows/sec number is a lie that
        # includes XLA compiles.  Read BEFORE stop(): every request has
        # completed (cli.infer blocks), and stop() retires this server's
        # series from the registry exposition.
        from paddle_tpu import monitor

        registry_recompiles = monitor.counter_value(
            "serving_recompiles_total", default=-1, server=name)
        padded_rows = (
            monitor.counter_value("predictor_padded_rows_total") - padded0)
        waste_rows = (
            monitor.counter_value("predictor_padding_waste_rows_total")
            - waste0)
        server.stop(drain=True)
        m = server.metrics()
        if registry_recompiles != 0 or m["recompiles"] != 0:
            raise AssertionError(
                "endpoint %r recompiled after warmup: registry=%s snapshot=%s"
                % (name, registry_recompiles, m["recompiles"]))
        rows = sum(total_rows)
        sharding_stats = None
        if getattr(predictor, "sharded", False):
            sharding_stats = predictor.sharding_stats()
        return {
            "rows_per_sec": round(rows / elapsed, 1),
            **({"sharding": sharding_stats} if sharding_stats else {}),
            "d2h_overlap": bool(server._nonblocking),
            "requests_per_sec": round(m["completed"] / elapsed, 1),
            "latency_p50_ms": m["latency_p50_ms"],
            "latency_p99_ms": m["latency_p99_ms"],
            "mean_batch_occupancy": m["mean_batch_occupancy"],
            # the bucket ladder's measured rent: padding rows computed
            # then sliced away, as a fraction of all padded rows — the
            # number an autotuned ladder must strictly reduce
            "padding_waste_ratio": (
                round(waste_rows / padded_rows, 4) if padded_rows else None),
            "padding_waste_rows": int(waste_rows),
            "arrival_histogram": m["arrival_histogram"],
            "batches": m["batches"],
            "completed": m["completed"],
            "shed": m["shed"],
            "expired": m["expired"],
            "recompiles_after_warmup": m["recompiles"],
            "warmup_compiles": warmup_compiles,
            "warmup_s": round(warmup_s, 2),
            "bucket_ladder": m["bucket_ladder"],
            "elapsed_s": round(elapsed, 2),
        }


def _bench_endpoint_wire(name, save_fn):
    """Client-observed latency for one endpoint served by a launched
    child process over loopback TCP (the wire half of the tax
    measurement; the in-process half is _bench_endpoint)."""
    from paddle_tpu.serving import wire

    with tempfile.TemporaryDirectory() as tmp:
        d = os.path.join(tmp, name)
        make_rows = save_fn(d)
        handle = wire.launch_server(
            d, name="%s-wire" % name, max_batch_size=MAX_BATCH,
            batch_timeout_ms=TIMEOUT_MS,
            queue_capacity=max(64, THREADS * 8))
        cli = wire.RemoteClient(handle.address)
        try:
            t0 = time.perf_counter()
            warmup_compiles = handle.warmup()
            warmup_s = time.perf_counter() - t0

            lats = [[] for _ in range(THREADS)]
            shed = [0] * THREADS
            start = threading.Barrier(THREADS + 1)

            def storm(tid):
                import paddle_tpu.serving as serving

                rng = np.random.RandomState(200 + tid)
                start.wait()
                for i in range(REQUESTS):
                    n = REQ_SIZES[(tid + i) % len(REQ_SIZES)]
                    feed = make_rows(n, rng)
                    r0 = time.perf_counter()
                    try:
                        cli.infer(feed)
                        lats[tid].append(time.perf_counter() - r0)
                    except serving.ServerOverloaded:
                        shed[tid] += 1

            threads = [threading.Thread(target=storm, args=(t,))
                       for t in range(THREADS)]
            for t in threads:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0

            status = wire.HttpTransport(*handle.address).get_json("/statusz")
            recompiles = status["metrics"]["recompiles"]
            if recompiles != 0:
                raise AssertionError(
                    "wire endpoint %r recompiled after warmup: %s"
                    % (name, recompiles))
            all_lats = np.asarray(
                [v for per in lats for v in per], dtype=np.float64)
            rows = sum(
                REQ_SIZES[(t + i) % len(REQ_SIZES)]
                for t in range(THREADS)
                for i in range(len(lats[t])))
            return {
                "rows_per_sec": round(rows / elapsed, 1),
                "requests_per_sec": round(all_lats.size / elapsed, 1),
                "latency_p50_ms": round(
                    float(np.percentile(all_lats, 50)) * 1e3, 3),
                "latency_p99_ms": round(
                    float(np.percentile(all_lats, 99)) * 1e3, 3),
                "completed": int(all_lats.size),
                "shed": int(sum(shed)),
                "server_metrics": {
                    k: status["metrics"][k]
                    for k in ("completed", "batches", "latency_p50_ms",
                              "latency_p99_ms", "mean_batch_occupancy")},
                "recompiles_after_warmup": int(recompiles),
                "warmup_compiles": int(warmup_compiles),
                "warmup_s": round(warmup_s, 2),
                "elapsed_s": round(elapsed, 2),
                "backend_pid": handle.pid,
            }
        finally:
            cli.close()
            handle.shutdown()


def run_wire():
    """The ``--wire loopback`` line: in-process vs loopback-TCP numbers
    for the same endpoints, plus the measured wire tax."""
    import jax

    import bench_common

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    endpoints = {}
    for name, save_fn in (("lenet", _save_lenet), ("deepfm", _save_deepfm)):
        inproc = _bench_endpoint(name, save_fn)
        over_wire = _bench_endpoint_wire(name, save_fn)
        endpoints[name] = {
            "inprocess": inproc,
            "wire": over_wire,
            "wire_tax_p50_ms": round(
                over_wire["latency_p50_ms"] - inproc["latency_p50_ms"], 3),
            "wire_tax_p99_ms": round(
                over_wire["latency_p99_ms"] - inproc["latency_p99_ms"], 3),
        }
    from paddle_tpu import monitor

    # parent-side codec cost across the whole wire storm (the children
    # have their own registries): histogram sum/count over both ops
    codec = monitor.snapshot().get("wire_codec_seconds") or {}
    codec_sum = sum(
        s["value"]["sum"] for s in codec.get("series", ()))
    codec_count = sum(
        s["value"]["count"] for s in codec.get("series", ()))
    return {
        "metric": "serving_wire_tax",
        "unit": "ms",
        "value": endpoints["lenet"]["wire_tax_p50_ms"],
        "endpoints": endpoints,
        "codec_seconds_sum": round(codec_sum, 4),
        "codec_messages": int(codec_count),
        "threads": THREADS,
        "requests_per_thread": REQUESTS,
        "max_batch_size": MAX_BATCH,
        "batch_timeout_ms": TIMEOUT_MS,
        "platform": jax.devices()[0].platform,
    }


def _bench_overload(name, save_fn):
    """The graceful-degradation sweep for one endpoint: saturation
    throughput first (closed loop), then open-loop offered load at
    multiples of it with mixed priorities."""
    from paddle_tpu import serving
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor

    stage_s = float(os.environ.get("BENCH_OVERLOAD_SECONDS", "3"))
    multipliers = tuple(
        float(m) for m in os.environ.get(
            "BENCH_OVERLOAD_MULTIPLIERS", "1,2,3").split(","))
    deadline_ms = float(os.environ.get("BENCH_OVERLOAD_DEADLINE_MS", "2000"))
    prios = (("high", serving.PRIORITY_HIGH),
             ("normal", serving.PRIORITY_NORMAL),
             ("low", serving.PRIORITY_LOW))

    with tempfile.TemporaryDirectory() as tmp:
        d = os.path.join(tmp, name)
        make_rows = save_fn(d)
        predictor = create_paddle_predictor(AnalysisConfig(d))
        server = serving.InferenceServer(
            predictor, max_batch_size=MAX_BATCH, batch_timeout_ms=TIMEOUT_MS,
            queue_capacity=max(64, THREADS * 8), name=name)
        try:
            server.warmup()
            cli = serving.Client(server)

            # --- saturation: closed-loop storm, completed requests/sec
            done = [0] * THREADS
            stop_flag = threading.Event()
            start = threading.Barrier(THREADS + 1)

            def closed(tid):
                rng = np.random.RandomState(300 + tid)
                start.wait()
                while not stop_flag.is_set():
                    n = REQ_SIZES[(tid + done[tid]) % len(REQ_SIZES)]
                    try:
                        cli.infer(make_rows(n, rng), timeout_ms=deadline_ms)
                        done[tid] += 1
                    except serving.ServingError:
                        pass

            threads = [threading.Thread(target=closed, args=(t,))
                       for t in range(THREADS)]
            for t in threads:
                t.start()
            start.wait()
            t0 = time.perf_counter()
            time.sleep(stage_s)
            stop_flag.set()
            for t in threads:
                t.join()
            sat_rps = sum(done) / (time.perf_counter() - t0)

            # --- overload sweep: open-loop submission at mult * sat_rps
            stages = {}
            rng = np.random.RandomState(7)
            for mult in multipliers:
                target_rps = max(1.0, mult * sat_rps)
                interval = 1.0 / target_rps
                per = {
                    label: {"offered": 0, "completed": 0, "shed": 0,
                            "expired": 0, "lat": []}
                    for label, _ in prios
                }
                hints = []
                pending = []
                t0 = time.perf_counter()
                i = 0
                while True:
                    now = time.perf_counter()
                    if now - t0 >= stage_s:
                        break
                    # paced submission: catch up to the offered-load
                    # schedule, then sleep to the next slot (open loop —
                    # the arrival process does not care who completed)
                    while i * interval <= now - t0:
                        label, prio = prios[i % len(prios)]
                        n = REQ_SIZES[i % len(REQ_SIZES)]
                        per[label]["offered"] += 1
                        try:
                            req = server.submit(
                                make_rows(n, rng), timeout_ms=deadline_ms,
                                priority=prio)
                            pending.append((label, time.perf_counter(), req))
                        except serving.ServerOverloaded as e:
                            per[label]["shed"] += 1
                            if e.retry_after_ms is not None:
                                hints.append(e.retry_after_ms)
                        except serving.DeadlineExceeded:
                            per[label]["expired"] += 1
                        i += 1
                    time.sleep(min(interval, 0.002))
                elapsed_submit = time.perf_counter() - t0
                for label, t_sub, req in pending:
                    try:
                        req.result()
                        per[label]["completed"] += 1
                        # done_t is stamped at COMPLETION, so latency is
                        # honest even though this gather loop drains
                        # sequentially after the submission window
                        per[label]["lat"].append(
                            ((req.done_t or time.perf_counter()) - t_sub)
                            * 1e3)
                    except serving.ServerOverloaded as e:
                        per[label]["shed"] += 1  # evicted while queued
                        if e.retry_after_ms is not None:
                            hints.append(e.retry_after_ms)
                    except serving.ServingError:
                        per[label]["expired"] += 1
                completed = sum(p["completed"] for p in per.values())
                for label in per:
                    lat = sorted(per[label].pop("lat"))
                    per[label]["p99_ms"] = (
                        round(lat[int(0.99 * (len(lat) - 1))], 3)
                        if lat else None)
                stages["%gx" % mult] = {
                    "offered_rps": round(i / elapsed_submit, 1),
                    "goodput_rps": round(completed / elapsed_submit, 1),
                    "goodput_vs_saturation": round(
                        completed / elapsed_submit / sat_rps, 3)
                    if sat_rps else None,
                    "per_priority": per,
                    "retry_after_ms_p50": (
                        round(sorted(hints)[len(hints) // 2], 2)
                        if hints else None),
                    "admit_limit_end": server._batcher.queue.limit,
                    "brownout_level_end": server._brownout.level,
                }
            m = server.metrics()
            return {
                "saturation_rps": round(sat_rps, 1),
                "stages": stages,
                "shed_total": m["shed"],
                "expired_total": m["expired"],
                "admit_limit_final": m["admit_limit"],
            }
        finally:
            server.stop(drain=False)


def run_overload():
    """The ``--overload`` line: the degradation curve past saturation."""
    import jax

    import bench_common

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    endpoints = {"lenet": _bench_overload("lenet", _save_lenet)}
    # numeric, not lexicographic: "10x" must beat "5x" for the headline
    last = max(endpoints["lenet"]["stages"], key=lambda k: float(k[:-1]))
    return {
        "metric": "serving_overload_goodput",
        "unit": "fraction_of_saturation",
        "value": endpoints["lenet"]["stages"][last]["goodput_vs_saturation"],
        "endpoints": endpoints,
        "threads": THREADS,
        "max_batch_size": MAX_BATCH,
        "batch_timeout_ms": TIMEOUT_MS,
        "platform": jax.devices()[0].platform,
    }


def _wire_mode(argv=None):
    """``--wire loopback`` / $BENCH_SERVING_WIRE."""
    import sys

    argv = sys.argv[1:] if argv is None else argv
    for i, a in enumerate(argv):
        if a == "--wire" and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith("--wire="):
            return a.split("=", 1)[1]
    return os.environ.get("BENCH_SERVING_WIRE")


def _dump_flight_trace(recorder, path):
    """Write the slowest 1% of bench requests (by client-observed
    latency) with their full span trees — the /tracez document shape,
    pre-filtered to the tail."""
    recs = recorder.snapshot()
    recs.sort(key=lambda r: r.get("latency_ms", 0.0), reverse=True)
    n_keep = max(1, len(recs) // 100)
    with open(path, "w") as f:
        json.dump({
            "metric": "serving_flight_trace",
            "slowest_pct": 1,
            "total_requests": len(recs),
            "slow_ms": recorder.slow_ms,
            "requests": recs[:n_keep],
        }, f)
    return n_keep


def run():
    import jax

    from paddle_tpu import monitor, profiler

    import bench_common

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    trace = os.environ.get("BENCH_SERVING_TRACE")
    trace_out = _trace_out_path()
    recorder = None
    if trace_out:
        # slow_ms=0 retains EVERY request so the slowest 1% is an exact
        # post-hoc selection, not a guessed threshold
        recorder = monitor.flight_recorder(
            capacity=2 * THREADS * REQUESTS + 64, slow_ms=0.0)
    if trace:
        profiler.start_jsonl_trace(trace)
    try:
        endpoints = {
            "lenet": _bench_endpoint("lenet", _save_lenet),
            "deepfm": _bench_endpoint("deepfm", _save_deepfm),
        }
    finally:
        if trace:
            profiler.stop_jsonl_trace()
    result = {
        "metric": "serving_dynamic_batching",
        "unit": "rows/sec",
        "value": endpoints["lenet"]["rows_per_sec"],
        "endpoints": endpoints,
        "threads": THREADS,
        "requests_per_thread": REQUESTS,
        "max_batch_size": MAX_BATCH,
        "batch_timeout_ms": TIMEOUT_MS,
        "platform": jax.devices()[0].platform,
    }
    if recorder is not None:
        result["trace_out"] = trace_out
        result["trace_out_requests"] = _dump_flight_trace(recorder, trace_out)
        recorder.close()
    return result


# ---------------------------------------------------------------------------
# --sharded: a 2-way tp model-parallel group vs the replicated baseline
# ---------------------------------------------------------------------------
SHARDED_TP = int(os.environ.get("BENCH_SHARDED_TP", "2"))
_LM_V, _LM_D, _LM_L, _LM_H, _LM_DI, _LM_S = 512, 64, 2, 4, 128, 32


def _save_lm_bench(sharded: bool, precision=None):
    """Save-fn factory for the transformer-LM endpoint (the "giant
    model" stand-in): same weights both ways (seeded), with the
    canonical tp layout + mesh embedded in the manifest when
    ``sharded`` — the predictor then loads as ONE model-parallel group
    spanning ``BENCH_SHARDED_TP`` devices of the virtual CPU mesh.
    ``precision`` composes a precision policy into the same export (the
    --precision sharded-bf16 leg rides this)."""
    def save_fn(dirname):
        import paddle_tpu as fluid
        from paddle_tpu import framework, models, sharding

        prog, startup = framework.Program(), framework.Program()
        prog.random_seed = startup.random_seed = 17
        with framework.program_guard(prog, startup):
            ids = fluid.layers.data("src_ids", [_LM_S], dtype="int64")
            _, logits = models.transformer_lm(
                ids, None, vocab_size=_LM_V, d_model=_LM_D,
                n_layer=_LM_L, n_head=_LM_H, d_inner=_LM_DI,
                seq_len=_LM_S, max_pos=2 * _LM_S)
        exe = fluid.Executor(fluid.CPUPlace())
        kw = {}
        if sharded:
            kw = dict(sharding_rules=sharding.transformer_lm_rules("tp"),
                      sharding_mesh={"tp": SHARDED_TP})
        if precision is not None:
            kw["precision_policy"] = precision
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            fluid.save_inference_model(
                dirname, ["src_ids"], [logits], exe, prog, **kw)

        def make_rows(n, rng):
            return {"src_ids": rng.randint(
                1, _LM_V, (n, _LM_S)).astype(np.int64)}

        return make_rows

    return save_fn


def run_sharded():
    """The ``--sharded`` line: the same transformer-LM endpoint served
    replicated (one chip's replica) vs as a 2-way tp model-parallel
    group on the 8-device CPU mesh — QPS and post-warmup recompile
    count for both, plus the per-device HBM footprint the sharding
    buys (sharded params hold 1/tp of their bytes per device)."""
    import sys

    import bench_common

    if "jax" not in sys.modules:
        # standalone invocation (`python bench_serving.py --sharded`):
        # the tp group needs the virtual multi-device CPU mesh, and the
        # env only takes effect before the first jax import (bench.py's
        # serving_sharded stage injects the same env into its
        # subprocess; this covers the direct path)
        os.environ.update(bench_common.virtual_mesh_env())
    import jax

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    replicated = _bench_endpoint("lm-replicated", _save_lm_bench(False))
    shard = _bench_endpoint("lm-tp%d" % SHARDED_TP, _save_lm_bench(True))
    stats = shard.get("sharding") or {}
    return {
        "metric": "serving_sharded_qps",
        "unit": "rows/sec",
        "value": shard["rows_per_sec"],
        "replicated_rows_per_sec": replicated["rows_per_sec"],
        "qps_vs_replicated": round(
            shard["rows_per_sec"] / max(1e-9, replicated["rows_per_sec"]),
            3),
        "tp": SHARDED_TP,
        "recompiles_after_warmup": shard["recompiles_after_warmup"],
        "hbm_bytes_per_device": stats.get("hbm_bytes_per_device"),
        "replicated_hbm_bytes": stats.get("replicated_bytes"),
        "params_sharded": stats.get("n_sharded"),
        "endpoints": {"replicated": replicated, "sharded": shard},
        "threads": THREADS,
        "requests_per_thread": REQUESTS,
        "max_batch_size": MAX_BATCH,
        "batch_timeout_ms": TIMEOUT_MS,
        "platform": jax.devices()[0].platform,
    }


# ---------------------------------------------------------------------------
# --long-context: sequence-parallel ring attention + pipelined predictor
# ---------------------------------------------------------------------------
_LC_S = int(os.environ.get("BENCH_LC_SEQ", "512"))
_LC_B = int(os.environ.get("BENCH_LC_BATCH", "4"))
_LC_REPS = int(os.environ.get("BENCH_LC_REPS", "6"))
_LC_BUDGET = int(os.environ.get("BENCH_LC_CHIP_BUDGET_BYTES",
                                str(16 << 20)))
_LC_DIMS = (512, 64, 2, 4, 128)  # V, D, L, H, DI


def _save_lc_lm(n_sp):
    """Save-fn factory for the LONG-CONTEXT fused-attention LM export
    (seq ``_LC_S``; causality is the fused op's attr, so no [S, S]
    bias tensor exists to blow the activation budget or block the
    pipeline cut).  ``n_sp > 1`` embeds the canonical ``sp`` layout +
    mesh in the manifest: the loaded predictor then constrains every
    [*, S, *] intermediate onto ``n_sp`` devices and dispatches
    attention as ring attention over the sp axis."""
    V, D, L, H, DI = _LC_DIMS

    def save_fn(dirname):
        import paddle_tpu as fluid
        from paddle_tpu import framework, models, sharding

        prog, startup = framework.Program(), framework.Program()
        prog.random_seed = startup.random_seed = 11
        with framework.program_guard(prog, startup):
            ids = fluid.layers.data("src_ids", [_LC_S], dtype="int64")
            _, logits = models.transformer_lm(
                ids, None, vocab_size=V, d_model=D, n_layer=L,
                n_head=H, d_inner=DI, seq_len=_LC_S, max_pos=2 * _LC_S,
                fused_attention=True)
        exe = fluid.Executor(fluid.CPUPlace())
        kw = {}
        if n_sp > 1:
            kw = dict(sharding_rules=sharding.transformer_lm_rules("sp"),
                      sharding_mesh={"sp": n_sp})
        with fluid.scope_guard(fluid.Scope()):
            exe.run(startup)
            fluid.save_inference_model(
                dirname, ["src_ids"], [logits], exe, prog, **kw)

    return save_fn


def _lc_tokens_per_s(run_fn):
    """tokens/s over ``_LC_REPS`` steady dispatches of a [B, S] batch
    (one untimed dispatch first: compile + placement)."""
    run_fn()
    t0 = time.perf_counter()
    out = None
    for _ in range(_LC_REPS):
        out = run_fn()
    np.asarray(out[0])
    elapsed = time.perf_counter() - t0
    return round(_LC_REPS * _LC_B * _LC_S / elapsed, 1)


def run_long_context():
    """The ``--long-context`` line (see module docstring)."""
    import sys

    import bench_common

    if "jax" not in sys.modules:
        os.environ.update(bench_common.virtual_mesh_env())
    import jax

    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor
    from paddle_tpu.parallel.pipeline_predictor import PipelinePredictor

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    V = _LC_DIMS[0]
    rng = np.random.RandomState(42)
    x = rng.randint(1, V, (_LC_B, _LC_S)).astype(np.int64)
    x_small = x[:2]

    with tempfile.TemporaryDirectory() as tmp:
        legs = {}
        preds = {}
        for n_sp in (1, 2, 4):
            name = "unsharded" if n_sp == 1 else "sp%d" % n_sp
            d = os.path.join(tmp, name)
            _save_lc_lm(n_sp)(d)
            pred = create_paddle_predictor(AnalysisConfig(d))
            preds[name] = (pred, d)
            tps = _lc_tokens_per_s(lambda p=pred: p.run({"src_ids": x}))
            leg = {"tokens_per_s": tps}
            if pred.sharded:
                stats = pred.sharding_stats()
                leg["activation_bytes_per_device"] = (
                    stats["activation_bytes_per_device"])
                leg["activation_bytes_unsharded"] = (
                    stats["activation_bytes_unsharded"])
            legs[name] = leg

        # parity: the sp-4 ring-attention group must reproduce the
        # unsharded logits (the acceptance rtol)
        ref, _ = preds["unsharded"]
        sp4, _ = preds["sp4"]
        out_r, = ref.run({"src_ids": x_small})
        out_s, = sp4.run({"src_ids": x_small})
        np.testing.assert_allclose(out_s, out_r, rtol=2e-4, atol=2e-4)

        # capacity: the unsharded activation footprint exceeds the
        # per-chip budget; the sp-4 share is exactly 1/4 and fits it
        unsharded_act = legs["sp4"]["activation_bytes_unsharded"]
        sp4_act = legs["sp4"]["activation_bytes_per_device"]
        if unsharded_act <= _LC_BUDGET:
            raise AssertionError(
                "long-context leg is not long enough: unsharded "
                "activations %d <= budget %d (raise BENCH_LC_SEQ)"
                % (unsharded_act, _LC_BUDGET))
        if sp4_act * 4 != unsharded_act or sp4_act > _LC_BUDGET:
            raise AssertionError(
                "sp-4 activation share %d is not 1/4 of %d within the "
                "%d budget" % (sp4_act, unsharded_act, _LC_BUDGET))

        # zero-recompile across a mixed-length storm: warm the padded
        # sizes once each, then a shuffled storm must never miss again
        storm_sizes = sorted({_LC_B, max(1, _LC_B // 2), 1})
        feeds = {n: {"src_ids": x[:n]} for n in storm_sizes}
        for f in feeds.values():
            sp4.run(f)
        misses0 = sp4.jit_cache_stats()["misses"]
        order = [storm_sizes[i % len(storm_sizes)] for i in range(12)]
        rng.shuffle(order)
        for n in order:
            sp4.run(feeds[n])
        recompiles = sp4.jit_cache_stats()["misses"] - misses0
        if recompiles:
            raise AssertionError(
                "sp-4 predictor recompiled %d time(s) during the "
                "mixed-length storm" % recompiles)

        # pipeline: the SAME unsharded export served pp-2 micro-batched
        # (M=4) vs sequential (M=1, the structural 0.5-bubble worst
        # case) — outputs must be exact, executed bubble < 0.5
        _, udir = preds["unsharded"]
        out_ref, = ref.run({"src_ids": x})
        for label, m in (("pp2_m4", 4), ("pp2_m1", 1)):
            pipe = PipelinePredictor(udir, n_stages=2, num_microbatches=m)
            tps = _lc_tokens_per_s(
                lambda p=pipe: p.run({"src_ids": x}))
            out_p, = pipe.run({"src_ids": x})
            if np.abs(out_p - out_ref).max() != 0.0:
                raise AssertionError(
                    "pipelined (%s) output is not exact vs unpipelined"
                    % label)
            st = pipe.pipeline_stats()
            legs[label] = {
                "tokens_per_s": tps,
                "bubble_ratio": st["bubble_ratio"],
                "stage_occupancy": st["stage_occupancy"],
                "cut_vars": st["cut_vars"],
            }
        if not legs["pp2_m4"]["bubble_ratio"] < 0.5:
            raise AssertionError(
                "pp-2/M-4 bubble ratio %r is not < 0.5"
                % legs["pp2_m4"]["bubble_ratio"])

    return {
        "metric": "serving_long_context_tokens_per_s",
        "unit": "tokens/sec",
        "value": legs["sp4"]["tokens_per_s"],
        "seq_len": _LC_S,
        "batch": _LC_B,
        "chip_budget_bytes": _LC_BUDGET,
        "unsharded_activation_bytes": unsharded_act,
        "sp4_activation_bytes_per_device": sp4_act,
        "recompiles_after_warmup": 0,
        "pipeline_bubble_ratio": legs["pp2_m4"]["bubble_ratio"],
        "legs": legs,
        "platform": jax.devices()[0].platform,
    }


# ---------------------------------------------------------------------------
# --decode: continuous batching vs request-at-a-time on a transformer LM
# ---------------------------------------------------------------------------
def _decode_workload(rng, n, max_seq_len):
    """Interleaved long/short prompts (the mixed-length traffic that
    makes request-at-a-time batching waste freed slots): every 4th
    request decodes near the length cap, the rest are short."""
    reqs = []
    for i in range(n):
        if i % 4 == 0:
            plen, gen = 12, max_seq_len - 16
        else:
            plen, gen = 2 + i % 5, 4 + i % 6
        prompt = rng.randint(3, 400, plen).astype(np.int32)
        reqs.append((prompt, gen))
    return reqs


# target-LM dims shared by the decode legs (the tier-2 legs rebuild
# draft/verify fns and the fleet endpoint around the same weights)
_DEC_DIMS = (512, 64, 2, 4, 128, 64)  # V, D, L, H, DI, max_seq_len


def _decode_prefix_drill(srv, prefix, suffixes, gen=4):
    """The staggered shared-prefix drill: sequential requests (each
    waits its result, so the freed slot's prefix KV is offered before
    the next prompt probes).  Returns (prefill-token delta, sorted
    TTFT list) — the on/off comparison runs this twice."""
    d0 = int(srv.metrics()["decode"]["prefill_tokens"])
    ttfts = []
    for sfx in suffixes:
        prompt = np.concatenate([prefix, sfx]).astype(np.int32)
        r = srv.submit({"tokens": prompt}, max_new_tokens=gen)
        r.result(timeout=300.0)
        ttfts.append(r.first_token_t - r.submit_t)
        time.sleep(0.02)  # let the release tick offer the prefix KV
    ttfts.sort()
    return int(srv.metrics()["decode"]["prefill_tokens"]) - d0, ttfts


def _decode_spec_block(state, spec_prompts, spec_gen, refs, rollouts):
    """The speculative leg: distill a unigram transition-table draft
    from the baseline pass's OWN greedy rollouts (the cheapest draft
    that still tracks the target — ~70% of this LM's greedy transitions
    are last-token-predictable), then decode the same prompts with and
    without draft-then-verify on one server at ``steps_per_tick=1``,
    the dispatch-bound regime where a k-wide accepted run amortizes
    scheduler dispatches.  Greedy-exact acceptance pins parity: both
    passes must emit sequences bit-identical to ``refs``."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.decoding import (
        make_transformer_lm_pooled_step_fn,
        make_transformer_lm_pooled_verify_fn,
    )
    from paddle_tpu.serving.decode import DecodeServer
    from paddle_tpu.serving.speculative import SpeculativeConfig

    V, D, L, H, DI, ML = _DEC_DIMS
    k = int(os.environ.get("BENCH_DECODE_SPEC_K", "8"))
    counts = {}
    for seq in rollouts:
        for a, b in zip(seq[:-1].tolist(), seq[1:].tolist()):
            row = counts.setdefault(a, {})
            row[b] = row.get(b, 0) + 1
    table_np = np.zeros((V,), np.int32)
    for a, nxt in counts.items():
        table_np[a] = max(nxt.items(), key=lambda kv: kv[1])[0]
    table = jnp.asarray(table_np)

    def draft_step_fn(cache, tok, ts):
        # one table lookup as logits — argmax lands on table[tok]
        return jax.nn.one_hot(table[tok], V, dtype=jnp.float32) * 10.0, cache

    step_fn, make_cache = make_transformer_lm_pooled_step_fn(
        state, V, D, L, H, DI)
    verify_fn = make_transformer_lm_pooled_verify_fn(
        state, V, D, L, H, DI)
    spec = SpeculativeConfig(
        verify_fn, draft_step_fn,
        lambda s, t: {"bias": jnp.zeros((s, 1), jnp.float32)}, k=k)
    srv = DecodeServer(step_fn, make_cache, eos_id=1, max_seq_len=ML,
                       max_slots=4, steps_per_tick=1,
                       name="bench-decode-spec", speculative=spec)
    warm = srv.warmup()

    def one_pass(speculative):
        g0 = int(srv.metrics()["decode"]["generated_tokens"])
        t0 = time.perf_counter()
        outs = []
        for g in range(0, len(spec_prompts), 4):
            grp = [srv.submit({"tokens": p}, max_new_tokens=spec_gen,
                              speculative=speculative)
                   for p in spec_prompts[g:g + 4]]
            outs.extend(np.asarray(r.result(timeout=300.0)[0])
                        for r in grp)
        elapsed = time.perf_counter() - t0
        toks = int(srv.metrics()["decode"]["generated_tokens"]) - g0
        return outs, toks / elapsed

    base_outs, base_tps = one_pass(False)
    spec_outs, spec_tps = one_pass(True)
    sm = srv.metrics()
    telemetry = dict(sm["decode"].get("speculative") or {})
    recompiles = int(sm.get("recompiles", 0))
    srv.stop(drain=True, timeout=60.0)
    for ref, b_out, s_out in zip(refs, base_outs, spec_outs):
        if not (np.array_equal(ref, b_out) and np.array_equal(ref, s_out)):
            raise AssertionError(
                "speculative decode broke greedy parity: ref=%r base=%r "
                "spec=%r" % (ref.tolist(), b_out.tolist(), s_out.tolist()))
    if recompiles:
        raise AssertionError(
            "speculative server recompiled after warmup: %d" % recompiles)
    telemetry.update(
        steps_per_tick=1,
        baseline_tokens_per_s=round(base_tps, 1),
        speculative_tokens_per_s=round(spec_tps, 1),
        speedup=round(spec_tps / max(1e-9, base_tps), 2),
        parity=True,
        warmup_compiles=int(warm),
        recompiles=recompiles)
    return telemetry


def _decode_affinity_fleet_block(state):
    """The cache-affinity leg: a REAL 2-child fleet hosting one saved
    decode endpoint with a per-child prefix KV cache, driven by
    returning "sessions" (prompts sharing a per-session head).  With
    prefix affinity ON the balancer re-routes a returning prefix hash
    to the child whose cache last served it (a bounded tie-break that
    never defeats load balancing); OFF, least-loaded routing scatters
    the sessions across children and the child-side caches miss.  Each
    phase uses DISJOINT session prefixes so both start cold."""
    from paddle_tpu.serving import wire
    from paddle_tpu.serving.decode import save_decode_endpoint

    V, D, L, H, DI, ML = _DEC_DIMS
    sessions = int(os.environ.get("BENCH_DECODE_AFFINITY_SESSIONS", "4"))
    rounds = int(os.environ.get("BENCH_DECODE_AFFINITY_ROUNDS", "3"))

    def drill(fb, bases):
        ttfts, toks = [], [0]
        lock = threading.Lock()

        def session(si):
            srng = np.random.RandomState(1000 + si)
            for r_i in range(rounds):
                sfx = srng.randint(3, 400, 2 + r_i).astype(np.int32)
                prompt = np.concatenate([bases[si], sfx])
                t0 = time.perf_counter()
                first, n = None, 0
                for c in fb.infer_stream({"tokens": prompt},
                                         max_new_tokens=4):
                    if first is None:
                        first = time.perf_counter() - t0
                    n += int(np.asarray(c).reshape(-1).size)
                with lock:
                    ttfts.append(first)
                    toks[0] += n
                time.sleep(0.05)  # freed slot offers its prefix KV

        t0 = time.perf_counter()
        threads = [threading.Thread(target=session, args=(i,))
                   for i in range(sessions)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - t0
        ttfts.sort()
        return {
            "tokens_per_s": round(toks[0] / elapsed, 1),
            "ttft_ms_p50": round(ttfts[len(ttfts) // 2] * 1e3, 2),
            "ttft_ms_p99": round(
                ttfts[min(len(ttfts) - 1,
                          int(len(ttfts) * 0.99))] * 1e3, 2),
            "requests": len(ttfts),
        }

    rng = np.random.RandomState(11)
    bases_off = [rng.randint(3, 400, 32).astype(np.int32)
                 for _ in range(sessions)]
    bases_on = [rng.randint(3, 400, 32).astype(np.int32)
                for _ in range(sessions)]
    with tempfile.TemporaryDirectory() as tmp:
        d = os.path.join(tmp, "lm-decode-affinity")
        save_decode_endpoint(
            d, state, vocab_size=V, d_model=D, n_layer=L, n_head=H,
            d_inner=DI, eos_id=1, max_seq_len=ML, max_slots=4,
            steps_per_tick=4, prefix_cache_bytes=16 << 20)
        fleet = wire.FleetBalancer.from_launch(
            d, 2, name="decode-affinity", prefix_affinity=True)
        try:
            warmup_compiles = fleet.warmup()

            def child_cache_stats():
                out = {}
                for be in fleet._backends:
                    h = be.transport.get_json("/healthz")
                    out[be.name] = dict(h.get("prefix_cache") or {})
                return out

            # OFF phase: a plain least-loaded balancer over the SAME
            # children (bare addresses — no relaunch, same warm caches)
            fb_off = wire.FleetBalancer(
                [(be.handle.host, be.handle.port)
                 for be in fleet._backends],
                name="decode-affinity-off", prefix_affinity=False)
            try:
                c0 = child_cache_stats()
                off = drill(fb_off, bases_off)
            finally:
                fb_off.stop()
            c1 = child_cache_stats()
            on = drill(fleet, bases_on)
            c2 = child_cache_stats()

            def hit_delta(a, b):
                return sum(int(b[n].get("hits", 0)) - int(a[n].get("hits", 0))
                           for n in b)

            off["child_prefix_hits"] = hit_delta(c0, c1)
            on["child_prefix_hits"] = hit_delta(c1, c2)
            on["affinity_hits"] = sum(
                s["affinity_hits"]
                for s in fleet.backend_stats().values())
            if on["affinity_hits"] <= 0:
                raise AssertionError(
                    "prefix-affinity fleet recorded no affinity hits")
            recompiles = {}
            for be in fleet._backends:
                st = be.transport.get_json("/statusz")
                recompiles[be.name] = int(st["jit_cache"]["misses"])
            if any(recompiles.values()):
                raise AssertionError(
                    "decode-affinity fleet recompiled after warmup: %r"
                    % recompiles)
            return {
                "children": 2,
                "sessions": sessions,
                "rounds": rounds,
                "affinity_on": on,
                "affinity_off": off,
                "warmup_compiles": int(warmup_compiles),
                "jit_misses_after_warmup": recompiles,
            }
        finally:
            fleet.stop(shutdown_backends=True)


def _decode_int8_kv_block(state, prompts, gen, max_slots, steps):
    """The int8 KV-slot leg: the SAME LM weights behind a fp32-KV and
    an int8-KV decode server — greedy token parity asserted exactly,
    tokens/s both ways, and concurrent sequences at a fixed HBM budget
    from the pool's own ``kv_rung_bytes`` accounting (the int8 rung
    must buy >= 1.8x, the acceptance floor; per-slot-per-head fp32
    scales cost 4/d_head extra so the exact ratio is
    (d_head + 4) / (4 * d_head))."""
    from paddle_tpu.decoding import make_transformer_lm_pooled_step_fn
    from paddle_tpu.serving.decode import DecodeServer

    V, D, L, H, DI, ML = _DEC_DIMS
    legs, tokens = {}, {}
    for dt in ("fp32", "int8"):
        step_fn, make_cache = make_transformer_lm_pooled_step_fn(
            state, V, D, L, H, DI, kv_dtype=dt)
        srv = DecodeServer(step_fn, make_cache, eos_id=1, max_seq_len=ML,
                           max_slots=max_slots, steps_per_tick=steps,
                           name="bench-decode-kv-" + dt, kv_dtype=dt)
        warm = srv.warmup()
        outs = []
        t0 = time.perf_counter()
        for g in range(0, len(prompts), max_slots):
            grp = [srv.submit({"tokens": p}, max_new_tokens=gen)
                   for p in prompts[g:g + max_slots]]
            outs.extend(np.asarray(r.result(timeout=300.0)[0])
                        for r in grp)
        elapsed = time.perf_counter() - t0
        m = srv.metrics()
        generated = int(m["decode"]["generated_tokens"])
        recompiles = int(m.get("recompiles", 0))
        pool = srv._pool
        rungs = pool.rung_pairs()
        rung_bytes = {r: pool.kv_rung_bytes(*r) for r in rungs}
        srv.stop(drain=True, timeout=60.0)
        if recompiles:
            raise AssertionError(
                "%s-KV decode server recompiled after warmup: %d"
                % (dt, recompiles))
        tokens[dt] = outs
        legs[dt] = {
            "tokens_per_s": round(generated / elapsed, 1),
            "kv_bytes_top_rung": int(rung_bytes[rungs[-1]]),
            "warmup_compiles": int(warm),
            "recompiles": recompiles,
            "_rung_bytes": rung_bytes,
        }
    for a, b in zip(tokens["fp32"], tokens["int8"]):
        if not np.array_equal(a, b):
            raise AssertionError(
                "int8-KV greedy tokens diverged from fp32-KV: %r vs %r"
                % (a.tolist(), b.tolist()))
    # fixed HBM budget: at every (slots, len) rung pair, how many
    # concurrent sequences does a budget sized for 4 fp32 rungs buy?
    worst = None
    rb32 = legs["fp32"].pop("_rung_bytes")
    rb8 = legs["int8"].pop("_rung_bytes")
    for (s, t), b32 in rb32.items():
        budget = 4 * b32
        seq32 = (budget // b32) * s
        seq8 = (budget // rb8[(s, t)]) * s
        ratio = seq8 / max(1, seq32)
        if worst is None or ratio < worst[0]:
            worst = (ratio, s, t, seq32, seq8)
    if worst[0] < 1.8:
        raise AssertionError(
            "int8 KV bought only %.2fx concurrent sequences at rung "
            "(%d, %d) — the acceptance floor is 1.8x" % worst[:3])
    return {
        "concurrent_sequences_vs_fp32": round(worst[0], 2),
        "worst_rung": [worst[1], worst[2]],
        "sequences_at_budget_fp32": int(worst[3]),
        "sequences_at_budget_int8": int(worst[4]),
        "kv_bytes_vs_fp32": round(
            legs["int8"]["kv_bytes_top_rung"]
            / legs["fp32"]["kv_bytes_top_rung"], 4),
        "token_parity_exact": True,
        "requests": len(prompts),
        "max_new_tokens": gen,
        "fp32": legs["fp32"],
        "int8": legs["int8"],
    }


def run_decode():
    """The ``--decode`` line: token-level scheduling, measured."""
    import jax

    import bench_common
    from paddle_tpu.decoding import (
        make_transformer_lm_pooled_step_fn,
        random_transformer_lm_state,
    )
    from paddle_tpu.serving.client import Client
    from paddle_tpu.serving.decode import DecodeServer

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    n_requests = int(os.environ.get("BENCH_DECODE_REQUESTS", "24"))
    max_slots = int(os.environ.get("BENCH_DECODE_SLOTS", "8"))
    steps = int(os.environ.get("BENCH_DECODE_STEPS", "4"))
    V, D, L, H, DI, ML = _DEC_DIMS
    rng = np.random.RandomState(0)
    state = random_transformer_lm_state(rng, V, D, L, H, DI, ML)
    step_fn, make_cache = make_transformer_lm_pooled_step_fn(
        state, V, D, L, H, DI)
    srv = DecodeServer(step_fn, make_cache, eos_id=1, max_seq_len=ML,
                       max_slots=max_slots, steps_per_tick=steps,
                       name="bench-decode")
    t0 = time.perf_counter()
    compiles = srv.warmup()
    warmup_s = time.perf_counter() - t0
    work = _decode_workload(rng, n_requests, ML)

    def gen_tokens():
        return int(srv.metrics()["decode"]["generated_tokens"])

    # request-at-a-time: admit in arrival-order groups of max_slots,
    # wait the WHOLE group before the next
    g0, t0 = gen_tokens(), time.perf_counter()
    for g in range(0, len(work), max_slots):
        group = [srv.submit({"tokens": p}, max_new_tokens=c)
                 for p, c in work[g:g + max_slots]]
        for r in group:
            r.result(timeout=300.0)
    rat_s = time.perf_counter() - t0
    rat_tokens = gen_tokens() - g0

    # continuous: streamed clients, all submitted up front; TTFT is
    # first-chunk arrival as the CLIENT sees it
    cli = Client(srv)
    ttfts = []
    lock = threading.Lock()

    def stream_one(prompt, cap):
        t_submit = time.perf_counter()
        first = None
        for _ in cli.infer_stream({"tokens": prompt}, max_new_tokens=cap):
            if first is None:
                first = time.perf_counter() - t_submit
        with lock:
            ttfts.append(first)

    g0, t0 = gen_tokens(), time.perf_counter()
    threads = [threading.Thread(target=stream_one, args=(p, c))
               for p, c in work]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    cont_s = time.perf_counter() - t0
    cont_tokens = gen_tokens() - g0

    # the late-arrival drill: fill the pool with STAGGERED long decodes
    # (the shortest frees its slot while the longest still runs), submit
    # one short request mid-flight, compare scheduler timestamps
    longs = [srv.submit({"tokens": work[0][0]},
                        max_new_tokens=min(ML - 16, 16 + 4 * i))
             for i in range(max_slots)]
    while srv.metrics()["decode"]["slot_occupancy"] == 0.0:
        time.sleep(0.001)
    late = srv.submit({"tokens": np.array([5, 6], np.int32)},
                      max_new_tokens=4)
    late.result(timeout=300.0)
    for r in longs:
        r.result(timeout=300.0)
    late_before_batch = late.first_token_t < max(r.done_t for r in longs)
    late_ttft_ms = (late.first_token_t - late.submit_t) * 1e3

    m = srv.metrics()
    d = m["decode"]

    # --- decode tier 2: prefix-cache OFF leg + the speculative leg's
    # baseline rollouts, both on the (cache-less) main server ---------
    rng2 = np.random.RandomState(7)
    n_prefix = int(os.environ.get("BENCH_DECODE_PREFIX_REQUESTS", "10"))
    shared = rng2.randint(3, 400, 48).astype(np.int32)
    suffixes = [rng2.randint(3, 400, 2 + i % 4).astype(np.int32)
                for i in range(n_prefix)]
    off_prefill, off_ttfts = _decode_prefix_drill(srv, shared, suffixes)

    spec_n = int(os.environ.get("BENCH_DECODE_SPEC_REQUESTS", "8"))
    spec_gen = int(os.environ.get("BENCH_DECODE_SPEC_GEN", "24"))
    spec_prompts = [rng2.randint(3, 400, 4 + i % 5).astype(np.int32)
                    for i in range(spec_n)]
    refs, rollouts = [], []
    for g in range(0, spec_n, max_slots):
        grp = [srv.submit({"tokens": p}, max_new_tokens=spec_gen)
               for p in spec_prompts[g:g + max_slots]]
        for p, r in zip(spec_prompts[g:g + max_slots], grp):
            out = np.asarray(r.result(timeout=300.0)[0])
            refs.append(out)
            rollouts.append(np.concatenate([p, out]))
    recompiles = int(srv.metrics().get("recompiles", 0))
    srv.stop(drain=True, timeout=60.0)

    # prefix-cache ON leg: the same staggered drill against a server
    # whose freed slots offer their prefix KV for shared-prefix admits
    psrv = DecodeServer(step_fn, make_cache, eos_id=1, max_seq_len=ML,
                        max_slots=max_slots, steps_per_tick=steps,
                        name="bench-decode-prefix",
                        prefix_cache=32 << 20)
    prefix_warm = psrv.warmup()
    on_prefill, on_ttfts = _decode_prefix_drill(psrv, shared, suffixes)
    pm = psrv.metrics()
    prefix_stats = dict(pm["decode"].get("prefix_cache") or {})
    prefix_recompiles = int(pm.get("recompiles", 0))
    psrv.stop(drain=True, timeout=60.0)
    prefill_cut = 1.0 - on_prefill / max(1, off_prefill)
    if prefill_cut < 0.5:
        raise AssertionError(
            "shared-prefix cache cut prefill tokens by only %.0f%% "
            "(off=%d on=%d) — the acceptance bar is >= 50%%"
            % (prefill_cut * 100, off_prefill, on_prefill))
    if prefix_recompiles:
        raise AssertionError(
            "prefix-cache server recompiled after warmup: %d"
            % prefix_recompiles)
    prefix_block = {
        "requests": n_prefix,
        "prefill_tokens_off": off_prefill,
        "prefill_tokens_on": on_prefill,
        "prefill_cut": round(prefill_cut, 3),
        "ttft_ms_p50_off": round(off_ttfts[len(off_ttfts) // 2] * 1e3, 2),
        "ttft_ms_p50_on": round(on_ttfts[len(on_ttfts) // 2] * 1e3, 2),
        "cache": prefix_stats,
        "warmup_compiles": int(prefix_warm),
        "recompiles": prefix_recompiles,
    }

    spec_block = _decode_spec_block(
        state, spec_prompts, spec_gen, refs, rollouts)
    affinity_block = _decode_affinity_fleet_block(state)
    int8_n = int(os.environ.get("BENCH_DECODE_INT8_REQUESTS", "6"))
    int8_gen = int(os.environ.get("BENCH_DECODE_INT8_GEN", "16"))
    int8_prompts = [rng2.randint(3, 400, 3 + i % 4).astype(np.int32)
                    for i in range(int8_n)]
    int8_block = _decode_int8_kv_block(
        state, int8_prompts, int8_gen, max_slots, steps)
    ttfts.sort()
    cont_tps = cont_tokens / cont_s
    rat_tps = rat_tokens / rat_s
    return {
        "metric": "serving_decode_tokens_per_s",
        "unit": "tokens/s",
        "value": round(cont_tps, 1),
        "request_at_a_time_tokens_per_s": round(rat_tps, 1),
        "continuous_speedup": round(cont_tps / rat_tps, 2),
        "ttft_ms_p50": round(ttfts[len(ttfts) // 2] * 1e3, 2),
        "ttft_ms_p99": round(
            ttfts[min(len(ttfts) - 1, int(len(ttfts) * 0.99))] * 1e3, 2),
        "late_arrival_ttft_ms": round(late_ttft_ms, 2),
        "late_arrival_before_batch_done": bool(late_before_batch),
        "prefill_decode_ratio": round(
            d["prefill_tokens"] / max(1, d["generated_tokens"]), 3),
        "ticks": d["ticks"],
        "steps_per_tick": steps,
        "max_slots": max_slots,
        "requests": n_requests,
        "warmup_compiles": compiles,
        "warmup_s": round(warmup_s, 1),
        "recompiles": recompiles,
        "prefix_cache": prefix_block,
        "speculative": spec_block,
        "affinity": affinity_block,
        "int8_kv": int8_block,
        "platform": jax.devices()[0].platform,
    }


# ---------------------------------------------------------------------------
# --precision: bf16 serving vs fp32 on the same endpoints, plus a real
# 2-child wire fleet serving the mixed-precision manifest
# ---------------------------------------------------------------------------
def _parity_check(name, save_fn):
    """Load the bf16-policy endpoint once and compare its default
    (bf16) output against its own fp32 opt-out on a seeded feed — the
    runtime confirmation of the bound the export parity gate measured
    (both numbers ride the JSON line)."""
    from paddle_tpu.inference import AnalysisConfig, create_paddle_predictor

    with tempfile.TemporaryDirectory() as tmp:
        d = os.path.join(tmp, name)
        make_rows = save_fn(d, precision={"dtype": "bf16"})
        pred = create_paddle_predictor(AnalysisConfig(d))
        policy = pred.precision_policy
        rng = np.random.RandomState(42)
        feed = make_rows(4, rng)
        out_low = pred.run(feed)
        out_fp32 = pred.run(feed, precision="fp32")
        from paddle_tpu.contrib.mixed_precision.inference import max_rel_err

        worst = max_rel_err(out_fp32, out_low)
        if worst > policy["rtol"]:
            raise AssertionError(
                "endpoint %r bf16 parity %.4g exceeds exported rtol %.4g"
                % (name, worst, policy["rtol"]))
        return {
            "rtol": policy["rtol"],
            "export_max_rel_err": policy["max_rel_err"],
            "runtime_max_rel_err": round(worst, 6),
        }


def _precision_fleet_block(save_fn, requests=48):
    """The acceptance leg: a REAL 2-child wire fleet serving one
    mixed-precision (bf16-manifest) endpoint dir.  Every child
    reconstructs the variant from the manifest, the fleet-wide warmup
    compiles both ladders in both processes, a mixed bf16/fp32-opt-out
    storm runs through the balancer, and each child's /statusz is the
    recompile ground truth (must be 0)."""
    from paddle_tpu.serving import wire

    with tempfile.TemporaryDirectory() as tmp:
        d = os.path.join(tmp, "lenet-prec-fleet")
        make_rows = save_fn(d, precision={"dtype": "bf16"})
        fleet = wire.FleetBalancer.from_launch(
            d, 2, name="prec-fleet",
            launch_kwargs={"max_batch_size": MAX_BATCH,
                           "batch_timeout_ms": TIMEOUT_MS})
        try:
            t0 = time.perf_counter()
            warmup_compiles = fleet.warmup()
            warmup_s = time.perf_counter() - t0
            health = fleet._backends[0].transport.get_json("/healthz")
            rng = np.random.RandomState(9)
            lat = []
            for i in range(requests):
                n = REQ_SIZES[i % len(REQ_SIZES)]
                kw = {"precision": "fp32"} if i % 4 == 0 else {}
                r0 = time.perf_counter()
                fleet.infer(make_rows(n, rng), **kw)
                lat.append(time.perf_counter() - r0)
            recompiles = {}
            for be in fleet._backends:
                status = be.transport.get_json("/statusz")
                recompiles[be.name] = int(status["metrics"]["recompiles"])
            if any(recompiles.values()):
                raise AssertionError(
                    "mixed-precision fleet recompiled after warmup: %r"
                    % recompiles)
            lat.sort()
            return {
                "children": 2,
                "endpoint_precision": health.get("precision"),
                "precision_dtypes": health.get("precision_dtypes"),
                "completed": len(lat),
                "latency_p50_ms": round(lat[len(lat) // 2] * 1e3, 3),
                "warmup_compiles": int(warmup_compiles),
                "warmup_s": round(warmup_s, 2),
                "recompiles_after_warmup": recompiles,
            }
        finally:
            fleet.stop(shutdown_backends=True)


def _precision_sharded_block():
    """The composed precision × sharding leg (the tentpole's
    acceptance number): the transformer-LM endpoint exported
    sharded-fp32 vs exported with BOTH the tp layout and a bf16
    precision policy in one manifest.  QPS both ways plus the
    dtype-aware ``hbm_bytes_per_device`` from ``sharding_stats()`` —
    the composed endpoint must rent strictly fewer per-device bytes
    (the hoisted params live bf16 at shard shape; embedding lookups
    stay fp32, so the saving is the cast set's half-width, not exactly
    half the total).  Both endpoints enforce the zero-recompile
    contract inside ``_bench_endpoint``."""
    f32 = _bench_endpoint("lm-tp%d-fp32" % SHARDED_TP,
                          _save_lm_bench(True))
    bf16 = _bench_endpoint(
        "lm-tp%d-bf16" % SHARDED_TP,
        _save_lm_bench(True, precision={"dtype": "bf16"}))
    hbm_f32 = (f32.get("sharding") or {}).get("hbm_bytes_per_device")
    hbm_bf16 = (bf16.get("sharding") or {}).get("hbm_bytes_per_device")
    if not hbm_f32 or not hbm_bf16 or hbm_bf16 >= hbm_f32:
        raise AssertionError(
            "composed sharded-bf16 endpoint did not cut per-device HBM: "
            "fp32=%r bf16=%r" % (hbm_f32, hbm_bf16))
    return {
        "tp": SHARDED_TP,
        "qps_vs_sharded_fp32": round(
            bf16["rows_per_sec"] / max(1e-9, f32["rows_per_sec"]), 3),
        "hbm_bytes_per_device_fp32": int(hbm_f32),
        "hbm_bytes_per_device_bf16": int(hbm_bf16),
        "hbm_bytes_vs_fp32": round(hbm_bf16 / hbm_f32, 4),
        "endpoints": {"sharded_fp32": f32, "sharded_bf16": bf16},
    }


def run_precision():
    """The ``--precision`` line: the same endpoints served fp32 vs
    under a bf16 precision policy — QPS and p99 both ways, parity
    within the exported rtol bound, 0 recompiles after warmup
    (bf16-default AND fp32-opt-out requests), the 2-child wire fleet
    leg serving the mixed-precision manifest, and the sharded-bf16
    composed leg (the tp transformer-LM endpoint fp32 vs with a bf16
    policy in the same manifest: QPS + dtype-aware per-device HBM)."""
    import functools
    import sys

    import bench_common

    if "jax" not in sys.modules:
        # standalone invocation (`python bench_serving.py --precision`):
        # the sharded-bf16 composed leg loads a tp group and needs the
        # virtual multi-device CPU mesh (env only effective before the
        # first jax import; bench.py's serving_precision stage injects
        # the same env into its subprocess)
        os.environ.update(bench_common.virtual_mesh_env())
    import jax

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    endpoints = {}
    for name, save_fn in (("lenet", _save_lenet), ("deepfm", _save_deepfm)):
        fp32 = _bench_endpoint(name + "-fp32", save_fn)
        bf16 = _bench_endpoint(
            name + "-bf16",
            functools.partial(save_fn, precision={"dtype": "bf16"}))
        endpoints[name] = {
            "fp32": fp32,
            "bf16": bf16,
            "qps_vs_fp32": round(
                bf16["requests_per_sec"]
                / max(1e-9, fp32["requests_per_sec"]), 3),
            "p99_vs_fp32": (
                round(bf16["latency_p99_ms"] / fp32["latency_p99_ms"], 3)
                if fp32["latency_p99_ms"] else None),
            "parity": _parity_check(name, save_fn),
        }
    fleet = _precision_fleet_block(_save_lenet)
    sharded_bf16 = _precision_sharded_block()
    return {
        "metric": "serving_precision_qps_vs_fp32",
        "unit": "ratio",
        "value": endpoints["lenet"]["qps_vs_fp32"],
        "endpoints": endpoints,
        "fleet": fleet,
        "sharded_bf16": sharded_bf16,
        "threads": THREADS,
        "requests_per_thread": REQUESTS,
        "max_batch_size": MAX_BATCH,
        "batch_timeout_ms": TIMEOUT_MS,
        "platform": jax.devices()[0].platform,
    }


def _fleet_obs_storm(fleet, make_rows, threads, requests,
                     stagger_s=0.02, seed=300):
    """Staggered-arrival open storm through the balancer: every thread
    starts ``stagger_s`` after its predecessor (an arrival ramp, not a
    thundering herd), mixed request sizes.  Returns the client-observed
    throughput/latency block."""
    from paddle_tpu import serving

    lats = [[] for _ in range(threads)]
    shed = [0] * threads
    start = threading.Barrier(threads + 1)

    def storm(tid):
        rng = np.random.RandomState(seed + tid)
        start.wait()
        time.sleep(stagger_s * tid)
        for i in range(requests):
            n = REQ_SIZES[(tid + i) % len(REQ_SIZES)]
            feed = make_rows(n, rng)
            r0 = time.perf_counter()
            try:
                fleet.infer(feed, timeout_ms=30000)
                lats[tid].append(time.perf_counter() - r0)
            except serving.ServerOverloaded:
                shed[tid] += 1

    workers = [threading.Thread(target=storm, args=(t,))
               for t in range(threads)]
    for t in workers:
        t.start()
    start.wait()
    t0 = time.perf_counter()
    for t in workers:
        t.join()
    elapsed = time.perf_counter() - t0
    all_lats = np.asarray(
        [v for per in lats for v in per], dtype=np.float64)
    return {
        "requests_per_sec": round(all_lats.size / elapsed, 1),
        "latency_p50_ms": round(
            float(np.percentile(all_lats, 50)) * 1e3, 3),
        "latency_p99_ms": round(
            float(np.percentile(all_lats, 99)) * 1e3, 3),
        "completed": int(all_lats.size),
        "shed": int(sum(shed)),
        "elapsed_s": round(elapsed, 2),
    }


def _fleet_obs_federation_check(fleet, admin):
    """The exact-sum federation contract, checked while the fleet is
    idle: every child ``serving_*`` counter series must appear in the
    federated ``/metrics`` verbatim under that child's ``backend=``
    label, and ``/statusz``'s fleet aggregate must equal the children's
    sum exactly."""
    from paddle_tpu.monitor import registry as _registry

    # direct child expositions first, then a forced scrape: with no
    # traffic in flight the serving_* counters cannot move in between,
    # so the cached docs the federated views serve match these exactly
    children = {}
    for be in fleet._backends:
        children[be.name] = _registry.parse_exposition(
            be.transport.get_text("/metrics"))
    fleet.scrape_once()
    fed = _registry.parse_exposition(admin.get_text("/metrics"))
    statusz = admin.get_json("/statusz")

    fed_index = {}
    for fam_name, fam in fed.items():
        if fam["type"] != "counter":
            continue
        for name, labels, value in fam["samples"]:
            fed_index[(name, tuple(sorted(labels.items())))] = value

    series_checked = 0
    families = set()
    sums = {}
    for backend, fams in children.items():
        for fam_name, fam in fams.items():
            if fam["type"] != "counter" or not fam_name.startswith(
                    "serving_"):
                continue
            for name, labels, value in fam["samples"]:
                want = dict(labels)
                want["backend"] = backend
                key = (name, tuple(sorted(want.items())))
                got = fed_index.get(key)
                if got != value:
                    raise AssertionError(
                        "federated /metrics mismatch for %s%r: child %s "
                        "has %r, federation has %r"
                        % (name, labels, backend, value, got))
                series_checked += 1
                families.add(fam_name)
                sums[fam_name] = sums.get(fam_name, 0.0) + value
    if series_checked == 0:
        raise AssertionError("no child serving_* counter series federated")

    agg = (statusz.get("aggregate") or {}).get("counters") or {}
    for fam_name, want in sums.items():
        got = agg.get(fam_name)
        if got != want:
            raise AssertionError(
                "federated /statusz aggregate mismatch for %s: children "
                "sum to %r, aggregate says %r" % (fam_name, want, got))

    backends_seen = {
        labels.get("backend")
        for fam in fed.values()
        for _, labels, _ in fam["samples"]}
    missing = {be.name for be in fleet._backends} - backends_seen
    if missing:
        raise AssertionError(
            "federated /metrics missing backend label(s): %r" % missing)
    return {
        "counter_families_checked": len(families),
        "series_checked": series_checked,
        "aggregate_families_checked": len(sums),
        "backends": sorted(be.name for be in fleet._backends),
    }


def _fleet_obs_slo_drill(fleet, admin, make_rows, slo_name, delay_s):
    """The injected-latency fire/clear drill: arm a delay fault on the
    balancer's own dispatch so every routed request blows the latency
    SLO's threshold, poll ``/sloz`` until the fast-burn pair fires,
    disarm, drive clean traffic until it clears, and verify both
    transitions landed in ``/eventz``."""
    from paddle_tpu import faults

    def fast_alert(doc):
        for obj in doc.get("objectives") or ():
            if obj.get("name") != slo_name:
                continue
            for a in obj.get("alerts") or ():
                if a.get("pair") == "fast":
                    return a, obj
        return None, None

    # continuous injectors keep the SCALED short window populated: a
    # serial one-at-a-time loop leaves sub-second gaps with no
    # completions at all, and an empty window reads as burn 0
    stop = threading.Event()

    def injector(seed):
        rng_l = np.random.RandomState(seed)
        while not stop.is_set():
            try:
                fleet.infer(make_rows(1, rng_l), timeout_ms=60000)
            except Exception:
                pass  # the drill only needs completions, not answers

    injectors = [threading.Thread(target=injector, args=(900 + i,))
                 for i in range(4)]
    fired_doc = None
    cleared = False
    fired_after_s = cleared_after_s = None
    try:
        t0 = time.perf_counter()
        with faults.armed("fleet.dispatch=delay:%g" % delay_s):
            for t in injectors:
                t.start()
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                doc = admin.get_json("/sloz")
                alert, obj = fast_alert(doc)
                if alert is not None and alert.get("firing"):
                    fired_doc = {
                        "alert": alert,
                        "burn_5m": (obj["windows"].get("5m")
                                    or {}).get("burn"),
                        "burn_1h": (obj["windows"].get("1h")
                                    or {}).get("burn"),
                    }
                    break
                time.sleep(0.05)
            fired_after_s = time.perf_counter() - t0
        if fired_doc is None:
            raise AssertionError(
                "fast-burn SLO alert never fired in /sloz under an "
                "injected %gs dispatch delay" % delay_s)

        # fault disarmed, injectors still running: clean completions
        # drain the short window and the alert must clear
        t0 = time.perf_counter()
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            alert, _ = fast_alert(admin.get_json("/sloz"))
            if alert is not None and not alert.get("firing"):
                cleared = True
                break
            time.sleep(0.05)
        cleared_after_s = time.perf_counter() - t0
    finally:
        stop.set()
        for t in injectors:
            t.join(timeout=90.0)
    if not cleared:
        raise AssertionError(
            "fast-burn SLO alert never cleared in /sloz after the "
            "injection window ended")

    events = (admin.get_json("/eventz").get("events") or ())
    transitions = {
        e["kind"]: e for e in events
        if e.get("kind") in ("slo/fired", "slo/cleared")
        and e.get("slo") == slo_name and e.get("pair") == "fast"}
    if "slo/fired" not in transitions:
        raise AssertionError(
            "no fast-pair slo/fired event for %r in federated /eventz"
            % slo_name)
    if transitions["slo/fired"].get("severity") != "critical":
        raise AssertionError(
            "fast-pair slo/fired event is not critical: %r"
            % transitions["slo/fired"])
    if "slo/cleared" not in transitions:
        raise AssertionError(
            "no fast-pair slo/cleared event for %r in federated /eventz"
            % slo_name)
    return {
        "fired_after_s": round(fired_after_s, 2),
        "cleared_after_s": round(cleared_after_s, 2),
        "burn_5m_at_fire": fired_doc["burn_5m"],
        "burn_1h_at_fire": fired_doc["burn_1h"],
        "events": sorted(transitions),
    }


def run_fleet_obs():
    """The ``--fleet-obs`` line: the observability control tower on a
    real 2-child fleet — federation exactness, the SLO fire/clear
    drill, and the cost of watching (QPS with the tower on vs off)."""
    import jax

    import bench_common
    from paddle_tpu import monitor
    from paddle_tpu.monitor import slo as slo_mod
    from paddle_tpu.serving import wire

    bench_common.configure_compile_cache(bench_common.HOME_CACHE_DIR)
    qps_floor = float(os.environ.get("BENCH_OBS_QPS_FLOOR", "0.98"))
    delay_s = float(os.environ.get("BENCH_OBS_FAULT_DELAY_S", "0.6"))
    slo_name = "fleet-p99-latency"

    with tempfile.TemporaryDirectory() as tmp:
        d = os.path.join(tmp, "lenet-obs-fleet")
        make_rows = _save_lenet(d)
        fleet = wire.FleetBalancer.from_launch(
            d, 2, name="obs-fleet",
            launch_kwargs={"max_batch_size": MAX_BATCH,
                           "batch_timeout_ms": TIMEOUT_MS,
                           "queue_capacity": max(64, THREADS * 8)},
            health_interval_s=0.5, scrape_interval_s=0.5)
        engine = None
        try:
            t0 = time.perf_counter()
            warmup_compiles = fleet.warmup()
            warmup_s = time.perf_counter() - t0

            # rinse storm: sockets opened, ladders exercised, so the
            # off/on comparison below measures the tower, not warmup
            _fleet_obs_storm(fleet, make_rows, THREADS,
                             max(4, REQUESTS // 4), seed=100)

            # interleaved off/on pairs, capacity = best storm per mode:
            # identical-config storms on this shared host jitter ~3-10%
            # (one-sided — interference only ever slows a storm), so the
            # max over several interleaved runs is the capacity estimate,
            # and a below-floor ratio earns extra pairs before failing —
            # only a REPRODUCIBLE tower tax trips the assert
            min_pairs = int(os.environ.get("BENCH_OBS_MIN_PAIRS", "3"))
            max_pairs = int(os.environ.get("BENCH_OBS_MAX_PAIRS", "6"))

            def tower_up():
                addr = fleet.start_admin()
                slo_mod.install(
                    [slo_mod.latency(
                        slo_name,
                        histogram="serving_request_latency_seconds",
                        threshold_s=0.25, target=0.99,
                        server="obs-fleet")],
                    interval_s=0.1, window_scale=0.001)
                return wire.HttpTransport(*addr)

            def tower_down():
                slo_mod.uninstall()
                fleet._stop_admin()

            off_runs, on_runs = [], []
            admin = None
            pair = 0
            while True:
                pair += 1
                if admin is not None:
                    tower_down()
                off_runs.append(_fleet_obs_storm(
                    fleet, make_rows, THREADS, REQUESTS, seed=200 + pair))
                admin = tower_up()
                engine = slo_mod.get()
                on_runs.append(_fleet_obs_storm(
                    fleet, make_rows, THREADS, REQUESTS, seed=300 + pair))
                off = max(off_runs, key=lambda r: r["requests_per_sec"])
                on = max(on_runs, key=lambda r: r["requests_per_sec"])
                qps_ratio = round(
                    on["requests_per_sec"]
                    / max(1e-9, off["requests_per_sec"]), 3)
                if pair >= min_pairs and qps_ratio >= qps_floor:
                    break
                if pair >= max_pairs:
                    break

            federation = _fleet_obs_federation_check(fleet, admin)
            drill = _fleet_obs_slo_drill(
                fleet, admin, make_rows, slo_name, delay_s)

            recompiles = {}
            for be in fleet._backends:
                status = be.transport.get_json("/statusz")
                recompiles[be.name] = int(status["metrics"]["recompiles"])
            if any(recompiles.values()):
                raise AssertionError(
                    "observed fleet recompiled after warmup: %r"
                    % recompiles)
            if qps_ratio < qps_floor:
                raise AssertionError(
                    "observability tax too high: QPS with federation+SLO "
                    "on is %.3fx off (floor %.2f; off=%s on=%s)"
                    % (qps_ratio, qps_floor, off["requests_per_sec"],
                       on["requests_per_sec"]))

            burn = monitor.snapshot().get("slo_burn_rate") or {}
            return {
                "metric": "serving_fleet_obs_qps_ratio",
                "unit": "ratio",
                "value": qps_ratio,
                "children": 2,
                "off": off,
                "on": on,
                "qps_floor": qps_floor,
                "storm_pairs": pair,
                "federation": federation,
                "slo_drill": drill,
                "burn_gauge_series": len(burn.get("series", ())),
                "recompiles_after_warmup": recompiles,
                "warmup_compiles": int(warmup_compiles),
                "warmup_s": round(warmup_s, 2),
                "threads": THREADS,
                "requests_per_thread": REQUESTS,
                "max_batch_size": MAX_BATCH,
                "batch_timeout_ms": TIMEOUT_MS,
                "platform": jax.devices()[0].platform,
            }
        finally:
            if engine is not None:
                slo_mod.uninstall()
            fleet.stop(shutdown_backends=True)


def main():
    import bench_common

    # --metrics-out <path> (or $BENCH_METRICS_OUT) dumps the monitor
    # registry snapshot next to the JSON line
    import sys

    if "--fleet-obs" in sys.argv[1:] or os.environ.get(
            "BENCH_SERVING_FLEET_OBS"):
        bench_common.emit_result(run_fleet_obs())
        return
    if "--precision" in sys.argv[1:] or os.environ.get(
            "BENCH_SERVING_PRECISION"):
        bench_common.emit_result(run_precision())
        return
    if "--overload" in sys.argv[1:] or os.environ.get(
            "BENCH_SERVING_OVERLOAD"):
        bench_common.emit_result(run_overload())
        return
    if "--decode" in sys.argv[1:] or os.environ.get(
            "BENCH_SERVING_DECODE"):
        bench_common.emit_result(run_decode())
        return
    if "--sharded" in sys.argv[1:] or os.environ.get(
            "BENCH_SERVING_SHARDED"):
        bench_common.emit_result(run_sharded())
        return
    if "--long-context" in sys.argv[1:] or os.environ.get(
            "BENCH_SERVING_LONG_CONTEXT"):
        bench_common.emit_result(run_long_context())
        return
    mode = _wire_mode()
    if mode:
        if mode != "loopback":
            raise SystemExit("--wire supports only 'loopback' (got %r)" % mode)
        bench_common.emit_result(run_wire())
        return
    bench_common.emit_result(run())


if __name__ == "__main__":
    main()
