"""Shared int8 row quantization helpers (traced, jit-side only).

One quantization scheme serves every int8 surface in the tree — KV
cache rows (``decoding.make_transformer_lm_pooled_step_fn(kv_dtype=
"int8")``) and mesh-table embedding rows (``MeshTableRuntime(
row_dtype="int8")``): **symmetric per-row absmax** in the LLM.int8()
lineage.  A "row" is the last axis of the tensor; each row gets one
fp32 scale ``max|row| / 127`` and the row is stored as
``round(row / scale)`` clipped to ``[-127, 127]``.

Two properties the callers rely on:

* **the max element always lands exactly on ±127**, so
  ``quantize_rows(dequantize_rows(q, s))`` is the identity — a
  gather→dequant→requant→scatter update path writes back
  bit-identical (q, scale) for untouched rows, which is what makes
  the sparse push's collision-safe scatter deterministic;
* **zero rows stay zero** (the scale is floored, not the values), so
  freshly allocated cache/table storage round-trips as exact zeros.

Both helpers are pure ``jnp`` and MUST only be called inside jitted
functions (the step fn, the shard_map lookup/push bodies) — never on
the scheduler tick loop or any other host thread.  ``tools/
check_hot_path.py`` lists this file so any future host-side region
added here inherits the blocking-sync guard.
"""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["quantize_rows", "dequantize_rows", "INT8_SCALE_FLOOR"]

# scale floor: keeps all-zero rows representable (0 / floor == 0) and
# the dequant finite; any real row's absmax dominates it
INT8_SCALE_FLOOR = 1e-8


# hot-path: begin int8_quant (pure jnp ops traced into the step/verify
# executables and the mesh-table push kernels; a host sync here would
# land in every decode tick and sparse train step)
def quantize_rows(x):
    """Quantize ``x [..., row]`` to (int8 values, fp32 scales [...]).

    Symmetric per-row absmax: ``scale = max|row| / 127`` (floored at
    :data:`INT8_SCALE_FLOOR`), values ``round(row / scale)`` clipped to
    ``[-127, 127]``.  The row's max element maps to exactly ±127.
    """
    x = jnp.asarray(x, jnp.float32)  # hot-ok: jnp.asarray is a traced cast, not a host d2h
    scale = jnp.maximum(
        jnp.max(jnp.abs(x), axis=-1) / 127.0, INT8_SCALE_FLOOR)
    q = jnp.clip(jnp.round(x / scale[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def dequantize_rows(q, scale):
    """Inverse of :func:`quantize_rows`: fp32 ``q * scale`` with the
    scale broadcast back over the row axis."""
    return q.astype(jnp.float32) * scale[..., None]
# hot-path: end int8_quant
