// recordio: chunked, CRC-checked, optionally zlib-compressed record file.
//
// Reference: /root/reference/paddle/fluid/recordio/{header,chunk,writer,
// scanner}.cc — same design (records batched into chunks, each chunk
// framed by a header carrying record count, sizes and a CRC32 of the
// payload), re-implemented as a dependency-free C API consumed from
// Python via ctypes (paddle_tpu/native/__init__.py).
//
// Chunk layout (little-endian u32 fields):
//   MAGIC  FLAGS(0=raw,1=zlib)  N_RECORDS  RAW_LEN  STORED_LEN  CRC32
//   payload[STORED_LEN]      payload = concat{ u32 len, bytes } per record
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include <zlib.h>

namespace {

constexpr uint32_t kMagic = 0x7061646c;  // "padl"
constexpr uint32_t kFlagRaw = 0;
constexpr uint32_t kFlagZlib = 1;

struct Writer {
  FILE* f;
  std::vector<std::string> pending;
  size_t pending_bytes;
  size_t max_chunk_bytes;
  uint32_t flags;
};

struct Scanner {
  FILE* f;
  std::vector<std::string> records;  // current chunk, decoded
  size_t cursor;
  bool error;
};

bool write_u32(FILE* f, uint32_t v) { return fwrite(&v, 4, 1, f) == 1; }
bool read_u32(FILE* f, uint32_t* v) { return fread(v, 4, 1, f) == 1; }

bool flush_chunk(Writer* w) {
  if (w->pending.empty()) return true;
  std::string payload;
  payload.reserve(w->pending_bytes + 4 * w->pending.size());
  for (const auto& r : w->pending) {
    uint32_t len = static_cast<uint32_t>(r.size());
    payload.append(reinterpret_cast<const char*>(&len), 4);
    payload.append(r);
  }
  std::string stored;
  uint32_t flags = w->flags;
  if (flags == kFlagZlib) {
    uLongf bound = compressBound(payload.size());
    stored.resize(bound);
    if (compress2(reinterpret_cast<Bytef*>(&stored[0]), &bound,
                  reinterpret_cast<const Bytef*>(payload.data()), payload.size(),
                  Z_DEFAULT_COMPRESSION) != Z_OK) {
      return false;
    }
    stored.resize(bound);
  } else {
    stored = payload;
  }
  uint32_t crc = crc32(0L, reinterpret_cast<const Bytef*>(stored.data()), stored.size());
  if (!write_u32(w->f, kMagic) || !write_u32(w->f, flags) ||
      !write_u32(w->f, static_cast<uint32_t>(w->pending.size())) ||
      !write_u32(w->f, static_cast<uint32_t>(payload.size())) ||
      !write_u32(w->f, static_cast<uint32_t>(stored.size())) || !write_u32(w->f, crc)) {
    return false;
  }
  if (fwrite(stored.data(), 1, stored.size(), w->f) != stored.size()) return false;
  w->pending.clear();
  w->pending_bytes = 0;
  return true;
}

bool load_chunk(Scanner* s) {
  uint32_t magic, flags, n, raw_len, stored_len, crc;
  if (!read_u32(s->f, &magic)) return false;  // clean EOF
  if (magic != kMagic || !read_u32(s->f, &flags) || !read_u32(s->f, &n) ||
      !read_u32(s->f, &raw_len) || !read_u32(s->f, &stored_len) || !read_u32(s->f, &crc)) {
    s->error = true;
    return false;
  }
  std::string stored(stored_len, '\0');
  if (fread(&stored[0], 1, stored_len, s->f) != stored_len) {
    s->error = true;
    return false;
  }
  if (crc32(0L, reinterpret_cast<const Bytef*>(stored.data()), stored.size()) != crc) {
    s->error = true;
    return false;
  }
  std::string payload;
  if (flags == kFlagZlib) {
    payload.resize(raw_len);
    uLongf out_len = raw_len;
    if (uncompress(reinterpret_cast<Bytef*>(&payload[0]), &out_len,
                   reinterpret_cast<const Bytef*>(stored.data()), stored.size()) != Z_OK ||
        out_len != raw_len) {
      s->error = true;
      return false;
    }
  } else {
    payload = std::move(stored);
  }
  s->records.clear();
  s->records.reserve(n);
  size_t off = 0;
  for (uint32_t i = 0; i < n; ++i) {
    if (off + 4 > payload.size()) { s->error = true; return false; }
    uint32_t len;
    memcpy(&len, payload.data() + off, 4);
    off += 4;
    if (off + len > payload.size()) { s->error = true; return false; }
    s->records.emplace_back(payload.data() + off, len);
    off += len;
  }
  s->cursor = 0;
  return true;
}

}  // namespace

extern "C" {

void* recordio_writer_create(const char* path, int compress, int max_chunk_bytes) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  w->pending_bytes = 0;
  w->max_chunk_bytes = max_chunk_bytes > 0 ? static_cast<size_t>(max_chunk_bytes) : (1 << 20);
  w->flags = compress ? kFlagZlib : kFlagRaw;
  return w;
}

int recordio_writer_write(void* handle, const char* data, int len) {
  auto* w = static_cast<Writer*>(handle);
  w->pending.emplace_back(data, len);
  w->pending_bytes += len;
  if (w->pending_bytes >= w->max_chunk_bytes) {
    return flush_chunk(w) ? 0 : -1;
  }
  return 0;
}

int recordio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = flush_chunk(w) ? 0 : -1;
  fclose(w->f);
  delete w;
  return rc;
}

void* recordio_scanner_create(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* s = new Scanner();
  s->f = f;
  s->cursor = 0;
  s->error = false;
  return s;
}

// Returns pointer to record bytes valid until the next call; len in *len.
// nullptr + *len==0 on EOF; nullptr + *len==-1 on corruption.
const char* recordio_scanner_next(void* handle, int* len) {
  auto* s = static_cast<Scanner*>(handle);
  if (s->cursor >= s->records.size()) {
    if (!load_chunk(s)) {
      *len = s->error ? -1 : 0;
      return nullptr;
    }
  }
  const std::string& r = s->records[s->cursor++];
  *len = static_cast<int>(r.size());
  return r.data();
}

void recordio_scanner_close(void* handle) {
  auto* s = static_cast<Scanner*>(handle);
  fclose(s->f);
  delete s;
}

// ---------------------------------------------------------------------------
// MultiSlot text parser (reference: paddle/fluid/framework/data_feed.cc
// MultiSlotDataFeed::ParseOneInstance — per line, per slot:
//   <num><space><num values...>   repeated for each slot)
// Parses a whole text block into per-slot flattened values + per-line
// counts, avoiding the Python tokenize/float() hot loop for CTR data.
// ---------------------------------------------------------------------------
struct ParsedSlots {
  std::vector<std::vector<float>> values;   // per slot
  std::vector<std::vector<int32_t>> counts; // per slot, per line
};

void* multislot_parse(const char* text, long text_len, int n_slots, int* n_lines_out) {
  auto* p = new ParsedSlots();
  p->values.resize(n_slots);
  p->counts.resize(n_slots);
  const char* cur = text;
  const char* end = text + text_len;
  int n_lines = 0;
  std::vector<size_t> line_start_values(n_slots);
  std::vector<size_t> line_start_counts(n_slots);
  while (cur < end) {
    const char* line_end = static_cast<const char*>(memchr(cur, '\n', end - cur));
    if (!line_end) line_end = end;
    if (line_end > cur) {
      // snapshot per-slot sizes so a malformed line restores exactly the
      // state before it, regardless of how many values were pushed
      for (int slot = 0; slot < n_slots; ++slot) {
        line_start_values[slot] = p->values[slot].size();
        line_start_counts[slot] = p->counts[slot].size();
      }
      const char* q = cur;
      bool ok = true;
      // strtol/strtof skip leading whitespace INCLUDING newlines, so an
      // under-filled line would otherwise steal tokens from the next
      // line; bound every token to [q, line_end).
      auto skip_ws = [&](const char*& s) {
        while (s < line_end && (*s == ' ' || *s == '\t' || *s == '\r')) ++s;
        return s < line_end;
      };
      for (int slot = 0; slot < n_slots && ok; ++slot) {
        char* next = nullptr;
        if (!skip_ws(q)) { ok = false; break; }
        long n = strtol(q, &next, 10);
        if (next == q || next > line_end || n < 0) { ok = false; break; }
        q = next;
        p->counts[slot].push_back(static_cast<int32_t>(n));
        for (long i = 0; i < n; ++i) {
          if (!skip_ws(q)) { ok = false; break; }
          float v = strtof(q, &next);
          if (next == q || next > line_end) { ok = false; break; }
          q = next;
          p->values[slot].push_back(v);
        }
      }
      if (ok) {
        ++n_lines;
      } else {
        for (int slot = 0; slot < n_slots; ++slot) {
          p->values[slot].resize(line_start_values[slot]);
          p->counts[slot].resize(line_start_counts[slot]);
        }
      }
    }
    cur = line_end + 1;
  }
  *n_lines_out = n_lines;
  return p;
}

long multislot_slot_size(void* handle, int slot) {
  return static_cast<ParsedSlots*>(handle)->values[slot].size();
}

void multislot_copy_slot(void* handle, int slot, float* values_out, int32_t* counts_out) {
  auto* p = static_cast<ParsedSlots*>(handle);
  memcpy(values_out, p->values[slot].data(), p->values[slot].size() * sizeof(float));
  memcpy(counts_out, p->counts[slot].data(), p->counts[slot].size() * sizeof(int32_t));
}

void multislot_free(void* handle) { delete static_cast<ParsedSlots*>(handle); }

}  // extern "C"
