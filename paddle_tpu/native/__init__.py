"""Native (C++) runtime components, loaded via ctypes.

Reference native parts this covers: paddle/fluid/recordio/ (chunked CRC'd
record files) and the MultiSlot parsing hot path of
paddle/fluid/framework/data_feed.cc.  The library builds on first use
with g++ (cached under ``~/.cache/paddle_tpu``); when no toolchain is
available a pure-Python fallback keeps the API working.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import tempfile
from typing import Iterator, List, Optional, Tuple

import numpy as np

__all__ = ["RecordIOWriter", "RecordIOScanner", "parse_multislot", "native_available"]

_lib = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    src = os.path.join(os.path.dirname(__file__), "recordio.cc")
    cache = os.environ.get(
        "PADDLE_TPU_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
    )
    os.makedirs(cache, exist_ok=True)
    so_path = os.path.join(cache, "libpaddle_tpu_native.so")
    if not os.path.exists(so_path) or os.path.getmtime(so_path) < os.path.getmtime(src):
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", so_path, "-lz"],
                check=True,
                capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            sys.stderr.write("paddle_tpu.native: build failed (%s); using Python fallback\n" % e)
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.recordio_writer_create.restype = ctypes.c_void_p
    lib.recordio_writer_create.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.recordio_writer_write.restype = ctypes.c_int
    lib.recordio_writer_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.recordio_writer_close.restype = ctypes.c_int
    lib.recordio_writer_close.argtypes = [ctypes.c_void_p]
    lib.recordio_scanner_create.restype = ctypes.c_void_p
    lib.recordio_scanner_create.argtypes = [ctypes.c_char_p]
    lib.recordio_scanner_next.restype = ctypes.POINTER(ctypes.c_char)
    lib.recordio_scanner_next.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_int)]
    lib.recordio_scanner_close.argtypes = [ctypes.c_void_p]
    lib.multislot_parse.restype = ctypes.c_void_p
    lib.multislot_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_long, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
    ]
    lib.multislot_slot_size.restype = ctypes.c_long
    lib.multislot_slot_size.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.multislot_copy_slot.argtypes = [
        ctypes.c_void_p, ctypes.c_int,
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_int32),
    ]
    lib.multislot_free.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def native_available() -> bool:
    return _build_and_load() is not None


class RecordIOWriter:
    """reference: recordio/writer.cc."""

    def __init__(self, path: str, compress: bool = True, max_chunk_bytes: int = 1 << 20):
        self._lib = _build_and_load()
        self._path = path
        if self._lib is not None:
            self._h = self._lib.recordio_writer_create(
                path.encode(), int(compress), max_chunk_bytes
            )
            if not self._h:
                raise IOError("cannot open %s" % path)
        else:  # python fallback: naive framed file
            self._f = open(path, "wb")
            self._f.write(b"PYRIO\x00")

    def write(self, record: bytes) -> None:
        if self._lib is not None:
            rc = self._lib.recordio_writer_write(self._h, record, len(record))
            if rc != 0:
                raise IOError("recordio write failed")
        else:
            self._f.write(len(record).to_bytes(4, "little") + record)

    def close(self) -> None:
        if self._lib is not None:
            if self._lib.recordio_writer_close(self._h) != 0:
                raise IOError("recordio flush failed")
        else:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class RecordIOScanner:
    """reference: recordio/scanner.cc."""

    def __init__(self, path: str):
        self._lib = _build_and_load()
        self._path = path
        if self._lib is not None:
            self._h = self._lib.recordio_scanner_create(path.encode())
            if not self._h:
                raise IOError("cannot open %s" % path)
        else:
            self._f = open(path, "rb")
            magic = self._f.read(6)
            if magic != b"PYRIO\x00":
                raise IOError("bad recordio file (python-fallback format)")

    def __iter__(self) -> Iterator[bytes]:
        if self._lib is not None:
            n = ctypes.c_int(0)
            while True:
                ptr = self._lib.recordio_scanner_next(self._h, ctypes.byref(n))
                if not ptr:
                    if n.value == -1:
                        raise IOError("corrupt recordio chunk (CRC mismatch)")
                    return
                yield ctypes.string_at(ptr, n.value)
        else:
            while True:
                hdr = self._f.read(4)
                if len(hdr) < 4:
                    return
                ln = int.from_bytes(hdr, "little")
                yield self._f.read(ln)

    def close(self):
        if self._lib is not None:
            self._lib.recordio_scanner_close(self._h)
        else:
            self._f.close()


def parse_multislot(text: bytes, n_slots: int) -> Tuple[int, List[Tuple[np.ndarray, np.ndarray]]]:
    """Parse MultiSlot text (reference data_feed.cc format: per line, per
    slot ``<count> <v0> <v1> ...``).  Returns (n_lines, [(values, counts)]
    per slot)."""
    if isinstance(text, str):
        text = text.encode()
    lib = _build_and_load()
    if lib is not None:
        n_lines = ctypes.c_int(0)
        h = lib.multislot_parse(text, len(text), n_slots, ctypes.byref(n_lines))
        out = []
        try:
            for s in range(n_slots):
                nv = lib.multislot_slot_size(h, s)
                values = np.empty(nv, np.float32)
                counts = np.empty(n_lines.value, np.int32)
                if n_lines.value:
                    lib.multislot_copy_slot(
                        h, s,
                        values.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    )
                out.append((values, counts))
        finally:
            lib.multislot_free(h)
        return n_lines.value, out
    return _parse_multislot_py(text, n_slots)


def _parse_multislot_py(text: bytes, n_slots: int):
    """Pure-Python fallback; malformed lines are skipped whole (matching
    the native parser's per-line rollback)."""
    if isinstance(text, str):
        text = text.encode()
    values = [[] for _ in range(n_slots)]
    counts = [[] for _ in range(n_slots)]
    n_lines = 0
    for line in text.decode().splitlines():
        toks = line.split()
        if not toks:
            continue
        pos = 0
        row = []
        ok = True
        for s in range(n_slots):
            if pos >= len(toks):
                ok = False
                break
            try:
                n = int(toks[pos])
                pos += 1
                if n < 0:
                    ok = False
                    break
                vals = [float(t) for t in toks[pos : pos + n]]
            except ValueError:
                ok = False
                break
            if len(vals) != n:
                ok = False
                break
            pos += n
            row.append((n, vals))
        if not ok:
            continue
        n_lines += 1
        for s, (n, vals) in enumerate(row):
            counts[s].append(n)
            values[s].extend(vals)
    return n_lines, [
        (np.asarray(values[s], np.float32), np.asarray(counts[s], np.int32))
        for s in range(n_slots)
    ]


# ---------------------------------------------------------------------------
# Native (C++) inference predictor — the Python-free deployment path
# (reference: inference/api/api_impl.h NativePaddlePredictor + the
# train/demo pure-C++ story).  predictor.cc parses __model__ JSON + .npy
# weights itself; this wrapper only builds/loads the .so and marshals
# buffers, so the same library is usable from any C program.
# ---------------------------------------------------------------------------
_pred_lib = None
_pred_tried = False


def _predictor_lib():
    global _pred_lib, _pred_tried
    if _pred_tried:
        return _pred_lib
    _pred_tried = True
    src = os.path.join(os.path.dirname(__file__), "predictor.cc")
    cache = os.environ.get(
        "PADDLE_TPU_CACHE", os.path.join(os.path.expanduser("~"), ".cache", "paddle_tpu")
    )
    os.makedirs(cache, exist_ok=True)
    so_path = os.path.join(cache, "libpaddle_tpu_predictor.so")
    if not os.path.exists(so_path) or os.path.getmtime(so_path) < os.path.getmtime(src):
        try:
            subprocess.run(
                ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", so_path],
                check=True,
                capture_output=True,
            )
        except (OSError, subprocess.CalledProcessError) as e:
            sys.stderr.write(
                "paddle_tpu.native: predictor build failed (%s)\n" % e
            )
            return None
    try:
        lib = ctypes.CDLL(so_path)
    except OSError:
        return None
    lib.ptp_predictor_create.restype = ctypes.c_void_p
    lib.ptp_predictor_create.argtypes = [ctypes.c_char_p]
    lib.ptp_predictor_error.restype = ctypes.c_char_p
    lib.ptp_predictor_error.argtypes = [ctypes.c_void_p]
    lib.ptp_predictor_set_input.restype = ctypes.c_int
    lib.ptp_predictor_set_input.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ]
    lib.ptp_predictor_set_input_i64.restype = ctypes.c_int
    lib.ptp_predictor_set_input_i64.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ]
    lib.ptp_predictor_run.restype = ctypes.c_int
    lib.ptp_predictor_run.argtypes = [ctypes.c_void_p]
    lib.ptp_predictor_num_outputs.restype = ctypes.c_int
    lib.ptp_predictor_num_outputs.argtypes = [ctypes.c_void_p]
    lib.ptp_predictor_get_output.restype = ctypes.c_int64
    lib.ptp_predictor_get_output.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int),
        ctypes.c_int,
    ]
    lib.ptp_predictor_destroy.restype = None
    lib.ptp_predictor_destroy.argtypes = [ctypes.c_void_p]
    _pred_lib = lib
    return lib


class NativePredictor:
    """C++ inference over a saved inference model (no jax, no Python op
    kernels).  Covers the host inference op subset — see predictor.cc;
    unsupported ops raise with the supported list.  For full-op or TPU
    inference use ``paddle_tpu.inference.AnalysisPredictor``."""

    def __init__(self, model_dir: str):
        lib = _predictor_lib()
        if lib is None:
            raise RuntimeError(
                "native predictor unavailable (g++ build failed)"
            )
        self._lib = lib
        self._h = lib.ptp_predictor_create(str(model_dir).encode())
        err = lib.ptp_predictor_error(self._h)
        if err:
            msg = err.decode()
            lib.ptp_predictor_destroy(self._h)
            self._h = None
            raise RuntimeError("native predictor load: " + msg)

    def run(self, feeds: dict):
        lib = self._lib
        for name, arr in feeds.items():
            arr = np.ascontiguousarray(arr)
            shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
            if np.issubdtype(arr.dtype, np.integer):
                a64 = np.ascontiguousarray(arr, dtype=np.int64)
                lib.ptp_predictor_set_input_i64(
                    self._h, name.encode(),
                    a64.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    shape, arr.ndim,
                )
            else:
                a32 = np.ascontiguousarray(arr, dtype=np.float32)
                lib.ptp_predictor_set_input(
                    self._h, name.encode(),
                    a32.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    shape, arr.ndim,
                )
        if lib.ptp_predictor_run(self._h) != 0:
            raise RuntimeError(
                "native predictor run: "
                + lib.ptp_predictor_error(self._h).decode()
            )
        outs = []
        for i in range(lib.ptp_predictor_num_outputs(self._h)):
            shape = (ctypes.c_int64 * 16)()
            ndim = ctypes.c_int()
            n = lib.ptp_predictor_get_output(
                self._h, i, None, shape, ctypes.byref(ndim), 16)
            if n < 0:
                raise RuntimeError("native predictor: missing output %d" % i)
            buf = np.empty(int(n), np.float32)
            lib.ptp_predictor_get_output(
                self._h, i,
                buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                shape, ctypes.byref(ndim), 16,
            )
            outs.append(buf.reshape([int(shape[d]) for d in range(ndim.value)]))
        return outs

    def __del__(self):
        if getattr(self, "_h", None) is not None:
            self._lib.ptp_predictor_destroy(self._h)


__all__.append("NativePredictor")
