// Native (C++) inference predictor for the JSON Program format.
//
// Reference analog: paddle/fluid/inference/api/api_impl.h (NativePaddle-
// Predictor — load a saved inference model and run it without Python)
// and the pure-C++ deployment story of paddle/fluid/train/demo.
//
// This executor covers the CPU inference op subset (fc decomposition:
// mul/elementwise_add/activations, softmax, batch_norm is_test, scale,
// reshape2, dropout is_test, lookup_table, int8 dequantize_abs_max from
// the QAT freeze pass).  The TPU compute path stays XLA/JAX — this is
// the Python-free DEPLOYMENT path for host-side serving, exercised from
// Python via ctypes (paddle_tpu/native/__init__.py NativePredictor) and
// buildable as a standalone CLI (-DPTP_MAIN).
//
// File formats consumed (written by paddle_tpu.io.save_inference_model):
//   <dir>/__model__           JSON: {program:{blocks:[{vars,ops}]},
//                                    feed_names, fetch_names}
//   <dir>/<var>.npy           NPY v1/v2, '/'->'%2F' escaped names;
//                             dtypes f4/f8/i1/i4/i8
#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <map>
#include <memory>
#include <sstream>
#include <string>
#include <vector>

namespace ptp {

// ---------------------------------------------------------------------------
// Minimal JSON parser (objects/arrays/strings/numbers/bools/null).
// ---------------------------------------------------------------------------
struct Json {
  enum Kind { kNull, kBool, kNum, kStr, kArr, kObj } kind = kNull;
  bool b = false;
  double num = 0;
  std::string str;
  std::vector<Json> arr;
  std::vector<std::pair<std::string, Json>> obj;

  const Json* get(const std::string& key) const {
    for (auto& kv : obj)
      if (kv.first == key) return &kv.second;
    return nullptr;
  }
  int64_t as_int() const { return static_cast<int64_t>(num); }
};

struct JsonParser {
  const char* p;
  const char* end;
  std::string err;

  explicit JsonParser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }
  bool fail(const char* m) {
    if (err.empty()) err = m;
    return false;
  }
  bool parse(Json* out) {
    skip_ws();
    if (p >= end) return fail("eof");
    switch (*p) {
      case '{': return parse_obj(out);
      case '[': return parse_arr(out);
      case '"': out->kind = Json::kStr; return parse_str(&out->str);
      case 't':
        if (end - p >= 4 && !strncmp(p, "true", 4)) {
          out->kind = Json::kBool; out->b = true; p += 4; return true;
        }
        return fail("bad literal");
      case 'f':
        if (end - p >= 5 && !strncmp(p, "false", 5)) {
          out->kind = Json::kBool; out->b = false; p += 5; return true;
        }
        return fail("bad literal");
      case 'n':
        if (end - p >= 4 && !strncmp(p, "null", 4)) {
          out->kind = Json::kNull; p += 4; return true;
        }
        return fail("bad literal");
      default: return parse_num(out);
    }
  }
  bool parse_str(std::string* out) {
    ++p;  // opening quote
    out->clear();
    while (p < end && *p != '"') {
      if (*p == '\\') {
        ++p;
        if (p >= end) return fail("bad escape");
        switch (*p) {
          case 'n': out->push_back('\n'); break;
          case 't': out->push_back('\t'); break;
          case 'r': out->push_back('\r'); break;
          case 'b': out->push_back('\b'); break;
          case 'f': out->push_back('\f'); break;
          case 'u': {
            if (end - p < 5) return fail("bad \\u");
            int code = 0;
            for (int i = 1; i <= 4; ++i) {
              char c = p[i];
              code <<= 4;
              if (c >= '0' && c <= '9') code |= c - '0';
              else if (c >= 'a' && c <= 'f') code |= c - 'a' + 10;
              else if (c >= 'A' && c <= 'F') code |= c - 'A' + 10;
              else return fail("bad \\u digit");
            }
            // UTF-8 encode (BMP only; surrogate pairs unsupported)
            if (code < 0x80) out->push_back(static_cast<char>(code));
            else if (code < 0x800) {
              out->push_back(static_cast<char>(0xC0 | (code >> 6)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            } else {
              out->push_back(static_cast<char>(0xE0 | (code >> 12)));
              out->push_back(static_cast<char>(0x80 | ((code >> 6) & 0x3F)));
              out->push_back(static_cast<char>(0x80 | (code & 0x3F)));
            }
            p += 4;
            break;
          }
          default: out->push_back(*p);
        }
        ++p;
      } else {
        out->push_back(*p++);
      }
    }
    if (p >= end) return fail("unterminated string");
    ++p;  // closing quote
    return true;
  }
  bool parse_num(Json* out) {
    char* q = nullptr;
    out->kind = Json::kNum;
    out->num = strtod(p, &q);
    if (q == p) return fail("bad number");
    p = q;
    return true;
  }
  bool parse_arr(Json* out) {
    out->kind = Json::kArr;
    ++p;
    skip_ws();
    if (p < end && *p == ']') { ++p; return true; }
    while (true) {
      out->arr.emplace_back();
      if (!parse(&out->arr.back())) return false;
      skip_ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == ']') { ++p; return true; }
      return fail("bad array");
    }
  }
  bool parse_obj(Json* out) {
    out->kind = Json::kObj;
    ++p;
    skip_ws();
    if (p < end && *p == '}') { ++p; return true; }
    while (true) {
      skip_ws();
      if (p >= end || *p != '"') return fail("bad key");
      std::string key;
      if (!parse_str(&key)) return false;
      skip_ws();
      if (p >= end || *p != ':') return fail("missing colon");
      ++p;
      out->obj.emplace_back(key, Json());
      if (!parse(&out->obj.back().second)) return false;
      skip_ws();
      if (p < end && *p == ',') { ++p; continue; }
      if (p < end && *p == '}') { ++p; return true; }
      return fail("bad object");
    }
  }
};

// ---------------------------------------------------------------------------
// Tensors (fp32 compute; int ids kept as double-free fp32 copies is NOT ok
// for lookup ids, so an int64 side buffer is carried when integral).
// ---------------------------------------------------------------------------
struct Tensor {
  std::vector<int64_t> shape;
  std::vector<float> f;        // fp32 payload (compute path)
  std::vector<int64_t> i;      // integral payload (lookup ids)
  bool is_int = false;

  int64_t numel() const {
    int64_t n = 1;
    for (auto s : shape) n *= s;
    return n;
  }
};

// NPY reader (v1/v2, little-endian, C order).
static bool read_npy(const std::string& path, Tensor* out, std::string* err) {
  std::ifstream fin(path, std::ios::binary);
  if (!fin) { *err = "cannot open " + path; return false; }
  char magic[8];
  fin.read(magic, 8);
  if (!fin || strncmp(magic, "\x93NUMPY", 6) != 0) {
    *err = "bad npy magic in " + path;
    return false;
  }
  uint32_t hlen = 0;
  if (magic[6] == 1) {
    uint16_t h16 = 0;
    fin.read(reinterpret_cast<char*>(&h16), 2);
    hlen = h16;
  } else {
    fin.read(reinterpret_cast<char*>(&hlen), 4);
  }
  std::string header(hlen, '\0');
  fin.read(&header[0], hlen);
  auto find_val = [&](const char* key) -> std::string {
    auto k = header.find(key);
    if (k == std::string::npos) return "";
    k = header.find(':', k);
    auto e = header.find_first_of(",}", k);
    return header.substr(k + 1, e - k - 1);
  };
  std::string descr = find_val("'descr'");
  bool fortran = find_val("'fortran_order'").find("True") != std::string::npos;
  if (fortran) { *err = "fortran order unsupported: " + path; return false; }
  // shape is a tuple — "(4, 6)" contains commas, so span the parens
  // instead of using the comma-terminated find_val
  std::string shape_s;
  {
    auto k = header.find("'shape'");
    if (k == std::string::npos) { *err = "npy header missing shape: " + path; return false; }
    auto o = header.find('(', k);
    auto c = header.find(')', o);
    if (o == std::string::npos || c == std::string::npos) {
      *err = "bad npy shape header: " + path;
      return false;
    }
    shape_s = header.substr(o, c - o + 1);
  }
  out->shape.clear();
  for (size_t i = 0; i < shape_s.size();) {
    if (isdigit(shape_s[i])) {
      char* q = nullptr;
      out->shape.push_back(strtol(shape_s.c_str() + i, &q, 10));
      i = q - shape_s.c_str();
    } else {
      ++i;
    }
  }
  int64_t n = 1;
  for (auto s : out->shape) n *= s;
  auto load = [&](auto sample, bool integral) {
    using T = decltype(sample);
    std::vector<T> buf(n);
    fin.read(reinterpret_cast<char*>(buf.data()), n * sizeof(T));
    out->is_int = integral;
    if (integral) {
      out->i.resize(n);
      for (int64_t k = 0; k < n; ++k) out->i[k] = static_cast<int64_t>(buf[k]);
    } else {
      out->f.resize(n);
      for (int64_t k = 0; k < n; ++k) out->f[k] = static_cast<float>(buf[k]);
    }
  };
  if (descr.find("f4") != std::string::npos) load(float{}, false);
  else if (descr.find("f8") != std::string::npos) load(double{}, false);
  else if (descr.find("i1") != std::string::npos) load(int8_t{}, false);
  else if (descr.find("i4") != std::string::npos) load(int32_t{}, true);
  else if (descr.find("i8") != std::string::npos) load(int64_t{}, true);
  else { *err = "unsupported npy dtype " + descr + " in " + path; return false; }
  if (!fin) { *err = "truncated npy " + path; return false; }
  return true;
}

// ---------------------------------------------------------------------------
// Predictor
// ---------------------------------------------------------------------------
struct Predictor {
  Json model;
  std::map<std::string, Tensor> vars;   // persistables + intermediates
  std::vector<std::string> feed_names;
  std::vector<std::string> fetch_names;
  std::map<std::string, bool> persist_names;  // loaded persistables
  std::map<std::string, bool> fed;            // feeds set since last run
  const Json* ops = nullptr;
  bool load_ok = false;
  std::string err;

  static std::string escape_name(const std::string& n) {
    std::string out;
    for (char c : n) {
      if (c == '/') out += "%2F";
      else out.push_back(c);
    }
    return out;
  }

  bool load(const std::string& dir) {
    std::ifstream fin(dir + "/__model__");
    if (!fin) { err = "no __model__ in " + dir; return false; }
    std::stringstream ss;
    ss << fin.rdbuf();
    std::string text = ss.str();
    JsonParser jp(text);
    if (!jp.parse(&model)) { err = "model json: " + jp.err; return false; }
    // every get() is null-checked: a structurally valid but incomplete
    // __model__ must surface through err, never a null dereference
    const Json* prog = model.get("program");
    if (!prog) { err = "no program"; return false; }
    const Json* blocks = prog->get("blocks");
    if (!blocks || blocks->arr.empty()) { err = "no blocks"; return false; }
    const Json* block = &blocks->arr[0];
    ops = block->get("ops");
    if (!ops) { err = "no ops"; return false; }
    const Json* jfeed = model.get("feed_names");
    const Json* jfetch = model.get("fetch_names");
    if (!jfeed || !jfetch) { err = "missing feed/fetch names"; return false; }
    for (auto& v : jfeed->arr) feed_names.push_back(v.str);
    for (auto& v : jfetch->arr) fetch_names.push_back(v.str);
    for (auto& op : ops->arr) {
      if (!op.get("type") || !op.get("inputs") || !op.get("outputs")) {
        err = "malformed op entry in program";
        return false;
      }
    }
    // load persistables
    const Json* jvars = block->get("vars");
    if (!jvars) { err = "no vars"; return false; }
    for (auto& v : jvars->arr) {
      const Json* pers = v.get("persistable");
      const Json* jname = v.get("name");
      if (!pers || !pers->b || !jname) continue;
      const std::string name = jname->str;
      Tensor t;
      std::string e;
      if (!read_npy(dir + "/" + escape_name(name) + ".npy", &t, &e)) {
        err = e;
        return false;
      }
      vars[name] = std::move(t);
      persist_names[name] = true;
    }
    load_ok = true;
    return true;
  }

  const Tensor& in(const Json& op, const char* slot, int idx = 0) {
    const Json* names = op.get("inputs")->get(slot);
    return vars[names->arr[idx].str];
  }
  Tensor& out(const Json& op, const char* slot, int idx = 0) {
    const Json* names = op.get("outputs")->get(slot);
    return vars[names->arr[idx].str];
  }
  static double attr_num(const Json& op, const char* key, double dflt) {
    const Json* a = op.get("attrs");
    const Json* v = a ? a->get(key) : nullptr;
    return v ? (v->kind == Json::kBool ? (v->b ? 1 : 0) : v->num) : dflt;
  }

  bool run() {
    err.clear();  // a failed run must not replay its error on the next
    // drop stale intermediates (incl. previous runs' feeds) so the
    // missing-feed pre-flight stays effective on EVERY run — without
    // this, a typo'd feed on run 2 would silently reuse run 1's tensor
    // and serve the previous request's result.  Persistables stay:
    // they are the model state (sgd updates them in place).
    for (auto it = vars.begin(); it != vars.end();) {
      if (!persist_names.count(it->first) && !fed.count(it->first))
        it = vars.erase(it);
      else
        ++it;
    }
    fed.clear();
    // pre-flight: every op input must be a loaded persistable, a set
    // feed, or an earlier op's output — a typo'd feed name must error
    // here, not read a default-constructed empty Tensor (UB)
    std::map<std::string, bool> known;
    for (auto& kv : vars) known[kv.first] = true;
    for (auto& op : ops->arr) {
      const std::string& type = op.get("type")->str;
      if (type == "feed" || type == "fetch") continue;
      for (auto& slot : op.get("inputs")->obj)
        for (auto& n : slot.second.arr)
          if (!n.str.empty() && !known.count(n.str)) {
            err = "input var '" + n.str + "' for op '" + type +
                  "' is not set — missing feed? (feeds: ";
            for (size_t i = 0; i < feed_names.size(); ++i)
              err += (i ? ", " : "") + feed_names[i];
            err += ")";
            return false;
          }
      for (auto& slot : op.get("outputs")->obj)
        for (auto& n : slot.second.arr)
          if (!n.str.empty()) known[n.str] = true;
    }
    for (auto& op : ops->arr) {
      const std::string& type = op.get("type")->str;
      if (type == "feed" || type == "fetch") continue;
      if (!exec(op, type)) return false;
    }
    return true;
  }

  bool exec(const Json& op, const std::string& type) {
    if (type == "mul") return op_mul(op);
    if (type == "elementwise_add") return op_ewise(op, '+');
    if (type == "elementwise_sub") return op_ewise(op, '-');
    if (type == "elementwise_mul") return op_ewise(op, '*');
    if (type == "elementwise_div") return op_ewise(op, '/');
    if (type == "relu") return op_unary(op, [](float x) { return x > 0 ? x : 0; });
    if (type == "tanh") return op_unary(op, [](float x) { return std::tanh(x); });
    if (type == "sigmoid")
      return op_unary(op, [](float x) { return 1.0f / (1.0f + std::exp(-x)); });
    if (type == "exp") return op_unary(op, [](float x) { return std::exp(x); });
    if (type == "sqrt") return op_unary(op, [](float x) { return std::sqrt(x); });
    if (type == "softmax") return op_softmax(op);
    if (type == "scale") return op_scale(op);
    if (type == "reshape2" || type == "reshape") return op_reshape(op);
    if (type == "dropout") return op_dropout(op);
    if (type == "batch_norm") return op_batch_norm(op);
    if (type == "lookup_table" || type == "lookup_table_v2")
      return op_lookup(op);
    if (type == "dequantize_abs_max") return op_dequant(op);
    if (type == "dequantize_channel_wise_abs_max") return op_dequant_cw(op);
    if (type == "fake_quantize_dequantize_abs_max") return op_fake_quant(op);
    if (type == "fake_quantize_dequantize_moving_average_abs_max" ||
        type == "fake_quantize_dequantize_range_abs_max")
      return op_fake_quant_ma(op);  // is_test form: fixed InScale
    if (type == "moving_average_abs_max_scale") return op_ma_scale(op);
    if (type == "cast") return op_cast(op);
    if (type == "conv2d") return op_conv2d(op);
    if (type == "pool2d") return op_pool2d(op);
    // training subset (the pure-C++ train demo analog, demo_trainer.cc)
    if (type == "fill_constant") return op_fill_constant(op);
    if (type == "mean") return op_mean(op);
    if (type == "square_error_cost") return op_sec(op);
    if (type == "mean_grad") return op_mean_grad(op);
    if (type == "square_error_cost_grad") return op_sec_grad(op);
    if (type == "relu_grad") return op_relu_grad(op);
    if (type == "elementwise_add_grad") return op_ewise_add_grad(op);
    if (type == "mul_grad") return op_mul_grad(op);
    if (type == "sgd") return op_sgd(op);
    err = "native predictor: unsupported op '" + type +
          "' (supported: mul, conv2d, pool2d, elementwise_{add,sub,mul,div}, "
          "relu, tanh, sigmoid, exp, sqrt, softmax, scale, reshape2, "
          "dropout[is_test], batch_norm[is_test], lookup_table, "
          "dequantize_abs_max, cast, "
          "and the train set fill_constant/mean/square_error_cost/"
          "{mean,square_error_cost,relu,elementwise_add,mul}_grad/sgd; "
          "use the Python AnalysisPredictor for the full op set)";
    return false;
  }

  bool has_out(const Json& op, const char* slot) {
    const Json* names = op.get("outputs")->get(slot);
    return names && !names->arr.empty() && !names->arr[0].str.empty();
  }

  // mul: collapse x to 2D at x_num_col_dims, y at y_num_col_dims
  bool op_mul(const Json& op) {
    const Tensor& x = in(op, "X");
    const Tensor& y = in(op, "Y");
    int xd = static_cast<int>(attr_num(op, "x_num_col_dims", 1));
    int yd = static_cast<int>(attr_num(op, "y_num_col_dims", 1));
    int64_t m = 1, k = 1, k2 = 1, n = 1;
    for (int i = 0; i < xd; ++i) m *= x.shape[i];
    for (size_t i = xd; i < x.shape.size(); ++i) k *= x.shape[i];
    for (int i = 0; i < yd; ++i) k2 *= y.shape[i];
    for (size_t i = yd; i < y.shape.size(); ++i) n *= y.shape[i];
    if (k != k2) { err = "mul: K mismatch"; return false; }
    Tensor& o = out(op, "Out");
    o.shape.assign(x.shape.begin(), x.shape.begin() + xd);
    o.shape.insert(o.shape.end(), y.shape.begin() + yd, y.shape.end());
    o.f.assign(m * n, 0.0f);
    o.is_int = false;
    for (int64_t i = 0; i < m; ++i)
      for (int64_t kk = 0; kk < k; ++kk) {
        float xv = x.f[i * k + kk];
        if (xv == 0.0f) continue;
        const float* yrow = &y.f[kk * n];
        float* orow = &o.f[i * n];
        for (int64_t j = 0; j < n; ++j) orow[j] += xv * yrow[j];
      }
    return true;
  }

  // elementwise with trailing/bcast-at-axis Y (reference elementwise_op.h)
  bool op_ewise(const Json& op, char kind) {
    const Tensor& x = in(op, "X");
    const Tensor& y = in(op, "Y");
    int axis = static_cast<int>(attr_num(op, "axis", -1));
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.f.resize(x.f.size());
    o.is_int = false;
    int64_t ny = 1;
    for (auto s : y.shape) ny *= s;
    if (axis < 0) axis = static_cast<int>(x.shape.size() - y.shape.size());
    int64_t pre = 1, mid = 1, post = 1;
    for (int i = 0; i < axis; ++i) pre *= x.shape[i];
    for (size_t i = axis; i < axis + y.shape.size() && i < x.shape.size(); ++i)
      mid *= x.shape[i];
    post = static_cast<int64_t>(x.f.size()) / (pre * mid);
    if (mid != ny) { err = "elementwise: shape mismatch"; return false; }
    for (int64_t a = 0; a < pre; ++a)
      for (int64_t b = 0; b < mid; ++b)
        for (int64_t c = 0; c < post; ++c) {
          int64_t idx = (a * mid + b) * post + c;
          float xv = x.f[idx], yv = y.f[b];
          o.f[idx] = kind == '+' ? xv + yv
                     : kind == '-' ? xv - yv
                     : kind == '*' ? xv * yv
                                   : xv / yv;
        }
    return true;
  }

  template <typename F>
  bool op_unary(const Json& op, F fn) {
    const Tensor& x = in(op, "X");
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    for (size_t i = 0; i < x.f.size(); ++i) o.f[i] = fn(x.f[i]);
    return true;
  }

  bool op_softmax(const Json& op) {
    const Tensor& x = in(op, "X");
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    int64_t d = x.shape.back();
    int64_t rows = static_cast<int64_t>(x.f.size()) / d;
    for (int64_t r = 0; r < rows; ++r) {
      const float* xi = &x.f[r * d];
      float* oi = &o.f[r * d];
      float mx = xi[0];
      for (int64_t j = 1; j < d; ++j) mx = std::max(mx, xi[j]);
      float sum = 0;
      for (int64_t j = 0; j < d; ++j) { oi[j] = std::exp(xi[j] - mx); sum += oi[j]; }
      for (int64_t j = 0; j < d; ++j) oi[j] /= sum;
    }
    return true;
  }

  bool op_scale(const Json& op) {
    float s = static_cast<float>(attr_num(op, "scale", 1.0));
    float b = static_cast<float>(attr_num(op, "bias", 0.0));
    bool after = attr_num(op, "bias_after_scale", 1.0) != 0.0;
    return op_unary(op, [=](float x) { return after ? x * s + b : (x + b) * s; });
  }

  bool op_reshape(const Json& op) {
    const Tensor& x = in(op, "X");
    Tensor& o = out(op, "Out");
    const Json* shp = op.get("attrs")->get("shape");
    o.f = x.f;
    o.i = x.i;
    o.is_int = x.is_int;
    o.shape.clear();
    int64_t known = 1, minus = -1;
    for (size_t i = 0; i < shp->arr.size(); ++i) {
      int64_t v = shp->arr[i].as_int();
      if (v == -1) minus = static_cast<int64_t>(i);
      else if (v == 0) v = x.shape[i];
      o.shape.push_back(v);
      if (v > 0) known *= v;
    }
    if (minus >= 0) o.shape[minus] = x.numel() / known;
    return true;
  }

  bool op_dropout(const Json& op) {
    if (attr_num(op, "is_test", 0.0) == 0.0) {
      err = "dropout: only is_test=True supported in the native predictor";
      return false;
    }
    std::string impl = "downgrade_in_infer";
    const Json* a = op.get("attrs")->get("dropout_implementation");
    if (a) impl = a->str;
    float keep = 1.0f - static_cast<float>(attr_num(op, "dropout_prob", 0.5));
    float mul = impl == "upscale_in_train" ? 1.0f : keep;
    return op_unary(op, [=](float x) { return x * mul; });
  }

  bool op_batch_norm(const Json& op) {
    if (attr_num(op, "is_test", 0.0) == 0.0) {
      err = "batch_norm: only is_test=True supported in the native predictor";
      return false;
    }
    const Tensor& x = in(op, "X");
    const Tensor& scale = in(op, "Scale");
    const Tensor& bias = in(op, "Bias");
    const Tensor& mean = in(op, "Mean");
    const Tensor& var = in(op, "Variance");
    float eps = static_cast<float>(attr_num(op, "epsilon", 1e-5));
    Tensor& o = out(op, "Y");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    // NCHW: channel axis 1
    int64_t c = x.shape.size() > 1 ? x.shape[1] : x.shape[0];
    int64_t pre = x.shape[0];
    int64_t post = static_cast<int64_t>(x.f.size()) / (pre * c);
    for (int64_t a = 0; a < pre; ++a)
      for (int64_t ch = 0; ch < c; ++ch) {
        float inv = scale.f[ch] / std::sqrt(var.f[ch] + eps);
        float sh = bias.f[ch] - mean.f[ch] * inv;
        float* row = &o.f[(a * c + ch) * post];
        const float* xr = &x.f[(a * c + ch) * post];
        for (int64_t j = 0; j < post; ++j) row[j] = xr[j] * inv + sh;
      }
    return true;
  }

  bool op_lookup(const Json& op) {
    const Tensor& w = in(op, "W");
    const Tensor& ids = in(op, "Ids");
    Tensor& o = out(op, "Out");
    int64_t d = w.shape[1];
    int64_t n = ids.is_int ? static_cast<int64_t>(ids.i.size())
                           : static_cast<int64_t>(ids.f.size());
    // padding_idx rows come back zero, matching the Python kernel
    // (ops/tensor_ops.py lookup_table); absent/null/negative = disabled
    // (the attr may be JSON null — Python None — which attr_num would
    // misread as 0 and zero the id-0 rows)
    int64_t pad = -1;
    const Json* attrs = op.get("attrs");
    const Json* jpad = attrs ? attrs->get("padding_idx") : nullptr;
    if (jpad && jpad->kind == Json::kNum) pad = jpad->as_int();
    o.shape = ids.shape;
    if (!o.shape.empty() && o.shape.back() == 1) o.shape.pop_back();
    o.shape.push_back(d);
    o.is_int = false;
    o.f.resize(n * d);
    for (int64_t k = 0; k < n; ++k) {
      int64_t id = ids.is_int ? ids.i[k] : static_cast<int64_t>(ids.f[k]);
      if (id < 0 || id >= w.shape[0]) { err = "lookup: id out of range"; return false; }
      if (pad >= 0 && id == pad)
        std::fill(&o.f[k * d], &o.f[(k + 1) * d], 0.0f);
      else
        std::copy(&w.f[id * d], &w.f[(id + 1) * d], &o.f[k * d]);
    }
    return true;
  }

  bool op_dequant(const Json& op) {
    const Tensor& x = in(op, "X");     // int8 weights loaded as fp32
    const Tensor& scale = in(op, "Scale");
    float max_range = static_cast<float>(attr_num(op, "max_range", 127.0));
    float mul = scale.f[0] / max_range;
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    for (size_t i = 0; i < x.f.size(); ++i) o.f[i] = x.f[i] * mul;
    return true;
  }

  static int64_t attr_pair(const Json& op, const char* key, int idx,
                           int64_t dflt) {
    const Json* a = op.get("attrs");
    const Json* v = a ? a->get(key) : nullptr;
    if (!v) return dflt;
    if (v->kind == Json::kArr)
      return idx < static_cast<int>(v->arr.size()) ? v->arr[idx].as_int()
                                                   : dflt;
    return static_cast<int64_t>(v->num);
  }

  // NCHW direct convolution (inference serving sizes; groups=1,
  // dilation=1 — InferenceTranspiler folds BN so conv+bias+act covers
  // the exported CNN graphs)
  bool data_format_is_nchw(const Json& op, const char* what) {
    const Json* a = op.get("attrs");
    const Json* v = a ? a->get("data_format") : nullptr;
    if (v && v->kind == Json::kStr && v->str != "NCHW" && v->str != "AnyLayout") {
      err = std::string(what) + ": only NCHW supported natively (got " +
            v->str + ")";
      return false;
    }
    return true;
  }

  bool op_conv2d(const Json& op) {
    const Tensor& x = in(op, "Input");
    const Tensor& w = in(op, "Filter");  // OIHW
    if (!data_format_is_nchw(op, "conv2d")) return false;
    if (attr_num(op, "groups", 1) != 1) {
      err = "conv2d: only groups=1 supported natively";
      return false;
    }
    int64_t dil_h = attr_pair(op, "dilations", 0, 1);
    int64_t dil_w = attr_pair(op, "dilations", 1, 1);
    if (dil_h != 1 || dil_w != 1) {
      err = "conv2d: only dilation=1 supported natively";
      return false;
    }
    int64_t n = x.shape[0], ci = x.shape[1], h = x.shape[2], wd = x.shape[3];
    int64_t co = w.shape[0], kh = w.shape[2], kw = w.shape[3];
    if (w.shape[1] != ci) { err = "conv2d: channel mismatch"; return false; }
    int64_t sh = attr_pair(op, "strides", 0, 1);
    int64_t sw = attr_pair(op, "strides", 1, 1);
    int64_t ph = attr_pair(op, "paddings", 0, 0);
    int64_t pw = attr_pair(op, "paddings", 1, 0);
    // check the numerators BEFORE dividing: C++ integer division
    // truncates toward zero, so (-1)/2 + 1 == 1 would dodge an
    // output-dim guard and silently emit partial-window results
    int64_t num_h = h + 2 * ph - kh, num_w = wd + 2 * pw - kw;
    if (num_h < 0 || num_w < 0) {
      err = "conv2d: kernel exceeds padded input";
      return false;
    }
    int64_t oh = num_h / sh + 1;
    int64_t ow = num_w / sw + 1;
    Tensor& o = out(op, "Output");
    o.shape = {n, co, oh, ow};
    o.is_int = false;
    o.f.assign(n * co * oh * ow, 0.0f);
    for (int64_t b = 0; b < n; ++b)
      for (int64_t oc = 0; oc < co; ++oc)
        for (int64_t ic = 0; ic < ci; ++ic) {
          const float* wk = &w.f[((oc * ci) + ic) * kh * kw];
          const float* xi = &x.f[(b * ci + ic) * h * wd];
          float* oo = &o.f[(b * co + oc) * oh * ow];
          for (int64_t yy = 0; yy < oh; ++yy)
            for (int64_t xx = 0; xx < ow; ++xx) {
              float acc = 0;
              for (int64_t ky = 0; ky < kh; ++ky) {
                int64_t iy = yy * sh - ph + ky;
                if (iy < 0 || iy >= h) continue;
                for (int64_t kx = 0; kx < kw; ++kx) {
                  int64_t ix = xx * sw - pw + kx;
                  if (ix < 0 || ix >= wd) continue;
                  acc += xi[iy * wd + ix] * wk[ky * kw + kx];
                }
              }
              oo[yy * ow + xx] += acc;
            }
        }
    return true;
  }

  bool op_pool2d(const Json& op) {
    const Tensor& x = in(op, "X");
    if (!data_format_is_nchw(op, "pool2d")) return false;
    std::string ptype = "max";
    const Json* a = op.get("attrs");
    const Json* pt = a ? a->get("pooling_type") : nullptr;
    if (pt && pt->kind == Json::kStr) ptype = pt->str;
    bool global = attr_num(op, "global_pooling", 0.0) != 0.0;
    bool exclusive = attr_num(op, "exclusive", 1.0) != 0.0;
    int64_t n = x.shape[0], c = x.shape[1], h = x.shape[2], wd = x.shape[3];
    int64_t kh = global ? h : attr_pair(op, "ksize", 0, 2);
    int64_t kw = global ? wd : attr_pair(op, "ksize", 1, 2);
    int64_t sh = global ? 1 : attr_pair(op, "strides", 0, kh);
    int64_t sw = global ? 1 : attr_pair(op, "strides", 1, kw);
    int64_t ph = global ? 0 : attr_pair(op, "paddings", 0, 0);
    int64_t pw = global ? 0 : attr_pair(op, "paddings", 1, 0);
    bool ceil_mode = attr_num(op, "ceil_mode", 0.0) != 0.0;
    int64_t num_h = h + 2 * ph - kh, num_w = wd + 2 * pw - kw;
    if (num_h < 0 || num_w < 0) {  // numerator check: see op_conv2d
      err = "pool2d: kernel exceeds padded input";
      return false;
    }
    // ceil_mode rounds partial windows IN (reference pool_op.h
    // PoolOutputSize); the tap loops below already clamp to the input
    int64_t oh = (ceil_mode ? (num_h + sh - 1) / sh : num_h / sh) + 1;
    int64_t ow = (ceil_mode ? (num_w + sw - 1) / sw : num_w / sw) + 1;
    Tensor& o = out(op, "Out");
    o.shape = {n, c, oh, ow};
    o.is_int = false;
    o.f.assign(n * c * oh * ow, 0.0f);
    for (int64_t b = 0; b < n; ++b)
      for (int64_t ch = 0; ch < c; ++ch) {
        const float* xi = &x.f[(b * c + ch) * h * wd];
        float* oo = &o.f[(b * c + ch) * oh * ow];
        for (int64_t yy = 0; yy < oh; ++yy)
          for (int64_t xx = 0; xx < ow; ++xx) {
            float best = -3.4e38f;
            double sum = 0;
            int64_t cnt = 0;
            for (int64_t ky = 0; ky < kh; ++ky) {
              int64_t iy = yy * sh - ph + ky;
              if (iy < 0 || iy >= h) continue;
              for (int64_t kx = 0; kx < kw; ++kx) {
                int64_t ix = xx * sw - pw + kx;
                if (ix < 0 || ix >= wd) continue;
                float v = xi[iy * wd + ix];
                best = std::max(best, v);
                sum += v;
                ++cnt;
              }
            }
            oo[yy * ow + xx] =
                ptype == "max"
                    ? best
                    : static_cast<float>(
                          sum / (exclusive ? std::max<int64_t>(cnt, 1)
                                           : kh * kw));
          }
      }
    return true;
  }

  // --- training subset --------------------------------------------------
  bool op_fill_constant(const Json& op) {
    Tensor& o = out(op, "Out");
    const Json* shp = op.get("attrs")->get("shape");
    o.shape.clear();
    int64_t n = 1;
    if (shp)
      for (auto& s : shp->arr) { o.shape.push_back(s.as_int()); n *= s.as_int(); }
    o.is_int = false;
    o.f.assign(n, static_cast<float>(attr_num(op, "value", 0.0)));
    return true;
  }

  bool op_mean(const Json& op) {
    const Tensor& x = in(op, "X");
    Tensor& o = out(op, "Out");
    double s = 0;
    for (float v : x.f) s += v;
    o.shape = {1};
    o.is_int = false;
    o.f = {static_cast<float>(s / std::max<size_t>(x.f.size(), 1))};
    return true;
  }

  bool op_sec(const Json& op) {  // square_error_cost: (x - y)^2
    const Tensor& x = in(op, "X");
    const Tensor& y = in(op, "Y");
    if (x.f.size() != y.f.size()) { err = "square_error_cost: shape mismatch"; return false; }
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    for (size_t i = 0; i < x.f.size(); ++i) {
      float d = x.f[i] - y.f[i];
      o.f[i] = d * d;
    }
    return true;
  }

  bool op_mean_grad(const Json& op) {
    const Tensor& x = in(op, "X");
    const Tensor& og = in(op, "Out@GRAD");
    Tensor& xg = out(op, "X@GRAD");
    xg.shape = x.shape;
    xg.is_int = false;
    float g = og.f.empty() ? 1.0f : og.f[0];
    xg.f.assign(x.f.size(), g / std::max<size_t>(x.f.size(), 1));
    return true;
  }

  bool op_sec_grad(const Json& op) {
    const Tensor& x = in(op, "X");
    const Tensor& y = in(op, "Y");
    const Tensor& og = in(op, "Out@GRAD");
    // both grad slots are optional per the backward pass's grad-op
    // contract (Y is often a label with stop_gradient, but may be a
    // trainable branch); d/dx = 2(x-y)·og, d/dy = -2(x-y)·og
    if (has_out(op, "X@GRAD")) {
      Tensor& xg = out(op, "X@GRAD");
      xg.shape = x.shape;
      xg.is_int = false;
      xg.f.resize(x.f.size());
      for (size_t i = 0; i < x.f.size(); ++i)
        xg.f[i] = 2.0f * (x.f[i] - y.f[i]) * og.f[i];
    }
    if (has_out(op, "Y@GRAD")) {
      Tensor& yg = out(op, "Y@GRAD");
      yg.shape = y.shape;
      yg.is_int = false;
      yg.f.resize(y.f.size());
      for (size_t i = 0; i < y.f.size(); ++i)
        yg.f[i] = -2.0f * (x.f[i] - y.f[i]) * og.f[i];
    }
    return true;
  }

  bool op_relu_grad(const Json& op) {
    const Tensor& x = in(op, "X");  // pre-activation input
    const Tensor& og = in(op, "Out@GRAD");
    Tensor& xg = out(op, "X@GRAD");
    xg.shape = x.shape;
    xg.is_int = false;
    xg.f.resize(x.f.size());
    for (size_t i = 0; i < x.f.size(); ++i)
      xg.f[i] = x.f[i] > 0 ? og.f[i] : 0.0f;
    return true;
  }

  bool op_ewise_add_grad(const Json& op) {
    const Tensor& x = in(op, "X");
    const Tensor& y = in(op, "Y");
    const Tensor& og = in(op, "Out@GRAD");
    int axis = static_cast<int>(attr_num(op, "axis", -1));
    if (axis < 0) axis = static_cast<int>(x.shape.size() - y.shape.size());
    if (has_out(op, "X@GRAD")) {
      Tensor& xg = out(op, "X@GRAD");
      xg.shape = x.shape;
      xg.is_int = false;
      xg.f = og.f;
    }
    if (has_out(op, "Y@GRAD")) {
      int64_t ny = 1;
      for (auto s : y.shape) ny *= s;
      int64_t pre = 1, mid = 1;
      for (int i = 0; i < axis; ++i) pre *= x.shape[i];
      for (size_t i = axis; i < axis + y.shape.size() && i < x.shape.size(); ++i)
        mid *= x.shape[i];
      // shape consistency FIRST so a malformed program errors loudly
      // even when a zero-sized dim would otherwise take the early-out
      if (mid != ny) { err = "elementwise_add_grad: shape mismatch"; return false; }
      if (pre * mid == 0) {  // zero-sized dim: grads are zero, and the
        Tensor& yg = out(op, "Y@GRAD");  // division below would SIGFPE
        yg.shape = y.shape;
        yg.is_int = false;
        yg.f.assign(ny, 0.0f);
        return true;
      }
      int64_t post = static_cast<int64_t>(og.f.size()) / (pre * mid);
      Tensor& yg = out(op, "Y@GRAD");
      yg.shape = y.shape;
      yg.is_int = false;
      yg.f.assign(ny, 0.0f);
      for (int64_t a = 0; a < pre; ++a)
        for (int64_t b = 0; b < mid; ++b)
          for (int64_t c = 0; c < post; ++c)
            yg.f[b] += og.f[(a * mid + b) * post + c];
    }
    return true;
  }

  bool op_mul_grad(const Json& op) {
    const Tensor& x = in(op, "X");
    const Tensor& y = in(op, "Y");
    const Tensor& og = in(op, "Out@GRAD");
    int xd = static_cast<int>(attr_num(op, "x_num_col_dims", 1));
    int yd = static_cast<int>(attr_num(op, "y_num_col_dims", 1));
    int64_t m = 1, k = 1, n = 1;
    for (int i = 0; i < xd; ++i) m *= x.shape[i];
    for (size_t i = xd; i < x.shape.size(); ++i) k *= x.shape[i];
    for (size_t i = yd; i < y.shape.size(); ++i) n *= y.shape[i];
    if (has_out(op, "X@GRAD")) {  // og [m,n] x y^T [n,k]
      Tensor& xg = out(op, "X@GRAD");
      xg.shape = x.shape;
      xg.is_int = false;
      xg.f.assign(m * k, 0.0f);
      for (int64_t i = 0; i < m; ++i)
        for (int64_t j = 0; j < n; ++j) {
          float g = og.f[i * n + j];
          if (g == 0.0f) continue;
          for (int64_t kk = 0; kk < k; ++kk)
            xg.f[i * k + kk] += g * y.f[kk * n + j];
        }
    }
    if (has_out(op, "Y@GRAD")) {  // x^T [k,m] x og [m,n]
      Tensor& yg = out(op, "Y@GRAD");
      yg.shape = y.shape;
      yg.is_int = false;
      yg.f.assign(k * n, 0.0f);
      for (int64_t i = 0; i < m; ++i)
        for (int64_t kk = 0; kk < k; ++kk) {
          float xv = x.f[i * k + kk];
          if (xv == 0.0f) continue;
          for (int64_t j = 0; j < n; ++j)
            yg.f[kk * n + j] += xv * og.f[i * n + j];
        }
    }
    return true;
  }

  bool op_sgd(const Json& op) {
    const Tensor& param = in(op, "Param");
    const Tensor& grad = in(op, "Grad");
    const Tensor& lr = in(op, "LearningRate");
    if (param.f.size() != grad.f.size()) { err = "sgd: shape mismatch"; return false; }
    float eta = lr.f.empty() ? 0.01f : lr.f[0];
    Tensor next;
    next.shape = param.shape;
    next.f.resize(param.f.size());
    for (size_t i = 0; i < param.f.size(); ++i)
      next.f[i] = param.f[i] - eta * grad.f[i];
    out(op, "ParamOut") = std::move(next);  // same name as Param: in-place
    return true;
  }

  // QAT's dynamic activation quantization (kept at inference — the
  // trained behavior; see contrib/slim/quantization.py freeze docs)
  bool op_fake_quant(const Json& op) {
    const Tensor& x = in(op, "X");
    int bits = static_cast<int>(attr_num(op, "bit_length", 8));
    float qmax = static_cast<float>((1 << (bits - 1)) - 1);
    float scale = 1e-8f;
    for (float v : x.f) scale = std::max(scale, std::fabs(v));
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    for (size_t i = 0; i < x.f.size(); ++i) {
      float q = std::nearbyint(x.f[i] / scale * qmax);
      q = std::max(-qmax, std::min(qmax, q));
      o.f[i] = q * scale / qmax;
    }
    const Json* snames = op.get("outputs")->get("OutScale");
    if (snames && !snames->arr.empty()) {
      Tensor& s = vars[snames->arr[0].str];
      s.shape = {1};
      s.is_int = false;
      s.f = {scale};
    }
    return true;
  }

  // out-scale recorder (ScaleForTrainingPass): identity passthrough at
  // inference; the recorded threshold lives in the op attrs/scope
  bool op_ma_scale(const Json& op) {
    if (attr_num(op, "is_test", 0.0) == 0.0) {
      err = "moving_average_abs_max_scale: only is_test=True supported "
            "natively — apply ScaleForInferencePass before export";
      return false;
    }
    const Tensor& x = in(op, "X");
    out(op, "Out") = x;
    return true;
  }

  // per-output-channel int8 weight dequant (QAT channel_wise freeze)
  bool op_dequant_cw(const Json& op) {
    const Tensor& x = in(op, "X");     // int8 loaded as fp32, dim0 = C
    const Tensor& scale = in(op, "Scale");
    float max_range = static_cast<float>(attr_num(op, "max_range", 127.0));
    int64_t c = x.shape.empty() ? 0 : x.shape[0];
    if (c <= 0 || static_cast<int64_t>(scale.f.size()) != c) {
      err = "dequantize_channel_wise_abs_max: scale/channel mismatch";
      return false;
    }
    int64_t per = static_cast<int64_t>(x.f.size()) / c;
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    for (int64_t ch = 0; ch < c; ++ch) {
      float mul = scale.f[ch] / max_range;
      const float* xi = &x.f[ch * per];
      float* oo = &o.f[ch * per];
      for (int64_t j = 0; j < per; ++j) oo[j] = xi[j] * mul;
    }
    return true;
  }

  // stateful activation quantizers (moving-average / range), inference
  // form: the trained InScale is fixed (the freeze pass sets is_test);
  // training-mode state updates are a Python-path concern
  bool op_fake_quant_ma(const Json& op) {
    if (attr_num(op, "is_test", 0.0) == 0.0) {
      err = "stateful fake-quant op: only is_test=True (frozen scales) "
            "supported natively — freeze the program first";
      return false;
    }
    const Tensor& x = in(op, "X");
    const Tensor& in_scale = in(op, "InScale");
    int bits = static_cast<int>(attr_num(op, "bit_length", 8));
    float qmax = static_cast<float>((1 << (bits - 1)) - 1);
    float scale = std::max(in_scale.f.empty() ? 1e-8f : in_scale.f[0], 1e-8f);
    Tensor& o = out(op, "Out");
    o.shape = x.shape;
    o.is_int = false;
    o.f.resize(x.f.size());
    for (size_t i = 0; i < x.f.size(); ++i) {
      float q = std::nearbyint(x.f[i] / scale * qmax);
      q = std::max(-qmax, std::min(qmax, q));
      o.f[i] = q * scale / qmax;
    }
    return true;
  }

  bool op_cast(const Json& op) {
    const Tensor& x = in(op, "X");
    Tensor& o = out(op, "Out");
    // fp32 compute path: any cast lands on float — an integral input
    // must be CONVERTED, not copied with its empty float payload
    o.shape = x.shape;
    o.is_int = false;
    if (x.is_int) {
      o.f.resize(x.i.size());
      for (size_t k = 0; k < x.i.size(); ++k)
        o.f[k] = static_cast<float>(x.i[k]);
      o.i.clear();
    } else {
      o.f = x.f;
      o.i.clear();
    }
    return true;
  }
};

}  // namespace ptp

// ---------------------------------------------------------------------------
// C API (ctypes surface)
// ---------------------------------------------------------------------------
extern "C" {

void* ptp_predictor_create(const char* model_dir) {
  auto* p = new ptp::Predictor();
  if (!p->load(model_dir)) return p;  // error readable via ptp_predictor_error
  return p;
}

const char* ptp_predictor_error(void* h) {
  return static_cast<ptp::Predictor*>(h)->err.c_str();
}

int ptp_predictor_set_input(void* h, const char* name, const float* data,
                            const int64_t* shape, int ndim) {
  auto* p = static_cast<ptp::Predictor*>(h);
  ptp::Tensor t;
  t.shape.assign(shape, shape + ndim);
  t.f.assign(data, data + t.numel());
  p->vars[name] = std::move(t);
  p->fed[name] = true;
  return 0;
}

int ptp_predictor_set_input_i64(void* h, const char* name, const int64_t* data,
                                const int64_t* shape, int ndim) {
  auto* p = static_cast<ptp::Predictor*>(h);
  ptp::Tensor t;
  t.shape.assign(shape, shape + ndim);
  t.is_int = true;
  t.i.assign(data, data + t.numel());
  p->vars[name] = std::move(t);
  p->fed[name] = true;
  return 0;
}

int ptp_predictor_run(void* h) {
  auto* p = static_cast<ptp::Predictor*>(h);
  if (!p->load_ok) return 1;  // load failed; err holds the load error
  return p->run() ? 0 : 1;
}

int ptp_predictor_num_outputs(void* h) {
  return static_cast<int>(static_cast<ptp::Predictor*>(h)->fetch_names.size());
}

// Returns numel; fills shape (up to max_ndim) and *ndim.  Call with
// data=nullptr first to size the buffer.
int64_t ptp_predictor_get_output(void* h, int idx, float* data,
                                 int64_t* shape, int* ndim, int max_ndim) {
  auto* p = static_cast<ptp::Predictor*>(h);
  const std::string& name = p->fetch_names[idx];
  auto it = p->vars.find(name);
  if (it == p->vars.end()) return -1;
  const ptp::Tensor& t = it->second;
  *ndim = static_cast<int>(t.shape.size());
  for (int i = 0; i < *ndim && i < max_ndim; ++i) shape[i] = t.shape[i];
  if (data) {
    if (t.is_int) {
      // integral fetches come back as floats (the fp32 C API surface) —
      // converted, never an uninitialized buffer
      for (size_t k = 0; k < t.i.size(); ++k)
        data[k] = static_cast<float>(t.i[k]);
    } else {
      std::copy(t.f.begin(), t.f.end(), data);
    }
  }
  return t.numel();
}

void ptp_predictor_destroy(void* h) { delete static_cast<ptp::Predictor*>(h); }

}  // extern "C"

#ifdef PTP_MAIN
// Standalone CLI: predictor_demo <model_dir> <input_name:input.npy> ...
// Prints each fetch as "name shape: v0 v1 ..." — the demo_trainer.cc
// deployment analog (inference; training stays on the XLA path).
#include <cstdio>
int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <name:input.npy>...\n", argv[0]);
    return 2;
  }
  ptp::Predictor p;
  if (!p.load(argv[1])) {
    fprintf(stderr, "load: %s\n", p.err.c_str());
    return 1;
  }
  for (int a = 2; a < argc; ++a) {
    std::string arg = argv[a];
    auto colon = arg.find(':');
    std::string name = arg.substr(0, colon), path = arg.substr(colon + 1);
    ptp::Tensor t;
    std::string e;
    if (!ptp::read_npy(path, &t, &e)) {
      fprintf(stderr, "input: %s\n", e.c_str());
      return 1;
    }
    p.vars[name] = std::move(t);
    p.fed[name] = true;  // run()'s stale-var sweep keeps only fed+persistable
  }
  if (!p.run()) {
    fprintf(stderr, "run: %s\n", p.err.c_str());
    return 1;
  }
  for (auto& name : p.fetch_names) {
    const ptp::Tensor& t = p.vars[name];
    printf("%s [", name.c_str());
    for (size_t i = 0; i < t.shape.size(); ++i)
      printf("%s%lld", i ? "," : "", static_cast<long long>(t.shape[i]));
    printf("]:");
    for (int64_t i = 0; i < t.numel() && i < 16; ++i) printf(" %g", t.f[i]);
    printf("\n");
  }
  return 0;
}
#endif
