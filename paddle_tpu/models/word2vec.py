"""word2vec (skip-gram-ish N-gram LM) — reference book test:
python/paddle/fluid/tests/book/test_word2vec.py.
"""
from __future__ import annotations

from paddle_tpu import ParamAttr, layers

__all__ = ["word2vec_ngram"]


def word2vec_ngram(word_ids, next_word, dict_size: int, embed_size: int = 32, hidden_size: int = 256):
    """N-gram next-word predictor; ``word_ids`` is a list of int64 [N, 1]
    context-word vars sharing one embedding table.  Returns (avg_loss,
    prediction)."""
    embeds = [
        layers.embedding(
            w,
            size=[dict_size, embed_size],
            param_attr=ParamAttr(name="shared_w"),
        )
        for w in word_ids
    ]
    concat = layers.concat(embeds, axis=-1)
    hidden = layers.fc(concat, size=hidden_size, act="sigmoid")
    prediction = layers.fc(hidden, size=dict_size, act="softmax")
    loss = layers.cross_entropy(prediction, next_word)
    return layers.mean(loss), prediction
