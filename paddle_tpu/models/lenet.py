"""LeNet-5 MNIST classifier.

Reference: python/paddle/fluid/tests/book/test_recognize_digits.py:90-117
(the `conv_net` variant). The BASELINE.md "MNIST LeNet" config.
"""
from __future__ import annotations

from paddle_tpu import layers

__all__ = ["lenet5"]


def lenet5(images, labels, class_num: int = 10):
    """Build LeNet-5; returns (avg_loss, accuracy, prediction).

    ``images``: [N, 1, 28, 28] float32; ``labels``: [N, 1] int64.
    """
    conv1 = layers.conv2d(images, num_filters=20, filter_size=5, act="relu")
    pool1 = layers.pool2d(conv1, pool_size=2, pool_stride=2, pool_type="max")
    conv2 = layers.conv2d(pool1, num_filters=50, filter_size=5, act="relu")
    pool2 = layers.pool2d(conv2, pool_size=2, pool_stride=2, pool_type="max")
    hidden = layers.fc(pool2, size=500, act="relu", num_flatten_dims=1)
    prediction = layers.fc(hidden, size=class_num, act="softmax")
    loss = layers.cross_entropy(prediction, labels)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(prediction, labels)
    return avg_loss, acc, prediction
