"""DeepFM CTR model — the sparse/high-dim-lookup benchmark family
(BASELINE.md "DeepFM / Wide&Deep"; reference serves this class of model via
the distributed lookup table + PSLib path, SURVEY.md §2.10).

TPU design: the embedding table is a dense HBM gather; at scale the table
shards over the ``ep`` mesh axis (parallel/auto_shard.py maps
``*_fm_emb``/``*_deep_emb`` tables onto ``ep``).
"""
from __future__ import annotations

from paddle_tpu import ParamAttr, layers

__all__ = ["deepfm_ctr"]


def deepfm_ctr(
    feat_ids,
    feat_vals,
    labels,
    num_features: int = 100000,
    num_fields: int = 39,
    embed_dim: int = 8,
    deep_layers=(400, 400, 400),
    name: str = "deepfm",
    distributed_emb: bool = False,
):
    """feat_ids: int64 [N, F, 1]; feat_vals: float32 [N, F]; labels [N, 1].

    ``distributed_emb=True`` serves both tables from the parameter server
    (huge-vocab CTR where the tables exceed HBM — BASELINE.md DeepFM;
    feat_ids must be a feed, bind via
    distributed.bind_distributed_tables).

    Returns (avg_loss, auc_prob) where auc_prob is the CTR probability.
    """
    vals = layers.reshape(feat_vals, shape=[0, num_fields, 1])
    emb_kw = dict(is_sparse=True, is_distributed=True) if distributed_emb else {}
    # distributed mode looks up the raw [N, F, 1] feed ids (prefetch needs
    # the feed var); dense mode drops the trailing 1 first
    ids_in = feat_ids if distributed_emb else layers.reshape(feat_ids, shape=[0, num_fields])

    # ---- first-order (wide) term: sum_f w_id(f) * val(f)
    w1 = layers.embedding(
        ids_in,
        size=[num_features, 1],
        param_attr=ParamAttr(name=name + "_w1_emb"),
        **emb_kw,
    )  # [N, F, 1]
    first = layers.reduce_sum(w1 * vals, dim=[1])  # [N, 1]

    # ---- second-order FM term over [N, F, K] embeddings
    emb = layers.embedding(
        ids_in,
        size=[num_features, embed_dim],
        param_attr=ParamAttr(name=name + "_fm_emb"),
        **emb_kw,
    )  # [N, F, K]
    xv = emb * vals
    sum_sq = layers.square(layers.reduce_sum(xv, dim=[1]))  # [N, K]
    sq_sum = layers.reduce_sum(layers.square(xv), dim=[1])  # [N, K]
    second = layers.scale(layers.reduce_sum(sum_sq - sq_sum, dim=[1], keep_dim=True), scale=0.5)

    # ---- deep tower over flattened embeddings
    deep = layers.reshape(xv, shape=[0, num_fields * embed_dim])
    for i, width in enumerate(deep_layers):
        deep = layers.fc(deep, size=width, act="relu", param_attr=ParamAttr(name="%s_deep_fc%d_w" % (name, i)))
    deep_out = layers.fc(deep, size=1, param_attr=ParamAttr(name=name + "_deep_out_w"))

    logits = first + second + deep_out
    loss = layers.sigmoid_cross_entropy_with_logits(logits, layers.cast(labels, "float32"))
    prob = layers.sigmoid(logits)
    return layers.mean(loss), prob
