"""Transformer NMT (seq2seq) — the BASELINE.md "Transformer NMT" config.

Reference model family: python/paddle/fluid/tests/unittests/
dist_transformer.py and book test test_machine_translation.py (attention
seq2seq).  Variable-length sentence pairs use bucketed padding + masks
(the LoDTensor-equivalent; SURVEY.md §5 long-context notes), not ragged
LoD — masks feed both the encoder self-attention and the loss.

Decoding (greedy/beam) lives in paddle_tpu/decoding.py.
"""
from __future__ import annotations

from paddle_tpu import ParamAttr, layers
from paddle_tpu.models.transformer import (
    _causal_bias,
    _embeddings,
    _fc3,
    encoder_layer,
    multi_head_attention,
    positionwise_ffn,
)

__all__ = ["transformer_nmt", "decoder_layer"]


def decoder_layer(
    x,
    enc_out,
    d_model,
    n_head,
    d_inner,
    self_bias=None,
    cross_bias=None,
    dropout_rate: float = 0.0,
    is_test: bool = False,
    name: str = "dec_0",
):
    """Decoder block: causal self-attention + cross-attention + FFN."""
    att = multi_head_attention(
        x, x, d_model, n_head, dropout_rate, self_bias, is_test, name=name + "_self"
    )
    x = layers.layer_norm(
        x + att, begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_ln1_scale"),
        bias_attr=ParamAttr(name=name + "_ln1_bias"),
    )
    cross = multi_head_attention(
        x, enc_out, d_model, n_head, dropout_rate, cross_bias, is_test, name=name + "_cross"
    )
    x = layers.layer_norm(
        x + cross, begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_ln2_scale"),
        bias_attr=ParamAttr(name=name + "_ln2_bias"),
    )
    ffn = positionwise_ffn(x, d_model, d_inner, name + "_ffn", is_test=is_test, dropout_rate=dropout_rate)
    return layers.layer_norm(
        x + ffn, begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_ln3_scale"),
        bias_attr=ParamAttr(name=name + "_ln3_bias"),
    )


def transformer_nmt(
    src_ids,
    tgt_ids,
    labels=None,
    src_mask=None,
    src_vocab: int = 1000,
    tgt_vocab: int = 1000,
    d_model: int = 64,
    n_layer: int = 2,
    n_head: int = 4,
    d_inner: int = 128,
    src_len: int = 16,
    tgt_len: int = 16,
    dropout_rate: float = 0.0,
    is_test: bool = False,
    name: str = "nmt",
):
    """Returns (avg_loss or None, logits [N, tgt_len, tgt_vocab]).

    src_ids [N, src_len] int64; tgt_ids [N, tgt_len] (decoder input, BOS-
    shifted); labels [N, tgt_len, 1]; src_mask float [N, src_len] 1=token.
    """
    enc = _embeddings(src_ids, src_vocab, d_model, src_len, src_len, name + "_src")
    enc_bias = None
    cross_bias = None
    if src_mask is not None:
        m = layers.reshape(src_mask, shape=[-1, 1, 1, src_len])
        enc_bias = layers.scale(m, scale=1e9, bias=-1e9)  # (m-1)*1e9
        cross_bias = enc_bias
    for i in range(n_layer):
        enc = encoder_layer(
            enc, d_model, n_head, d_inner, enc_bias, dropout_rate, is_test,
            name="%s_enc_%d" % (name, i),
        )

    dec = _embeddings(tgt_ids, tgt_vocab, d_model, tgt_len, tgt_len, name + "_tgt")
    causal = _causal_bias(tgt_len, dec.dtype)
    for i in range(n_layer):
        dec = decoder_layer(
            dec, enc, d_model, n_head, d_inner, causal, cross_bias,
            dropout_rate, is_test, name="%s_dec_%d" % (name, i),
        )
    logits = _fc3(dec, tgt_vocab, name + "_head")
    if labels is None:
        return None, logits
    loss = layers.softmax_with_cross_entropy(logits, labels)
    avg_loss = layers.mean(loss)
    return avg_loss, logits
