"""Transformer family: BERT-style encoder and a decoder-only LM.

Reference model family: the reference ships transformer NMT as a dist test
model (python/paddle/fluid/tests/unittests/dist_transformer.py) built from
the same primitives used here (layers/nn.py fc/matmul/softmax/layer_norm).
This is the flagship for the multi-chip shardings: parameters get stable
names (``enc_<i>_...``) so `paddle_tpu.parallel` sharding rules can map
attention/FFN weights onto the ``tp`` axis (Megatron-style column/row
parallel) and activations onto ``sp``/``dp`` — see
parallel/auto_shard.py.

TPU notes: everything is static-shape [batch, seq_len]; variable-length
text uses bucketed padding + the input mask (the LoDTensor analog — see
SURVEY.md §5 long-context notes).
"""
from __future__ import annotations

import numpy as np

from paddle_tpu import ParamAttr, layers

__all__ = ["multi_head_attention", "encoder_layer", "bert_encoder", "bert_pretrain", "transformer_lm"]


def _fc3(x, size, name, num_flatten_dims=2, act=None):
    return layers.fc(
        x,
        size=size,
        num_flatten_dims=num_flatten_dims,
        param_attr=ParamAttr(name=name + "_w"),
        bias_attr=ParamAttr(name=name + "_b"),
        act=act,
    )


def multi_head_attention(
    q_in,
    kv_in,
    d_model: int,
    n_head: int,
    dropout_rate: float = 0.1,
    attn_bias=None,
    is_test: bool = False,
    name: str = "att",
    fused: bool = False,
    mask=None,
    causal: bool = False,
):
    """Scaled-dot-product multi-head attention over [N, S, d_model].

    Default path: q/k/v projections, [N, H, S, D] batched matmuls
    (MXU-shaped), optional additive ``attn_bias`` ([S, S] causal or
    [N, 1, 1, S] padding mask, broadcast into the logits), softmax, and
    the output projection.

    ``fused=True`` (needs dropout_rate==0 inside attention): the
    ``fused_attention`` op, with padding as ``mask`` [N, S] and
    causality as ``causal=`` instead of a materialized ``attn_bias``.
    That op defaults to XLA's native fused attention (measured faster
    at every S that fits HBM); set ``PADDLE_TPU_FLASH_ATTENTION=1`` for
    the pallas flash kernel when S^2 score tensors would exceed HBM
    (see the op docstring / BASELINE.md round-5 A/B table).
    """
    d_head = d_model // n_head
    q = _fc3(q_in, d_model, name + "_q")
    k = _fc3(kv_in, d_model, name + "_k")
    v = _fc3(kv_in, d_model, name + "_v")

    def split_heads(x):
        # [N, S, d_model] -> [N, H, S, D]
        x = layers.reshape(x, shape=[0, 0, n_head, d_head])
        return layers.transpose(x, perm=[0, 2, 1, 3])

    q, k, v = split_heads(q), split_heads(k), split_heads(v)
    if fused:
        if dropout_rate:
            raise ValueError(
                "fused attention has no in-kernel dropout; build with "
                "dropout_rate=0 (the reference's inference/pretrain-bench "
                "configs) or fused=False"
            )
        if attn_bias is not None:
            raise ValueError(
                "fused attention takes mask=/causal= instead of a "
                "materialized attn_bias"
            )
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(name + "_fused")
        ctx = helper.create_variable_for_type_inference(q.dtype)
        ins = {"Q": [q], "K": [k], "V": [v]}
        if mask is not None:
            ins["Mask"] = [mask]
        helper.append_op(
            type="fused_attention", inputs=ins, outputs={"Out": [ctx]},
            attrs={"causal": bool(causal),
                   "scale": 1.0 / float(np.sqrt(d_head))},
        )
    else:
        if mask is not None or causal:
            raise ValueError(
                "mask=/causal= are the fused-path inputs; the unfused path "
                "takes a materialized attn_bias (silently ignoring them "
                "would drop the masking)"
            )
        scores = layers.matmul(q, k, transpose_y=True, alpha=1.0 / float(np.sqrt(d_head)))
        if attn_bias is not None:
            scores = scores + attn_bias
        weights = layers.softmax(scores)
        if dropout_rate:
            weights = layers.dropout(weights, dropout_prob=dropout_rate, is_test=is_test)
        ctx = layers.matmul(weights, v)  # [N, H, S, D]
    ctx = layers.transpose(ctx, perm=[0, 2, 1, 3])
    ctx = layers.reshape(ctx, shape=[0, 0, d_model])
    return _fc3(ctx, d_model, name + "_out")


def positionwise_ffn(x, d_model, d_inner, name, act="gelu", is_test=False, dropout_rate=0.1):
    hidden = _fc3(x, d_inner, name + "_fc0", act=act)
    if dropout_rate:
        hidden = layers.dropout(hidden, dropout_prob=dropout_rate, is_test=is_test)
    return _fc3(hidden, d_model, name + "_fc1")


def encoder_layer(
    x,
    d_model,
    n_head,
    d_inner,
    attn_bias=None,
    dropout_rate: float = 0.1,
    is_test: bool = False,
    name: str = "enc_0",
    fused: bool = False,
    mask=None,
    causal: bool = False,
):
    """Post-LN transformer block (attention + FFN, residuals)."""
    att = multi_head_attention(
        x, x, d_model, n_head, dropout_rate, attn_bias, is_test,
        name=name + "_att", fused=fused, mask=mask, causal=causal,
    )
    if dropout_rate:
        att = layers.dropout(att, dropout_prob=dropout_rate, is_test=is_test)
    x = layers.layer_norm(
        x + att,
        begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_ln1_scale"),
        bias_attr=ParamAttr(name=name + "_ln1_bias"),
    )
    ffn = positionwise_ffn(x, d_model, d_inner, name + "_ffn", is_test=is_test, dropout_rate=dropout_rate)
    if dropout_rate:
        ffn = layers.dropout(ffn, dropout_prob=dropout_rate, is_test=is_test)
    return layers.layer_norm(
        x + ffn,
        begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_ln2_scale"),
        bias_attr=ParamAttr(name=name + "_ln2_bias"),
    )


def _causal_bias(seq_len: int, dtype="float32"):
    """[S, S] additive bias: 0 on/below diagonal, -1e9 above."""
    r = layers.range(0, seq_len, 1, "int32")
    rows = layers.reshape(r, shape=[seq_len, 1])
    cols = layers.reshape(r, shape=[1, seq_len])
    allowed = layers.cast(layers.less_equal(cols, rows), dtype)
    return (allowed - 1.0) * 1e9


def _embeddings(ids, vocab_size, d_model, max_pos, seq_len, name, extra_ids=None, extra_vocab=0):
    emb = layers.embedding(
        ids, size=[vocab_size, d_model], param_attr=ParamAttr(name=name + "_word_emb")
    )
    pos = layers.range(0, seq_len, 1, "int64")
    pos = layers.reshape(pos, shape=[1, seq_len])
    pos_emb = layers.embedding(
        pos, size=[max_pos, d_model], param_attr=ParamAttr(name=name + "_pos_emb")
    )
    out = emb + pos_emb
    if extra_ids is not None:
        out = out + layers.embedding(
            extra_ids, size=[extra_vocab, d_model], param_attr=ParamAttr(name=name + "_sent_emb")
        )
    return out


def bert_encoder(
    src_ids,
    input_mask=None,
    sent_ids=None,
    vocab_size: int = 30522,
    d_model: int = 768,
    n_layer: int = 12,
    n_head: int = 12,
    d_inner: int = 3072,
    max_pos: int = 512,
    seq_len: int = 128,
    dropout_rate: float = 0.1,
    is_test: bool = False,
    name: str = "bert",
    fused_attention: bool = False,
):
    """BERT-base encoder; returns the [N, S, d_model] sequence output.

    ``input_mask``: float [N, S] (1 = token, 0 = pad) -> additive bias
    (or the ``Mask`` input of the fused_attention op when
    ``fused_attention=True``; that op picks XLA-native vs pallas flash
    via PADDLE_TPU_FLASH_ATTENTION — see its docstring).
    """
    x = _embeddings(src_ids, vocab_size, d_model, max_pos, seq_len, name, sent_ids, 2)
    x = layers.layer_norm(
        x,
        begin_norm_axis=2,
        param_attr=ParamAttr(name=name + "_emb_ln_scale"),
        bias_attr=ParamAttr(name=name + "_emb_ln_bias"),
    )
    if dropout_rate:
        x = layers.dropout(x, dropout_prob=dropout_rate, is_test=is_test)
    attn_bias = None
    if input_mask is not None and not fused_attention:
        m = layers.reshape(input_mask, shape=[-1, 1, 1, seq_len])
        attn_bias = layers.scale(m, scale=1e9, bias=-1e9)  # (m-1)*1e9
    for i in range(n_layer):
        x = encoder_layer(
            x, d_model, n_head, d_inner, attn_bias, dropout_rate, is_test,
            name="%s_enc_%d" % (name, i), fused=fused_attention,
            mask=input_mask if fused_attention else None,
        )
    return x


def transformer_lm(
    src_ids,
    labels,
    vocab_size: int = 32000,
    d_model: int = 512,
    n_layer: int = 6,
    n_head: int = 8,
    d_inner: int = 2048,
    seq_len: int = 256,
    max_pos: int = 2048,
    dropout_rate: float = 0.0,
    is_test: bool = False,
    name: str = "lm",
    fused_attention: bool = False,
):
    """Decoder-only causal LM; returns (avg_loss, logits).

    src_ids/labels: int64 [N, S] / [N, S, 1].

    ``fused_attention=True`` (needs dropout_rate=0): causality goes in
    as the fused op's ``causal=`` attr instead of a materialized [S, S]
    bias — the build the sequence-parallel (sp) serving layout needs,
    since only the fused op can dispatch to ring attention (no S^2
    tensor may exist for the seq axis to shard).
    """
    x = _embeddings(src_ids, vocab_size, d_model, max_pos, seq_len, name)
    causal = None if fused_attention else _causal_bias(seq_len, x.dtype)
    for i in range(n_layer):
        x = encoder_layer(
            x, d_model, n_head, d_inner, causal, dropout_rate, is_test,
            name="%s_dec_%d" % (name, i), fused=fused_attention,
            causal=fused_attention,
        )
    logits = _fc3(x, vocab_size, name + "_head")
    if labels is None:  # inference/decoding program: logits only
        return None, logits
    loss = layers.softmax_with_cross_entropy(logits, labels)
    avg_loss = layers.mean(loss)
    return avg_loss, logits


def bert_pretrain(
    src_ids,
    sent_ids,
    input_mask,
    mask_pos,
    mask_labels,
    nsp_labels,
    vocab_size: int = 30522,
    d_model: int = 768,
    n_layer: int = 12,
    n_head: int = 12,
    d_inner: int = 3072,
    max_pos: int = 512,
    seq_len: int = 128,
    dropout_rate: float = 0.1,
    is_test: bool = False,
    name: str = "bert",
    fused_attention: bool = False,
):
    """BERT pretraining objective: masked-LM + next-sentence prediction
    (BASELINE.json flagship config 3; reference model family:
    ERNIE/BERT-on-fluid pretraining — the fluid repo itself ships only
    the encoder blocks, so heads follow the original BERT recipe).

    src_ids/sent_ids: int64 [N, S]; input_mask: float [N, S];
    mask_pos: int64 [N*M, 1] FLATTENED positions into [N*S];
    mask_labels: int64 [N*M, 1]; nsp_labels: int64 [N, 1].
    Returns (total_loss, mlm_loss, nsp_acc).
    """
    enc = bert_encoder(
        src_ids, input_mask, sent_ids, vocab_size, d_model, n_layer, n_head,
        d_inner, max_pos, seq_len, dropout_rate, is_test, name,
        fused_attention=fused_attention,
    )  # [N, S, D]

    # ---- masked LM head over gathered positions
    flat = layers.reshape(enc, shape=[-1, d_model])          # [N*S, D]
    picked = layers.gather(flat, layers.reshape(mask_pos, shape=[-1]))  # [N*M, D]
    trans = _fc3(picked, d_model, name + "_mlm_trans", num_flatten_dims=1, act="gelu")
    trans = layers.layer_norm(
        trans, begin_norm_axis=1,
        param_attr=ParamAttr(name=name + "_mlm_ln_scale"),
        bias_attr=ParamAttr(name=name + "_mlm_ln_bias"),
    )
    # output projection TIED to the word embedding (original BERT recipe)
    word_emb = enc.block.program.global_block().var(name + "_word_emb")
    mlm_logits = layers.matmul(trans, word_emb, transpose_y=True)  # [N*M, V]
    mlm_bias = layers.create_parameter([vocab_size], "float32",
                                       name=name + "_mlm_out_b", is_bias=True)
    mlm_logits = mlm_logits + mlm_bias
    mlm_loss = layers.mean(layers.softmax_with_cross_entropy(mlm_logits, mask_labels))

    # ---- next-sentence head on the [CLS] (first) token
    first = layers.slice(enc, axes=[1], starts=[0], ends=[1])   # [N, 1, D]
    pooled = _fc3(layers.reshape(first, shape=[-1, d_model]), d_model,
                  name + "_pool", num_flatten_dims=1, act="tanh")
    nsp_logits = _fc3(pooled, 2, name + "_nsp", num_flatten_dims=1)
    nsp_loss = layers.mean(layers.softmax_with_cross_entropy(nsp_logits, nsp_labels))
    nsp_acc = layers.accuracy(nsp_logits, nsp_labels)

    total = mlm_loss + nsp_loss
    return total, mlm_loss, nsp_acc
