"""VGG-16 — the reference's float16 benchmark model
(paddle/contrib/float16/float16_benchmark.md:21-33; book test
test_image_classification.py vgg16_bn_drop).
"""
from __future__ import annotations

from paddle_tpu import layers

__all__ = ["vgg16"]


def _conv_block(x, num_filter, groups, is_test=False):
    for _ in range(groups):
        x = layers.conv2d(x, num_filters=num_filter, filter_size=3, padding=1, act=None, bias_attr=False)
        x = layers.batch_norm(x, act="relu", is_test=is_test)
    return layers.pool2d(x, pool_size=2, pool_stride=2, pool_type="max")


def vgg16(images, labels, class_num: int = 1000, is_test: bool = False, dropout: bool = True):
    """Returns (avg_loss, accuracy, prediction). images: [N,3,H,W]."""
    x = _conv_block(images, 64, 2, is_test)
    x = _conv_block(x, 128, 2, is_test)
    x = _conv_block(x, 256, 3, is_test)
    x = _conv_block(x, 512, 3, is_test)
    x = _conv_block(x, 512, 3, is_test)

    if dropout:
        x = layers.dropout(x, dropout_prob=0.5, is_test=is_test)
    fc1 = layers.fc(x, size=4096, act=None)
    x = layers.batch_norm(fc1, act="relu", is_test=is_test)
    if dropout:
        x = layers.dropout(x, dropout_prob=0.5, is_test=is_test)
    fc2 = layers.fc(x, size=4096, act="relu")
    prediction = layers.fc(fc2, size=class_num, act="softmax")
    loss = layers.cross_entropy(prediction, labels)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(prediction, labels)
    return avg_loss, acc, prediction
