"""ResNet for ImageNet — the benchmark flagship (BASELINE.json north star:
ResNet-50 images/sec/chip + MFU on a v5e-16 mesh).

Reference model family: python/paddle/fluid/tests/book/
test_image_classification.py (resnet_cifar10) and the float16 benchmark's
ResNet-50 (paddle/contrib/float16/float16_benchmark.md:40-52).

TPU notes: NCHW layout is the API-surface default for reference parity;
``data_format="NHWC"`` runs the whole network channels-last (the layout
TPUs prefer — bench.py's BENCH_LAYOUT knob probes both).  Use bf16 via
the AMP decorator (contrib/mixed_precision) for benchmark runs.
"""
from __future__ import annotations

from paddle_tpu import layers

__all__ = ["resnet", "resnet50", "resnet18"]

_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def _conv_bn(x, num_filters, filter_size, stride=1, act=None, is_test=False,
             fmt="NCHW"):
    conv = layers.conv2d(
        x,
        num_filters=num_filters,
        filter_size=filter_size,
        stride=stride,
        padding=(filter_size - 1) // 2,
        bias_attr=False,
        data_format=fmt,
    )
    return layers.batch_norm(conv, act=act, is_test=is_test, data_layout=fmt)


def _channels(x, fmt):
    return x.shape[1] if fmt == "NCHW" else x.shape[-1]


def _shortcut(x, out_ch, stride, is_test, fmt):
    if _channels(x, fmt) != out_ch or stride != 1:
        return _conv_bn(x, out_ch, 1, stride, is_test=is_test, fmt=fmt)
    return x


def _basic_block(x, num_filters, stride, is_test, fmt):
    conv0 = _conv_bn(x, num_filters, 3, stride, act="relu", is_test=is_test, fmt=fmt)
    conv1 = _conv_bn(conv0, num_filters, 3, 1, is_test=is_test, fmt=fmt)
    short = _shortcut(x, num_filters, stride, is_test, fmt)
    return layers.relu(short + conv1)


def _bottleneck_block(x, num_filters, stride, is_test, fmt):
    conv0 = _conv_bn(x, num_filters, 1, act="relu", is_test=is_test, fmt=fmt)
    conv1 = _conv_bn(conv0, num_filters, 3, stride, act="relu", is_test=is_test, fmt=fmt)
    conv2 = _conv_bn(conv1, num_filters * 4, 1, is_test=is_test, fmt=fmt)
    short = _shortcut(x, num_filters * 4, stride, is_test, fmt)
    return layers.relu(short + conv2)


def resnet(images, labels, depth: int = 50, class_num: int = 1000,
           is_test: bool = False, data_format: str = "NCHW"):
    """Returns (avg_loss, accuracy, prediction).

    images: [N, 3, H, W] (NCHW) or [N, H, W, 3] (data_format="NHWC").
    """
    block_kind, stages = _DEPTH_CFG[depth]
    block_fn = _basic_block if block_kind == "basic" else _bottleneck_block
    fmt = data_format

    x = _conv_bn(images, 64, 7, stride=2, act="relu", is_test=is_test, fmt=fmt)
    x = layers.pool2d(x, pool_size=3, pool_stride=2, pool_padding=1,
                      pool_type="max", data_format=fmt)
    num_filters = [64, 128, 256, 512]
    for stage, blocks in enumerate(stages):
        for i in range(blocks):
            stride = 2 if i == 0 and stage > 0 else 1
            x = block_fn(x, num_filters[stage], stride, is_test, fmt)
    pool = layers.pool2d(x, pool_type="avg", global_pooling=True,
                         data_format=fmt)
    prediction = layers.fc(pool, size=class_num, act="softmax")
    loss = layers.cross_entropy(prediction, labels)
    avg_loss = layers.mean(loss)
    acc = layers.accuracy(prediction, labels)
    return avg_loss, acc, prediction


def resnet50(images, labels, class_num: int = 1000, is_test: bool = False,
             data_format: str = "NCHW"):
    return resnet(images, labels, depth=50, class_num=class_num,
                  is_test=is_test, data_format=data_format)


def resnet18(images, labels, class_num: int = 1000, is_test: bool = False,
             data_format: str = "NCHW"):
    return resnet(images, labels, depth=18, class_num=class_num,
                  is_test=is_test, data_format=data_format)
