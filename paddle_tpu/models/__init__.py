"""Model zoo: fluid-style program builders for the reference's book-test
model families (reference: python/paddle/fluid/tests/book/) plus the
benchmark flagships (ResNet-50, BERT/Transformer).

Each builder appends ops to the current default_main_program (use
``framework.program_guard``) and returns the key output Variables.
"""
from paddle_tpu.models import lenet, resnet, vgg, transformer, word2vec, deepfm, seq2seq  # noqa: F401
from paddle_tpu.models.lenet import lenet5  # noqa: F401
from paddle_tpu.models.resnet import resnet50  # noqa: F401
from paddle_tpu.models.vgg import vgg16  # noqa: F401
from paddle_tpu.models.transformer import bert_encoder, bert_pretrain, transformer_lm  # noqa: F401
from paddle_tpu.models.deepfm import deepfm_ctr  # noqa: F401
