"""Executor: compiles a Program block into one jitted XLA module and runs it.

Reference: paddle/fluid/framework/executor.cc:175 (interpret ops one by one)
and python/paddle/fluid/executor.py:295.  The TPU-native design instead:

* the whole block (forward + backward + optimizer ops) lowers to a single
  XLA computation (core/lowering.py) — the reference's per-op dispatch,
  garbage collector (garbage_collector.h), and memory-reuse passes are
  subsumed by XLA buffer assignment;
* persistable vars are functional state, donated so parameter updates are
  in-place in HBM;
* compiled executables are cached by (program uid+version+op count, feed
  signature, fetch list, steps) — the per-shape compile cache that stands
  in for the reference's ExecutorPrepareContext caching (executor.cc:351);
  the per-run block analysis itself is cached too (_RunPlan), so a
  steady-state run() is plan lookup -> feed coercion -> jitted call.

Data-parallel/sharded execution: pass a CompiledProgram (see
paddle_tpu/parallel/compiled_program.py); the executor consults it for a
device mesh and sharding specs and jits with those in/out shardings —
XLA GSPMD then inserts the all-reduces that the reference built manually
via ParallelExecutor + NCCL op-handles (parallel_executor.cc:356).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import framework
from paddle_tpu import faults as _faults
from paddle_tpu.core import lowering
from paddle_tpu.core import types as core_types
from paddle_tpu.monitor import events as _mon_events
from paddle_tpu.monitor import registry as _mon_registry
from paddle_tpu.monitor import spans as _mon_spans
from paddle_tpu.monitor import train as _mon_train
from paddle_tpu.scope import Scope, global_scope

__all__ = ["Executor", "AsyncExecutor"]

# run-phase observability (paddle_tpu/monitor).  The jit hit/miss/run
# counters are COLLECT-ON-READ: every Executor's ``_cache_stats`` dict
# registers here at construction and the registry sums them when a
# consumer scrapes, so the run() hot path pays nothing beyond the dict
# increments it already did (a locked registry counter costs ~1.5us per
# inc — real money against a ~200us cached dispatch).  The per-phase
# spans gate on _mon_spans.recording(), one flag check each when no
# trace session is active.
import threading as _threading
import weakref as _weakref

_exec_stats_lock = _threading.Lock()
_exec_stats: List[Dict[str, int]] = []  # one _cache_stats dict per LIVE Executor
_exec_retired = {
    "hits": 0, "misses": 0, "runs": 0,
    "plan_hits": 0, "plan_misses": 0, "dispatch_overhead_s": 0.0,
    "plan_evictions": 0, "jit_evictions": 0,
    "ps_pull_overlap_s": 0.0, "ps_pull_wait_s": 0.0,
}  # folded-in dead executors


def _retire_exec_stats(stats: Dict[str, int]) -> None:
    # weakref.finalize callback: fold a dead executor's totals into the
    # retired base so the counters stay monotonic without pinning every
    # stats dict (and paying O(all-executors-ever) per scrape) forever
    with _exec_stats_lock:
        try:
            _exec_stats.remove(stats)
        except ValueError:
            return
        for k in _exec_retired:
            _exec_retired[k] += stats.get(k, 0)


def _sum_exec_stats(key: str) -> int:
    with _exec_stats_lock:
        return _exec_retired[key] + sum(d.get(key, 0) for d in _exec_stats)


_mon_registry.REGISTRY.counter_callback(
    "executor_runs_total", "Executor.run invocations (all executors)",
    fn=lambda: _sum_exec_stats("runs"))
_mon_registry.REGISTRY.counter_callback(
    "executor_jit_cache_hits_total",
    "runs served by an existing compiled entry",
    fn=lambda: _sum_exec_stats("hits"))
_mon_registry.REGISTRY.counter_callback(
    "executor_jit_cache_misses_total",
    "newly built jitted entries (an XLA compile on first dispatch)",
    fn=lambda: _sum_exec_stats("misses"))
_mon_registry.REGISTRY.counter_callback(
    "executor_plan_cache_hits_total",
    "runs served by a cached run plan (no per-run block re-analysis)",
    fn=lambda: _sum_exec_stats("plan_hits"))
_mon_registry.REGISTRY.counter_callback(
    "executor_plan_cache_misses_total",
    "run-plan builds (an O(n_ops) dataflow analysis each)",
    fn=lambda: _sum_exec_stats("plan_misses"))
_mon_registry.REGISTRY.counter_callback(
    "executor_dispatch_overhead_seconds_total",
    "host-side run() seconds spent before the jitted dispatch",
    fn=lambda: _sum_exec_stats("dispatch_overhead_s"))
_mon_registry.REGISTRY.counter_callback(
    "executor_plan_cache_evictions_total",
    "run plans evicted by the LRU capacity bound",
    fn=lambda: _sum_exec_stats("plan_evictions"))
_mon_registry.REGISTRY.counter_callback(
    "executor_jit_cache_evictions_total",
    "compiled jit entries evicted by the LRU capacity bound",
    fn=lambda: _sum_exec_stats("jit_evictions"))
_mon_registry.REGISTRY.counter_callback(
    "executor_ps_pull_overlap_seconds_total",
    "dense-PS pull seconds hidden behind device compute (overlapped "
    "pull thread; train_from_dataset async mode)",
    fn=lambda: _sum_exec_stats("ps_pull_overlap_s"))
_mon_registry.REGISTRY.counter_callback(
    "executor_ps_pull_wait_seconds_total",
    "seconds run() blocked joining the overlapped dense-PS pull (the "
    "NOT-hidden remainder of the pull latency)",
    fn=lambda: _sum_exec_stats("ps_pull_wait_s"))
# per-run distribution, observed only while a trace session is active —
# a histogram observe is a lock + bucket scan (~2us), real money on a
# hot path whose whole budget is "almost nothing"; the always-on totals
# live in the callback counters above
_MON_DISPATCH_HIST = _mon_registry.REGISTRY.histogram(
    "executor_dispatch_overhead_seconds",
    "per-run host dispatch overhead (recorded under trace sessions)")
# per-step train-loop distribution — always on (a train step is ms-scale
# against a ~2us observe) with the epoch's trace id pinned as an
# OpenMetrics exemplar, the same linkage mechanism as
# serving_request_latency_seconds: a slow step surfaced in /trainz
# points straight at its flight-recorded span tree
_MON_TRAIN_STEP_HIST = _mon_registry.REGISTRY.histogram(
    "executor_train_step_seconds",
    "per-step train_from_dataset wall time (exemplar: epoch trace id)")


def _as_fetch_name(f) -> str:
    return f.name if isinstance(f, framework.Variable) else str(f)


def pow2_id_bucket(n_unique: int) -> int:
    """The default sparse-prefetch unique-id bucket: the next power of
    two >= ``n_unique``, floored at 8.  THE one definition — the
    prefetch (``_sparse_expand_ids``), the id-ladder autotune's
    comparison baseline (``autotune._pow2_id_ladder``), and the bench's
    warmup-bucket computation all call it, so the bucketing can never
    drift between the runtime and the tools sized against it."""
    return max(8, 1 << max(0, int(n_unique) - 1).bit_length())


def _donate_kwargs(device) -> Dict[str, Any]:
    """Buffer-donation jit kwargs for ``device``.

    Donating the mutable state makes param updates in-place in HBM — the
    point of the design on TPU.  On the CPU backend it buys nothing AND
    is unsafe with jax's persistent compilation cache: an executable
    compiled with input-output aliasing and then RELOADED from the disk
    cache returns fetches that observe the in-place-mutated params
    (reproduced: a DynamicRNN+Adam module fetches its rnn output
    computed with POST-update weights on every warm-cache process;
    cold compiles are always correct).  So: donate everywhere except
    CPU — tests/test_dispatch_fastpath.py pins the kwargs policy and
    tests/test_donation_cache.py pins the HAZARD itself with a
    two-process shared-cache drill (re-enabling donation here makes
    the warm-cache process disagree with the cold one)."""
    if getattr(device, "platform", None) == "cpu":
        return {}
    return {"donate_argnums": (0,)}


class _RunPlan:
    """Hoisted per-(program, feed/fetch signature) block analysis.

    Everything ``run()`` used to recompute per call that only depends on
    the program STRUCTURE plus the feed/fetch name sets lives here: the
    persistable scan over ``program.list_vars()``, the read/written
    dataflow sets, the ``state_mut/ro/out`` tuples, the resolved fetch
    list (including the hidden PS/dense-grad fetch tails), and the
    per-feed dtype coercion table.  A steady-state run is then: plan
    lookup -> coerce feeds -> jitted call.  Keyed (see ``run``) by
    (program uid, version, op count, feed names, fetch names, steps,
    per_step_feed, backend, compiled uid); the op count guards against
    ops appended after a run without a version bump.
    """

    __slots__ = (
        "feed_names", "fetch_names", "n_dense_fetch",
        "state_mut", "state_ro", "state_out",
        "feed_np_dtypes", "feed_jax_dtypes",
    )

    def __init__(self, feed_names, fetch_names, n_dense_fetch,
                 state_mut, state_ro, state_out, feed_np_dtypes,
                 feed_jax_dtypes):
        self.feed_names = feed_names
        self.fetch_names = fetch_names
        self.n_dense_fetch = n_dense_fetch
        self.state_mut = state_mut
        self.state_ro = state_ro
        self.state_out = state_out
        self.feed_np_dtypes = feed_np_dtypes
        self.feed_jax_dtypes = feed_jax_dtypes


class _LRUCache:
    """Bounded mapping with least-recently-used eviction.

    Long-lived multi-program processes (the serving server, a notebook
    driving many programs through one executor) must not grow the plan
    and jit caches without bound: a jit entry pins a compiled XLA
    executable plus its HBM constants.  Capacity defaults are generous
    (steady-state workloads never evict); ``on_evict`` feeds the
    ``executor_*_cache_evictions_total`` counters so an eviction storm
    — a capacity set too small for the program population — is visible
    on /metrics rather than silently recompiling every run."""

    __slots__ = ("_data", "capacity", "_on_evict")

    def __init__(self, capacity: int, on_evict=None):
        from collections import OrderedDict

        self._data: "OrderedDict" = OrderedDict()
        self.capacity = max(1, int(capacity))
        self._on_evict = on_evict

    def get(self, key, default=None):
        try:
            self._data.move_to_end(key)
        except KeyError:
            return default
        return self._data[key]

    def __setitem__(self, key, value):
        data = self._data
        data[key] = value
        data.move_to_end(key)
        while len(data) > self.capacity:
            data.popitem(last=False)
            if self._on_evict is not None:
                self._on_evict()

    def __contains__(self, key):
        return key in self._data

    def __len__(self):
        return len(self._data)

    def clear(self):
        self._data.clear()


# default cache bounds (env-overridable; constructor kwargs win).  Sized
# so ordinary workloads — even a serving process hosting dozens of
# endpoints x bucket rungs — never evict; the bound exists for the
# pathological long-lived case (programs built in a loop forever).
_PLAN_CACHE_CAPACITY = int(os.environ.get(
    "PADDLE_TPU_PLAN_CACHE_CAPACITY", "1024"))
_JIT_CACHE_CAPACITY = int(os.environ.get(
    "PADDLE_TPU_JIT_CACHE_CAPACITY", "512"))


class Executor:
    # train_from_dataset resume bookkeeping — class-level defaults so a
    # fresh executor answers reads before any epoch ran (each call
    # resets them as instance attributes)
    last_resume_step = None
    last_restore_path = None
    last_restore_fallbacks = 0
    last_restore_stats = None
    # training control tower (monitor/train.py): ``_train_ledger`` arms
    # run()'s phase charges for the duration of one train_from_dataset
    # epoch (one is-None gate on the disarmed path); the ``last_*``
    # handles keep /trainz answering after the epoch ends
    _train_ledger = None
    _train_admin = None
    _train_admin_thread = None
    last_train_ledger = None
    last_train_watchdog = None
    last_train_log = None

    def __init__(self, place=None, plan_cache_capacity: Optional[int] = None,
                 jit_cache_capacity: Optional[int] = None,
                 reshard_on_gather: Optional[bool] = None):
        # place=None means "process default device" (jax.devices()[0]) —
        # an explicit TPUPlace/CPUPlace is honored strictly (_device).
        self.place = place if place is not None else framework._DefaultPlace()
        # uncompiled-after-compiled interop: scope state a compiled run
        # committed to a MESH cannot feed a single-device jit.  Default
        # is a loud typed diagnostic (MeshCommittedStateError naming the
        # variable and its mesh); opting in here (or via
        # PADDLE_TPU_RESHARD_ON_GATHER=1) gathers the state back to
        # host ONCE at the offending run instead.
        self._reshard_on_gather = (
            bool(reshard_on_gather) if reshard_on_gather is not None
            else os.environ.get("PADDLE_TPU_RESHARD_ON_GATHER", "0") == "1")
        self._cache = _LRUCache(
            jit_cache_capacity if jit_cache_capacity is not None
            else _JIT_CACHE_CAPACITY,
            on_evict=lambda: self._bump("jit_evictions"))
        self._plans = _LRUCache(
            plan_cache_capacity if plan_cache_capacity is not None
            else _PLAN_CACHE_CAPACITY,
            on_evict=lambda: self._bump("plan_evictions"))
        self._dev = None  # resolved jax device (place is immutable)
        # jit-cache accounting (serving reads this): a miss means a NEW
        # jax.jit entry was built for a novel (program, feed-signature,
        # ...) key — i.e. an XLA compile on first dispatch.  This is the
        # ground truth behind serving's recompile counter, not an
        # inference from timing.  The dict also feeds the registry's
        # executor_* callback counters (summed across live executors at
        # scrape time; a finalizer folds this executor's totals into the
        # retired base on GC so the counters stay monotonic).
        self._cache_stats = {
            "hits": 0, "misses": 0, "runs": 0,
            "plan_hits": 0, "plan_misses": 0, "dispatch_overhead_s": 0.0,
            "plan_evictions": 0, "jit_evictions": 0,
            "ps_pull_overlap_s": 0.0, "ps_pull_wait_s": 0.0,
        }
        with _exec_stats_lock:
            _exec_stats.append(self._cache_stats)
        _weakref.finalize(self, _retire_exec_stats, self._cache_stats)

    def _bump(self, key: str, n: int = 1) -> None:
        self._cache_stats[key] += n

    # ------------------------------------------------------------------
    def _device(self):
        import jax

        backend = getattr(self.place, "backend", None)
        if backend:
            try:
                devs = jax.devices(backend)
                idx = getattr(self.place, "device_id", 0)
                return devs[idx % len(devs)]
            except RuntimeError as e:
                # Place mismatch is an error, like the reference's hard
                # failure on an unavailable Place (platform/place.h) —
                # unless the user opts into fallback explicitly.
                if os.environ.get("FLAGS_allow_place_fallback", "0") == "1":
                    import warnings

                    warnings.warn(
                        "place %r unavailable (%s); falling back to %s"
                        % (self.place, e, jax.devices()[0].platform)
                    )
                else:
                    raise RuntimeError(
                        "place %r requests backend %r which is unavailable: %s. "
                        "Set FLAGS_allow_place_fallback=1 to run on %s instead."
                        % (self.place, backend, e, jax.devices()[0].platform)
                    ) from e
        return jax.devices()[0]

    def _device_cached(self):
        # the place never changes after construction, so resolving the
        # jax device once keeps jax.devices() off the per-run hot path
        dev = self._dev
        if dev is None:
            dev = self._dev = self._device()
        return dev

    # ------------------------------------------------------------------
    def run(
        self,
        program=None,
        feed: Optional[Dict[str, Any]] = None,
        fetch_list: Optional[Sequence] = None,
        scope: Optional[Scope] = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
        steps: int = 1,
        per_step_feed: bool = False,
    ):
        """``steps`` (TPU-native extension): run N optimizer steps inside ONE
        jitted call (a ``lax.fori_loop`` over the compiled step), returning
        the last step's fetches.  Amortizes the per-dispatch host->device
        overhead — the analog of the reference's multi-iteration DeviceWorker
        loop (device_worker.h TrainFiles runs many batches per Run call).

        By default every iteration re-consumes the same feed (a pure
        compute benchmark regime).  With ``per_step_feed=True`` each feed
        value carries an extra leading ``steps`` axis (shape
        ``(steps,) + per_batch_shape``) and iteration ``i`` consumes slice
        ``i`` via ``lax.dynamic_index_in_dim`` — N *distinct* batches per
        jitted call, the compiled analog of the reference's buffered reader
        feeding the train loop (operators/reader/buffered_reader.cc)."""
        import jax

        stats = self._cache_stats
        stats["runs"] += 1
        if _faults.active is not None:  # disarmed: one is-None gate
            _faults.active.faultpoint("executor.run")
        _rec = _mon_spans.recording()
        # step-phase ledger (training control tower): disarmed runs pay
        # this one is-None gate; armed runs open a window-exclusive
        # device_execute window whose explicit h2d/ps_wait charges below
        # subtract out, so no wall-clock second is attributed twice
        _led = self._train_ledger
        _led_tok = _led.window_begin() if _led is not None else None
        _t_run0 = time.perf_counter()
        compiled = None
        if program is not None and getattr(program, "_is_compiled_program", False):
            compiled = program
            program = compiled._program
        if program is None:
            program = framework.default_main_program()
        scope = scope or global_scope()
        feed = dict(feed or {})

        if getattr(program, "_pserver_ctx", None):
            return self._run_pserver(program)

        if getattr(program, "_pipeline_plan", None):
            if steps != 1:
                raise ValueError("steps>1 is not supported for pipeline programs")
            return self._run_pipeline(
                program, feed,
                [_as_fetch_name(f) for f in (fetch_list or [])],
                scope, return_numpy,
            )

        dense_ps = getattr(program, "_dense_ps_ctx", None)
        if dense_ps is not None:
            if steps != 1:
                raise ValueError(
                    "steps>1 is incompatible with dense PS mode (the grad "
                    "send / param recv is host-side per batch)"
                )
            self._dense_ps_init(dense_ps, scope)
            # overlapped mode: install the params the background thread
            # pulled while the PREVIOUS step's device compute ran (must
            # land before this run's state gather)
            self._dense_ps_join_pending(dense_ps, scope)

        if getattr(program, "_pruned_params", None):
            # a writer appended after prune() would resurrect pruned
            # weights (ADVICE r2); re-validate when the op count moved
            n_ops = sum(len(b.ops) for b in program.blocks)
            if n_ops != getattr(program, "_pruned_checked_ops", None):
                from paddle_tpu.contrib.slim.prune import _check_no_late_writers

                _check_no_late_writers(program)
                program._pruned_checked_ops = n_ops

        # distributed lookup tables: pull rows before the step, push the
        # sparse grads after (reference: parameter_prefetch.cc + the
        # trainer-side send of SelectedRows grads).  Host-side per batch
        # (or a device-side mesh gather — sharding/sparse.py).  NOTE the
        # plan key uses the PRE-expansion feed names: the rows/local
        # names the prefetch adds are a deterministic function of them,
        # so the expanded plan is safe to reuse — and they are EXCLUDED
        # from the key even when already present (the overlapped
        # prefetch installs them ahead of run()), so the inline and
        # overlapped paths share one plan and one jit entry.  A
        # caller-managed manual prefetch (rows fed with NO side-channel
        # ids — grads are not pushed) is keyed separately.
        dist_tables = getattr(program, "_distributed_tables", None)
        feed_key_names = tuple(sorted(feed))
        manual_prefetch = ()
        if dist_tables:
            side = getattr(program, "_sparse_prefetched_ids", None) or {}
            internal = set()
            manual = []
            for meta in dist_tables.values():
                internal.add(meta["rows_name"])
                internal.add(meta["local_name"])
                if meta["rows_name"] in feed and meta["rows_name"] not in side:
                    manual.append(meta["rows_name"])
            feed_key_names = tuple(
                sorted(n for n in feed if n not in internal))
            manual_prefetch = tuple(sorted(manual))
        plan_key = (
            framework._program_uid(program),
            program.version,
            sum(len(b.ops) for b in program.blocks),
            feed_key_names,
            tuple(_as_fetch_name(f) for f in (fetch_list or [])),
            steps,
            per_step_feed,
            getattr(self.place, "backend", None),
            framework._program_uid(compiled) if compiled is not None else None,
            manual_prefetch,
        )
        ps_push = ()
        if dist_tables:
            if _led is None:
                ps_push = self._prefetch_distributed_tables(
                    program, program.global_block(), feed, compiled=compiled)
            else:
                # inline (non-overlapped) sparse pulls block right here —
                # the ledger files them under ps_wait, not device_execute
                _t_ps = time.perf_counter()
                ps_push = self._prefetch_distributed_tables(
                    program, program.global_block(), feed, compiled=compiled)
                _led.charge("ps_wait", time.perf_counter() - _t_ps)

        plan = self._plans.get(plan_key) if use_program_cache else None
        if plan is not None:
            stats["plan_hits"] += 1
        else:
            stats["plan_misses"] += 1
            plan = self._analyze(program, feed, fetch_list, ps_push, dense_ps)
            if use_program_cache:
                self._plans[plan_key] = plan

        if steps != 1 and (ps_push or steps < 1):
            raise ValueError(
                "steps=%d: multi-step run() needs steps>=1 and is "
                "incompatible with distributed lookup tables (the PS "
                "pull/push is host-side per batch)" % steps
            )
        if per_step_feed:
            bad = {
                n: np.shape(v)
                for n, v in feed.items()
                if np.shape(v)[:1] != (steps,)
            }
            if bad:
                raise ValueError(
                    "per_step_feed=True: every feed needs a leading "
                    "steps=%d axis; got %s" % (steps, bad)
                )

        feed_names = plan.feed_names
        fetch_names = plan.fetch_names
        state_mut, state_ro = plan.state_mut, plan.state_ro
        n_dense_fetch = plan.n_dense_fetch

        # hot-path: begin dispatch (plan hit -> feed coercion -> jitted call;
        # no blocking device sync may appear in this region — enforced by
        # tools/check_hot_path.py)
        # materialize feed on the target device; values that are already
        # jax Arrays (e.g. a device-resident input pipeline, reader.py)
        # pass through untouched — no host round-trip.  Dtype coercion
        # tables were resolved once at plan build.
        device = self._device_cached()
        if _rec or _led is not None:
            _t0 = time.perf_counter()
        feed_arrays = {}
        np_dts, jax_dts = plan.feed_np_dtypes, plan.feed_jax_dtypes
        for name, val in feed.items():
            if isinstance(val, jax.Array):
                # coerce device-resident feeds too (cheap on-device cast,
                # stays in HBM) so the compiled signature matches the
                # program var — same contract as numpy feeds
                want = jax_dts.get(name)
                if want is not None and val.dtype != want:
                    val = val.astype(want)
                feed_arrays[name] = val
                continue
            arr = np.asarray(val, dtype=np_dts.get(name))  # hot-ok: host ndarray feed, not a device array
            feed_arrays[name] = jax.device_put(arr, device)
        if _led is not None:
            _led.charge("h2d", time.perf_counter() - _t0)
        if _rec:
            _mon_spans.record_span(
                "executor/h2d_feed", _t0, time.perf_counter() - _t0,
                cat="transfer", n_feeds=len(feed_arrays))

        # gather state from scope (one pass doubles as the init check;
        # the committed-state probe is two getattrs per var, and only
        # for UNcompiled runs — compiled runs re-place via the mesh)
        mut_state, ro_state, missing, committed = {}, {}, None, None
        for names, out in ((state_mut, mut_state), (state_ro, ro_state)):
            for n in names:
                v = scope.get(n)
                if v is None:
                    missing = (missing or []) + [n]
                elif compiled is None:
                    sh = getattr(v, "sharding", None)
                    if sh is not None and len(
                            getattr(sh, "device_set", ())) > 1:
                        committed = (committed or []) + [(n, out, sh)]
                out[n] = v
        if missing:
            raise RuntimeError(
                "Variables %s are not initialized in scope — run the startup "
                "program first (reference: executor.py run startup)" % missing
            )
        if committed:
            # interop gap (ROADMAP): a program run UNCOMPILED after a
            # compiled run sees mesh-committed (sharded or mesh-
            # replicated) state; feeding it to a single-device jit
            # fails deep inside jax with a device mismatch.  Either
            # gather the state back to host once (opt-in) or name the
            # problem loudly here.
            if self._reshard_on_gather:
                for n, out, _sh in committed:
                    host = jax.device_get(out[n])  # hot-ok: cold interop path — committed state detected, gather once
                    out[n] = host
                    scope.set(n, host)  # later runs gather clean
            else:
                from paddle_tpu.sharding.rules import MeshCommittedStateError

                descs = []
                for n, _out, sh in committed[:4]:
                    mesh = getattr(sh, "mesh", None)
                    where = (
                        dict(zip(mesh.axis_names, mesh.devices.shape))
                        if mesh is not None else
                        "%d devices" % len(sh.device_set))
                    descs.append("%r on %s" % (n, where))
                more = len(committed) - len(descs)
                raise MeshCommittedStateError(
                    "running this program UNCOMPILED, but its scope state "
                    "is committed to a device mesh by a previous compiled "
                    "run: %s%s. Run it through the same CompiledProgram, "
                    "or opt into a one-time host gather with "
                    "Executor(reshard_on_gather=True) / "
                    "PADDLE_TPU_RESHARD_ON_GATHER=1."
                    % ("; ".join(descs),
                       " (+%d more)" % more if more > 0 else ""))

        feed_sig = tuple(
            (n, feed_arrays[n].shape, feed_arrays[n].dtype)
            for n in feed_names
        )
        # plan_key already pins program identity/version/op-count, fetch
        # list, steps/per_step_feed, backend, and compiled identity; the
        # state tuples are a pure function of those, so the jit key only
        # needs the per-run shape/dtype signature on top
        key = (plan_key, feed_sig)

        entry = self._cache.get(key) if use_program_cache else None
        first_dispatch = entry is None
        if entry is not None:
            stats["hits"] += 1
        else:
            stats["misses"] += 1
            block = program.global_block()
            state_out = plan.state_out
            if _rec:
                _t0 = time.perf_counter()
            fn = lowering.lower_block(block, feed_names, fetch_names, state_out)
            _act = (compiled.activation_constrainer()
                    if compiled is not None else None)
            if _act is not None:
                # sequence-parallel serving: install the activation
                # constrainer around the block trace so matched
                # intermediates get with_sharding_constraint applied
                # in-trace (trace time = first dispatch of this key —
                # steady-state dispatches never re-enter fn)
                _base_fn = fn

                def fn(state, feed, _base=_base_fn, _c=_act):
                    from paddle_tpu.sharding import activations as _sh_act

                    _c.begin_trace()
                    with _sh_act.tracing(_c):
                        out = _base(state, feed)
                    _c.end_trace()
                    return out

            if steps == 1:
                def stepfn(mut_state, ro_state, feed_dict):
                    state = dict(mut_state)
                    state.update(ro_state)
                    if per_step_feed:
                        feed_dict = {n: v[0] for n, v in feed_dict.items()}
                    return fn(state, feed_dict)
            else:
                def stepfn(mut_state, ro_state, feed_dict):
                    # carry (mut, fetches, extras) with extras = written-but-
                    # not-carried state, so no array appears twice in the
                    # loop carry (a duplicated param forces a copy per
                    # iteration)
                    def step_feed(i):
                        if not per_step_feed:
                            return feed_dict
                        return {
                            n: jax.lax.dynamic_index_in_dim(
                                v, i, axis=0, keepdims=False
                            )
                            for n, v in feed_dict.items()
                        }

                    def one(i, mut):
                        state = dict(mut)
                        state.update(ro_state)
                        fetches, new_state = fn(state, step_feed(i))
                        nxt = {n: new_state.get(n, mut[n]) for n in mut}
                        extras = {
                            n: v for n, v in new_state.items() if n not in mut
                        }
                        return nxt, fetches, extras

                    carry = one(0, mut_state)
                    mut, fetches, extras = jax.lax.fori_loop(
                        1, steps, lambda i, c: one(i, c[0]), carry
                    )
                    return fetches, {**mut, **extras}

            jit_kwargs = dict(_donate_kwargs(device))
            if compiled is not None:
                jit_kwargs.update(
                    compiled._jit_kwargs(
                        block, feed_names, fetch_names, state_mut, state_ro,
                        state_out, per_step_feed=per_step_feed,
                    )
                )
            entry = jax.jit(stepfn, **jit_kwargs)
            if _rec:
                # closure construction only; the block actually traces
                # inside the first dispatch (the lowering/trace_block
                # span nested in executor/jit_compile below)
                _mon_spans.record_span(
                    "executor/lower", _t0, time.perf_counter() - _t0,
                    cat="lower", n_ops=len(block.ops))
            if use_program_cache:
                self._cache[key] = entry

        if compiled is not None:
            # the steady token is scoped to THIS executor (uid, not
            # id() — CPython reuses ids after GC): two executors sharing
            # a CompiledProgram have independent scopes, so one reaching
            # steady state must not let the other skip placement
            feed_arrays, mut_state, ro_state, restaged = compiled._shard_inputs(
                feed_arrays, mut_state, ro_state, per_step_feed=per_step_feed,
                steady_token=(framework._program_uid(self), key),
            )
            for n, v in restaged.items():
                # keep the resharded copy: a read-only param must be
                # replicated onto the mesh ONCE, not per step (state_mut
                # self-heals via out_shardings-pinned outputs, but ro
                # state is never written back by the jitted call)
                scope.set(n, v)
        # everything above is the host's per-dispatch rent; on a plan +
        # jit cache hit it must stay "almost nothing" (the new
        # bench_dispatch.py pins it)
        _overhead = time.perf_counter() - _t_run0
        stats["dispatch_overhead_s"] += _overhead
        if _rec:
            # a serving replica runs this under the batch's trace
            # context — pin one of its trace ids to the bucket so the
            # OpenMetrics exposition links overhead tails to requests
            _ids = _mon_spans.current_trace_ids()
            _MON_DISPATCH_HIST.observe(
                _overhead, exemplar={"trace_id": _ids[0]} if _ids else None)
            _t0 = time.perf_counter()
        fetches, new_state = entry(mut_state, ro_state, feed_arrays)
        # hot-path: end dispatch (the jitted call is async; everything
        # below is allowed to sync)
        if _rec:
            # the first dispatch of a novel cache key is where XLA
            # compiles (jax.jit is lazy) — label it as the compile phase;
            # steady-state dispatches are device execution
            _mon_spans.record_span(
                "executor/jit_compile" if first_dispatch
                else "executor/device_execute",
                _t0, time.perf_counter() - _t0,
                cat="compile" if first_dispatch else "execute",
                steps=steps)
        for n, v in new_state.items():
            scope.set(n, v)
        if n_dense_fetch:
            # dense PS round (reference: send_barrier -> send grads ->
            # recv params, distribute_transpiler.py:320): push EVERY grad
            # before pulling ANY param — in sync mode the pull blocks on
            # the server applying all trainers' grads, so interleaving
            # would deadlock this trainer against itself
            client = self._dense_ps_client(dense_ps)
            names = list(dense_ps["params"])
            # overlapped pull (async mode, train_from_dataset): kick the
            # NEXT step's param pull off on a background thread NOW,
            # while this step's device compute is still in flight (the
            # np.asarray(grad) below is the d2h sync point) — the pull
            # latency hides behind the chip instead of serializing after
            # it.  Hogwild semantics: the pulled copy misses this step's
            # own push (bounded staleness 1), which async mode already
            # tolerates by construction.  Sync mode keeps the strict
            # push-all-then-pull-at-version ordering below.
            overlap = bool(dense_ps.get("overlap_pull")) and not dense_ps["sync"]
            if overlap:
                self._dense_ps_spawn_pull(dense_ps, names)
            grads = fetches[len(fetches) - n_dense_fetch:]
            fetches = fetches[: len(fetches) - n_dense_fetch]
            for name, grad in zip(names, grads):
                lr_var = dense_ps["params"][name]["lr_var"]
                lr_val = scope.get(lr_var)
                lr = float(np.asarray(lr_val)) if lr_val is not None else 0.1
                client.push_dense(name, np.asarray(grad), lr)
            dense_ps["step"] += 1
            if not overlap:
                min_v = dense_ps["step"] if dense_ps["sync"] else 0
                _t_pd = time.perf_counter() if _led is not None else 0.0
                for name in names:
                    scope.set(name, client.pull_dense(name, min_version=min_v))
                if _led is not None:
                    # the blocking (non-overlapped) dense pull is PS wire
                    # wait, not device time
                    _led.charge("ps_wait", time.perf_counter() - _t_pd)
        if ps_push:
            # mesh-resident tables: shard-wise device update, grad never
            # leaves HBM.  PS tables: async mode enqueues on the
            # Communicator (merge-before-send background thread), sync
            # mode pushes blocking — and a bound embedding cache
            # invalidates the pushed rows AFTER the server-side write
            # lands (invalidating before it would let a concurrent
            # read-through re-cache the pre-update row permanently; the
            # async path invalidates from the Communicator's send
            # thread, after each applied merge).
            comm = getattr(program, "_ps_communicator", None)
            client = getattr(program, "_ps_client", None)
            mesh_rt = getattr(program, "_mesh_tables", None)
            cache = getattr(program, "_embedding_cache", None)
            if comm is not None and cache is not None:
                comm.on_pushed = cache.invalidate_ids
            # fetch_names still carries the dense-grad tail even though
            # those entries were sliced off `fetches` above — subtract
            # both hidden tails or the sparse-grad zip walks user fetches
            n_user = len(fetch_names) - len(ps_push) - n_dense_fetch
            for (table, uniq, _), grad in zip(ps_push, fetches[n_user:]):
                if mesh_rt is not None and table in mesh_rt:
                    mesh_rt.push(table, uniq, grad)
                    continue
                if comm is not None:
                    comm.push(table, uniq, np.asarray(grad))
                else:
                    client.push_sparse(table, uniq, np.asarray(grad))
                    if cache is not None:
                        cache.invalidate_ids(table, uniq)
            fetches = fetches[:n_user]
        if os.environ.get("FLAGS_check_nan_inf", "0") == "1":
            # module-boundary nan/inf check (reference checks per-op after
            # each kernel, operator.cc:954; one compiled module => one
            # boundary). Costs a d2h sync — debug only.
            bad = [
                name
                for name, val in list(zip(fetch_names, fetches)) + list(new_state.items())
                if np.issubdtype(np.asarray(val).dtype, np.floating)
                and not np.all(np.isfinite(np.asarray(val)))
            ]
            if bad:
                raise RuntimeError(
                    "nan/inf detected in %s (FLAGS_check_nan_inf=1)" % bad
                )
        if return_numpy:
            if _rec:
                _t0 = time.perf_counter()
            fetches = [np.asarray(f) for f in fetches]
            if _rec:
                _mon_spans.record_span(
                    "executor/d2h_fetch", _t0, time.perf_counter() - _t0,
                    cat="transfer", n_fetch=len(fetches))
        if _led is not None:
            # remainder of the run window = dispatch + jitted call + the
            # d2h sync that realizes the device step (run() is async
            # after dispatch; the np.asarray above is where device time
            # becomes observable on this thread)
            _led.window_end(_led_tok, "device_execute")
        return fetches

    # ------------------------------------------------------------------
    def _analyze(self, program, feed, fetch_list, ps_push, dense_ps) -> _RunPlan:
        """The O(n_ops) block analysis ``run()`` used to repeat per call,
        done once per plan-cache key.  ``feed`` must already carry any
        distributed-table expansion (rows/local names) for this feed-name
        set."""
        import jax

        block = program.global_block()
        fetch_names = [_as_fetch_name(f) for f in (fetch_list or [])]

        persistable = {
            v.name for v in program.list_vars() if v.persistable
        }

        # true dataflow reads: a name counts as read-from-outside only
        # when some op reads it BEFORE any op writes it (a load/fill op
        # producing a persistable must not demand scope pre-init)
        read, written = set(), set()
        for op in block.ops:
            for n in op.input_arg_names:
                if n not in written:
                    read.add(n)
            for n in op.output_arg_names:
                written.add(n)
        for fname in fetch_names:
            if fname in persistable and fname not in written:
                read.add(fname)

        if ps_push:
            # fetch each prefetched-rows grad so it can be pushed; hidden
            # from the caller's fetch list (appended, sliced off by run)
            for _, _, gname in ps_push:
                fetch_names.append(gname)
        n_dense_fetch = 0
        if dense_ps is not None:
            # fetch each param's dense grad for the send (hidden like
            # ps_push; sliced off before returning to the caller)
            for desc in dense_ps["params"].values():
                fetch_names.append(desc["grad"])
                n_dense_fetch += 1

        feed_names = tuple(sorted(feed.keys()))
        state_mut = tuple(sorted(read & written & persistable))
        state_ro = tuple(
            sorted((read & persistable) - set(state_mut) - set(feed_names))
        )
        state_out = tuple(sorted(written & persistable))

        # dtype coercion tables: program-var dtype per feed, both as the
        # numpy target (host feeds) and the canonicalized jax target
        # (device-resident feeds) — resolved here so the hot path never
        # walks the var table or calls canonicalize_dtype
        np_dts, jax_dts = {}, {}
        for name in feed_names:
            var = block._find_var_recursive(name)
            if var is not None:
                dt = core_types.np_dtype(var.dtype)
                np_dts[name] = dt
                jax_dts[name] = jax.dtypes.canonicalize_dtype(dt)

        return _RunPlan(
            feed_names, fetch_names, n_dense_fetch,
            state_mut, state_ro, state_out, np_dts, jax_dts,
        )

    # ------------------------------------------------------------------
    # Dense legacy PS (reference: distribute_transpiler.py trainer side +
    # listen_and_serv_op.cc server loop)
    # ------------------------------------------------------------------
    def _dense_ps_client(self, ctx):
        client = ctx.get("_client")
        if client is None:
            from paddle_tpu.distributed.ps import PSClient

            client = ctx["_client"] = PSClient(ctx["endpoints"])
        return client

    def _dense_ps_pull_client(self, ctx):
        # the overlapped pull runs on its own thread CONCURRENTLY with
        # the main thread's push — PSClient sockets are not thread-safe
        # (interleaved frames corrupt the wire), so the pull thread gets
        # a dedicated client over the same endpoints
        client = ctx.get("_pull_client")
        if client is None:
            from paddle_tpu.distributed.ps import PSClient

            client = ctx["_pull_client"] = PSClient(ctx["endpoints"])
        return client

    # transient PS pull failures the background thread may retry: the
    # connection classes only — a PS in-band application error
    # (RuntimeError from PSClient._call) is deterministic and must
    # surface, not be retried
    _PS_PULL_RETRYABLE = (ConnectionError, OSError, TimeoutError)
    _PS_PULL_RETRY = None  # lazily built shared RetryPolicy

    @classmethod
    def _ps_pull_policy(cls):
        if cls._PS_PULL_RETRY is None:
            from paddle_tpu.faults.retry import RetryPolicy

            cls._PS_PULL_RETRY = RetryPolicy(
                max_attempts=4, base_delay_s=0.05, multiplier=2.0,
                max_delay_s=1.0)
        return cls._PS_PULL_RETRY

    def _dense_ps_spawn_pull(self, ctx, names) -> None:
        """Start the next step's param pull on a background thread (one
        in flight at a time — run() joins the previous before spawning).
        A transient PS failure (connection refused/reset — a flapping
        server) closes the dead client's sockets, redials on a fresh
        dedicated client, and retries under a RetryPolicy budget; on
        EVERY failure the erroring client's sockets are closed before
        the error propagates (no socket leak per failed pull thread)."""
        import threading

        from paddle_tpu.distributed.ps import PSClient

        client = self._dense_ps_pull_client(ctx)
        result: Dict[str, Any] = {}
        budget = self._ps_pull_policy().budget(op="ps.pull")

        def _pull():
            nonlocal client
            t0 = time.perf_counter()
            try:
                while True:
                    try:
                        result["vals"] = {
                            n: client.pull_dense(n, min_version=0)
                            for n in names
                        }
                        return
                    except self._PS_PULL_RETRYABLE:
                        # try/finally contract: the dedicated client's
                        # sockets close on this exit path no matter what
                        try:
                            client.close()
                        finally:
                            ctx.pop("_pull_client", None)
                        if not budget.backoff():
                            raise
                        client = ctx["_pull_client"] = PSClient(
                            ctx["endpoints"])
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                result["exc"] = e
            finally:
                result["dur"] = time.perf_counter() - t0

        th = threading.Thread(target=_pull, name="ptpu-ps-pull", daemon=True)
        ctx["_pull_pending"] = (th, result)
        th.start()

    def _dense_ps_join_pending(self, ctx, scope) -> None:
        """Join the in-flight overlapped pull (if any) and install the
        pulled params.  ``ps_pull_overlap_s`` accumulates the pull
        seconds that hid behind device compute; ``ps_pull_wait_s`` the
        remainder this join actually blocked for."""
        pending = ctx.pop("_pull_pending", None)
        if pending is None:
            return
        th, result = pending
        t0 = time.perf_counter()
        th.join()
        wait = time.perf_counter() - t0
        stats = self._cache_stats
        stats["ps_pull_wait_s"] += wait
        stats["ps_pull_overlap_s"] += max(0.0, result.get("dur", 0.0) - wait)
        led = self._train_ledger
        if led is not None:
            led.charge("ps_wait", wait)
        exc = result.get("exc")
        if exc is not None:
            raise exc
        for n, v in result["vals"].items():
            scope.set(n, v)

    # ------------------------------------------------------------------
    # Overlapped SPARSE prefetch (train_from_dataset async mode): batch
    # N+1's per-table PS pulls run on a background thread while batch
    # N's device compute is in flight — the sparse analog of the
    # overlapped dense pulls above, with the same dedicated-client and
    # overlap/wait accounting contracts.  Async (Communicator) mode
    # only: the prefetched rows miss the current step's own push
    # (bounded staleness 1), which async mode already tolerates by
    # construction; sync mode keeps the strict pull-push ordering.
    # ------------------------------------------------------------------
    def _sparse_overlap_clients(self, ctx, endpoints, n: int):
        """The overlap thread's own clients (one per table) — never the
        caller's, and never the inline pool's (those serve the caller
        thread's concurrent pulls)."""
        from paddle_tpu.distributed.ps import PSClient

        pool = ctx.setdefault("clients", [])
        while len(pool) < n:
            pool.append(PSClient(list(endpoints)))
        return pool[:n]

    def _sparse_overlap_close(self, ctx) -> None:
        for cl in ctx.pop("clients", []):
            try:
                cl.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass

    def _sparse_spawn_prefetch(self, program, feed) -> None:
        """Start the NEXT batch's table pulls on a background thread
        (one in flight at a time — the overlap iterator joins before
        spawning).  Per-table pulls inside the thread run concurrently
        on dedicated clients; a transient failure closes the thread's
        clients, redials, and retries under the shared RetryPolicy
        budget — on exhaustion the error surfaces typed at join."""
        import threading

        dist_tables = program._distributed_tables
        mesh_rt = getattr(program, "_mesh_tables", None)
        cache = getattr(program, "_embedding_cache", None)
        ladder = getattr(program, "_sparse_id_ladder", None)
        endpoints = getattr(
            getattr(program, "_ps_client", None), "endpoints", None)
        jobs = []
        for meta in dist_tables.values():
            if meta["rows_name"] in feed or meta["ids_name"] not in feed:
                continue
            if mesh_rt is not None and meta["table"] in mesh_rt:
                continue  # device-side gather: nothing to hide
            uniq_p, n, counts, local = self._sparse_expand_ids(
                meta, feed[meta["ids_name"]], ladder)
            self._record_uniq_count(program, n)
            jobs.append((meta, uniq_p, n, counts, local))
        if not jobs or not endpoints:
            return
        ctx = program.__dict__.setdefault("_sparse_overlap_ctx", {})
        result: Dict[str, Any] = {}
        budget = self._ps_pull_policy().budget(op="ps.pull")

        def _pull():
            t0 = time.perf_counter()
            try:
                while True:
                    try:
                        clients = self._sparse_overlap_clients(
                            ctx, endpoints, len(jobs))
                        vals, errs = self._fanout_table_pulls(
                            jobs, clients, cache)
                        if errs:
                            raise errs[0][0]
                        result["vals"] = vals
                        return
                    except self._PS_PULL_RETRYABLE:
                        # close + redial on a fresh set, like the dense
                        # pull thread (no socket leak per failed pull)
                        self._sparse_overlap_close(ctx)
                        if not budget.backoff():
                            raise
            except BaseException as e:  # noqa: BLE001 — re-raised at join
                result["exc"] = e
            finally:
                result["dur"] = time.perf_counter() - t0

        th = threading.Thread(target=_pull, name="ptpu-sparse-prefetch",
                              daemon=True)
        ctx["pending"] = (th, result, jobs)
        th.start()

    def _sparse_join_prefetch(self, program, feed) -> None:
        """Join the in-flight sparse prefetch and install the pulled
        rows + local maps into ``feed``; the unique ids ride the
        ``_sparse_prefetched_ids`` side-channel so the next run() still
        pushes this batch's sparse grads.  Accounting mirrors the dense
        path: ``ps_pull_overlap_s`` is the pull time that hid behind
        device compute, ``ps_pull_wait_s`` what this join blocked for."""
        ctx = program.__dict__.get("_sparse_overlap_ctx")
        pending = ctx.pop("pending", None) if ctx else None
        if pending is None:
            return
        th, result, jobs = pending
        t0 = time.perf_counter()
        th.join()
        wait = time.perf_counter() - t0
        stats = self._cache_stats
        stats["ps_pull_wait_s"] += wait
        stats["ps_pull_overlap_s"] += max(0.0, result.get("dur", 0.0) - wait)
        led = self._train_ledger
        if led is not None:
            # the join runs inside the data_wait window (next(batches));
            # window-exclusive accounting moves it into ps_wait
            led.charge("ps_wait", wait)
        exc = result.get("exc")
        if exc is not None:
            raise exc
        side = program.__dict__.setdefault("_sparse_prefetched_ids", {})
        for meta, uniq_p, _n, _counts, local in jobs:
            feed[meta["rows_name"]] = result["vals"][meta["rows_name"]]
            feed[meta["local_name"]] = local
            side[meta["rows_name"]] = uniq_p

    def _sparse_overlap_iter(self, program, batches):
        """One-step-lookahead wrapper: spawn batch N+1's pulls BEFORE
        yielding batch N (so they run while N computes), join + install
        when the consumer asks for N+1.  Every exit path joins the
        pending thread and closes the overlap clients."""
        ctx = program.__dict__.setdefault("_sparse_overlap_ctx", {})
        it = iter(batches)

        def pull_next():
            # work on a COPY: the join installs rows/local into the
            # feed, and mutating the CALLER's dict would make a second
            # epoch over the same feed list look manually-prefetched
            # (silently dropping its grad pushes)
            nxt = next(it, None)
            return dict(nxt) if isinstance(nxt, dict) else nxt

        try:
            cur = pull_next()
            if cur is None:
                return
            while True:
                nxt = pull_next()
                if nxt is not None:
                    self._sparse_spawn_prefetch(program, nxt)
                yield cur
                if nxt is None:
                    return
                self._sparse_join_prefetch(program, nxt)
                cur = nxt
        finally:
            pending = ctx.pop("pending", None)
            if pending is not None:
                # abandoned mid-epoch (consumer error/break): drain the
                # thread so it can't race teardown; its error is moot
                pending[0].join()
            self._sparse_overlap_close(ctx)
            program.__dict__.pop("_sparse_prefetched_ids", None)
            closer = getattr(it, "close", None)
            if closer is not None:
                closer()

    def _dense_ps_init(self, ctx, scope):
        """First-run handshake: create the server-side entries, trainer 0
        seeds its initial param values (deterministic broadcast), everyone
        pulls the seeded copy — the reference pserver startup + initial
        recv (distribute_transpiler.py get_startup_program)."""
        if ctx["initialized"]:
            return
        client = self._dense_ps_client(ctx)
        for name, desc in ctx["params"].items():
            val = scope.get(name)
            if val is None:
                raise RuntimeError(
                    "dense PS param %r not in scope — run the startup "
                    "program first" % name
                )
            client.create_dense(
                name, np.shape(val), optimizer=desc["optimizer"],
                attrs=desc["attrs"], n_trainers=ctx["n_trainers"],
                sync=ctx["sync"],
            )
            if ctx["trainer_id"] == 0:
                client.seed_dense(name, np.asarray(val))
            scope.set(name, client.pull_dense(name, min_version=0))
        ctx["initialized"] = True

    def _run_pserver(self, program):
        """Serve the dense params hashed to this endpoint and BLOCK, like
        the reference's listen_and_serv op.  The live server object is
        exposed as ``program._pserver`` so a host test/driver can stop it."""
        from paddle_tpu.distributed.ps import ParameterServer, PSClient

        ctx = program._pserver_ctx
        server = ParameterServer(ctx["endpoint"])
        # register this shard's dense params directly (no wire round-trip;
        # shard placement must match the trainer-side PSClient.shard_for)
        placer = PSClient(ctx["endpoints"])
        my_idx = ctx["endpoints"].index(ctx["endpoint"])
        from paddle_tpu.distributed.ps import _DenseParam

        for name, desc in ctx["params"].items():
            if placer.shard_for(name) != my_idx:
                continue
            server._dense[name] = _DenseParam(
                desc["shape"], optimizer=desc["optimizer"], attrs=desc["attrs"],
                n_trainers=ctx["n_trainers"], sync=ctx["sync"],
            )
        program._pserver = server
        server.start()
        try:
            server._thread.join()
        except KeyboardInterrupt:
            server.stop()
        return []

    # ------------------------------------------------------------------
    def _run_pipeline(self, program, feed, fetch_names, scope, return_numpy):
        """Run one compiled-GPipe step (PipelineOptimizer with cut_list;
        reference: PipelineTrainer/SectionWorker, section_worker.cc:141).
        Fetches are limited to the loss (the schedule's only global
        scalar)."""
        import jax

        from paddle_tpu.parallel import mesh as mesh_lib, pipeline_program

        plan = program._pipeline_plan
        loss_name = plan["loss_name"]
        K = len(plan["cut_vars"]) + 1
        feed_sig = tuple(
            (n, tuple(np.shape(v)),
             str(v.dtype if hasattr(v, "dtype") else np.asarray(v).dtype))
            for n, v in sorted(feed.items())
        )
        key = ("pipeline", framework._program_uid(program), program.version,
               feed_sig)
        entry = self._cache.get(key)
        if entry is None:
            # honor the executor's place like the main path (_device)
            mesh = mesh_lib.make_mesh(
                {"pp": K}, backend=getattr(self.place, "backend", None)
            )
            run_plan = dict(plan)
            run_plan["feed_names"] = sorted(feed.keys())
            step, state_names = pipeline_program.build_pipeline_step(
                program, loss_name, run_plan, mesh
            )
            # donate state like the main path: param/velocity updates are
            # in-place in HBM (skipped on CPU — see _donate_kwargs)
            entry = (
                jax.jit(step, **_donate_kwargs(mesh.devices.flat[0])),
                state_names,
            )
            self._cache[key] = entry
        step, state_names = entry

        # fetches: the loss plus any state var (params and optimizer
        # accumulators are the schedule's persistables)
        for f in fetch_names:
            if f != loss_name and f not in state_names:
                raise ValueError(
                    "pipeline programs can fetch the loss %r or a "
                    "persistable state var %s (got %r)"
                    % (loss_name, state_names, f)
                )
        state = {}
        for n in state_names:
            v = scope.get(n)
            if v is None:
                raise RuntimeError(
                    "var %r not initialized — run the startup program" % n
                )
            state[n] = v
        feed_arrays = {
            n: v if isinstance(v, jax.Array) else np.asarray(v)
            for n, v in feed.items()
        }
        loss, new_state = step(state, feed_arrays)
        for n, v in new_state.items():
            scope.set(n, v)
        out = [loss if f == loss_name else new_state[f] for f in fetch_names]
        if return_numpy:
            out = [np.asarray(o) for o in out]
        return out

    # ------------------------------------------------------------------
    # Distributed lookup tables: the sparse prefetch/push runtime.
    # Three backends behind one feed contract: mesh-resident tables
    # (sharding/sparse.py device gather), PS pulls (optionally through a
    # hot-id cache), and the overlapped background prefetch that
    # pipelines batch N+1's pulls behind batch N's device compute.
    # ------------------------------------------------------------------
    @staticmethod
    def _sparse_expand_ids(meta, ids_val, ladder=None):
        """Unique + bucket one table's batch ids.  Returns
        ``(uniq_padded, n_uniq, counts, local)``: the bucketed unique
        ids (padded by repeating ids[0], which receives zero gradient —
        no local index maps to it, so the push is a no-op for it), the
        real unique count, per-unique occurrence counts (the cache's
        served-rows accounting), and the ids->row map shaped like the
        feed.  ``ladder``: an explicit unique-count bucket ladder (the
        autotuned ``propose_id_bucket_ladder`` output); sizes above its
        top rung — or no ladder — fall back to power-of-two buckets."""
        ids_val = np.asarray(ids_val)
        flat = ids_val.reshape(-1).astype(np.int64)
        uniq, inv, counts = np.unique(
            flat, return_inverse=True, return_counts=True)
        n = len(uniq)
        bucket = None
        if ladder:
            for r in ladder:
                if int(r) >= n:
                    bucket = int(r)
                    break
        if bucket is None:
            bucket = pow2_id_bucket(n)
        fill = uniq[0] if n else 0
        uniq_p = np.concatenate(
            [uniq, np.full(bucket - n, fill, np.int64)])
        local = inv.astype(np.int32)
        if meta["squeeze_last"] and ids_val.ndim >= 2 and ids_val.shape[-1] == 1:
            local = local.reshape(ids_val.shape[:-1])
        else:
            local = local.reshape(ids_val.shape)
        return uniq_p, n, counts, local

    @staticmethod
    def _record_uniq_count(program, n: int) -> None:
        """Per-batch unique-id-count histogram (the offline id-ladder
        autotuner's input — serving.autotune.propose_id_bucket_ladder).
        Best-effort under the GIL, like the serving arrival histogram."""
        hist = program.__dict__.get("_uniq_id_hist")
        if hist is None:
            hist = program.__dict__.setdefault("_uniq_id_hist", {})
        hist[n] = hist.get(n, 0) + 1

    def _sparse_client_pool(self, program, n: int):
        """``n`` DEDICATED PSClients for concurrent per-table pulls (a
        PSClient socket is not thread-safe — interleaved frames corrupt
        the wire).  Pooled on the program and redialed lazily after an
        error closed one.  Returns None when the bound client is a
        duck-typed stub with no endpoints to dial (tests) — the caller
        then pulls serially on its own thread."""
        client = getattr(program, "_ps_client", None)
        endpoints = getattr(client, "endpoints", None)
        if not endpoints:
            return None
        from paddle_tpu.distributed.ps import PSClient

        pool = program.__dict__.setdefault("_sparse_pull_pool", [])
        while len(pool) < n:
            pool.append(PSClient(list(endpoints)))
        return pool[:n]

    def _pull_one_table(self, client, cache, meta, uniq_p, n_uniq, counts):
        """One table's row pull, through the hot-id cache when bound."""
        if cache is not None:
            rows = cache.lookup_through(
                client, meta["table"], uniq_p, n_valid=n_uniq,
                counts=counts)
        else:
            rows = client.pull_sparse(meta["table"], uniq_p)
        return np.asarray(rows, np.float32)

    def _fanout_table_pulls(self, jobs, clients, cache):
        """The shared per-table fan-out: job 0 on the CALLING thread
        with ``clients[0]``, jobs[1:] on worker threads each with its
        dedicated client (one socket per thread — frames never
        interleave).  Returns ``(results, errors)`` with ``errors`` as
        ``[(exc, client)]`` — callers decide the cleanup policy (the
        inline path drops the failed pool client; the overlap thread
        redials its whole set)."""
        results: Dict[str, np.ndarray] = {}
        errors: List = []

        def work(job, cl):
            meta, uniq_p, n, counts, _local = job
            try:
                results[meta["rows_name"]] = self._pull_one_table(
                    cl, cache, meta, uniq_p, n, counts)
            except BaseException as e:  # noqa: BLE001 — caller re-raises
                errors.append((e, cl))

        if len(jobs) == 1:
            work(jobs[0], clients[0])
            return results, errors
        import threading

        threads = [
            threading.Thread(target=work, args=(job, cl),
                             name="ptpu-sparse-pull", daemon=True)
            for job, cl in zip(jobs[1:], clients[1:])
        ]
        for th in threads:
            th.start()
        work(jobs[0], clients[0])
        for th in threads:
            th.join()
        return results, errors

    def _pull_tables_concurrent(self, program, client, cache, jobs):
        """Issue every job's ``pull_sparse`` CONCURRENTLY — job 0 on the
        calling thread with ``client``, the rest on worker threads each
        with a dedicated pool client (DeepFM has one table per sparse
        field; serializing them on one socket was the old behavior).
        Returns {rows_name: rows}; the first worker error propagates
        after all joins, with that worker's client closed and dropped
        from the pool (the next pull redials)."""
        pool = (self._sparse_client_pool(program, len(jobs) - 1)
                if len(jobs) > 1 else None)
        if len(jobs) > 1 and not pool:
            # duck-typed stub client with no endpoints to dial: serial
            results: Dict[str, np.ndarray] = {}
            for meta, uniq_p, n, counts, _local in jobs:
                results[meta["rows_name"]] = self._pull_one_table(
                    client, cache, meta, uniq_p, n, counts)
            return results
        results, errors = self._fanout_table_pulls(
            jobs, [client] + (pool or []), cache)
        if errors:
            exc = errors[0][0]
            pool_list = program.__dict__.get("_sparse_pull_pool", [])
            for e, cl in errors:
                if cl is not client:
                    try:
                        cl.close()
                    finally:
                        if cl in pool_list:
                            pool_list.remove(cl)
            raise exc
        return results

    def _prefetch_distributed_tables(self, program, block, feed,
                                     compiled=None):
        """Resolve each distributed table's rows for this batch's unique
        ids and add them (plus the ids->row map) to the feed.  Returns
        [(table, padded_unique_ids, rows_grad_name)] for tables whose
        grad exists in the program (training) so run() can push after
        the step.  Unique counts bucket (power-of-two, or the autotuned
        ``program._sparse_id_ladder``) to bound recompiles.

        Routing per table: a mesh-resident table (``bind_mesh_tables``)
        serves a device-side sharded gather — no host round-trip; PS
        tables pull host-side, all tables CONCURRENTLY (dedicated
        clients) and through the hot-id embedding cache when one is
        bound; rows already in the feed were supplied by the overlapped
        prefetch (its side-channel carries the unique ids so the grad
        push still happens) or by a manual caller (no push)."""
        dist_tables = getattr(program, "_distributed_tables", None)
        if not dist_tables:
            return []
        mesh_rt = getattr(program, "_mesh_tables", None)
        cache = getattr(program, "_embedding_cache", None)
        side = getattr(program, "_sparse_prefetched_ids", None)
        ladder = getattr(program, "_sparse_id_ladder", None)
        from paddle_tpu.framework import grad_var_name

        ps_push = []
        pulls = []  # PS-backed jobs, pulled concurrently below
        for meta in dist_tables.values():
            tname = meta["table"]
            rows_name = meta["rows_name"]
            if rows_name in feed:
                if side and rows_name in side:
                    # overlapped prefetch: rows landed ahead of run();
                    # the side-channel ids keep the grad push alive
                    uniq_p = side.pop(rows_name)
                    gname = grad_var_name(rows_name)
                    if block._find_var_recursive(gname) is not None:
                        ps_push.append((tname, uniq_p, gname))
                continue  # caller prefetched manually (no push)
            ids_name = meta["ids_name"]
            if ids_name not in feed:
                raise RuntimeError(
                    "distributed table %r needs ids var %r in the feed "
                    "(prefetch happens host-side per batch)" % (tname, ids_name)
                )
            uniq_p, n_uniq, counts, local = self._sparse_expand_ids(
                meta, feed[ids_name], ladder)
            self._record_uniq_count(program, n_uniq)
            feed[meta["local_name"]] = local
            gname = grad_var_name(rows_name)
            if block._find_var_recursive(gname) is not None:
                ps_push.append((tname, uniq_p, gname))
            if mesh_rt is not None and tname in mesh_rt:
                if compiled is None:
                    raise RuntimeError(
                        "table %r is mesh-resident (bind_mesh_tables): "
                        "its rows live sharded on the mesh, so this "
                        "program must run through its CompiledProgram "
                        "— an uncompiled run cannot consume the "
                        "mesh-committed lookup" % tname)
                feed[rows_name] = mesh_rt.lookup(tname, uniq_p)
            else:
                pulls.append((meta, uniq_p, n_uniq, counts, local))
        if pulls:
            client = getattr(program, "_ps_client", None)
            if client is None:
                raise RuntimeError(
                    "program has distributed lookup tables; call "
                    "paddle_tpu.distributed.bind_distributed_tables("
                    "program, endpoints) before running it"
                )
            rows_by_name = self._pull_tables_concurrent(
                program, client, cache, pulls)
            for meta, _uniq_p, _n, _counts, _local in pulls:
                feed[meta["rows_name"]] = rows_by_name[meta["rows_name"]]
        return ps_push

    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100,
                           trainer_desc=None, trace_id=None,
                           checkpoint_dir=None, checkpoint_every=0,
                           checkpoint_epoch=0, resume_from=None,
                           checkpoint_async=False, phase_ledger=None,
                           watchdog=None, train_log=None):
        """Loop the dataset's batches through run() (reference:
        executor.py train_from_dataset -> C++ Trainer/DeviceWorker loop,
        trainer.h:38; here the compiled step is the device worker).

        ``trainer_desc`` (trainer_desc.py): supplies fetch config
        defaults and validates that the chosen device worker matches the
        program (Section needs a PipelineOptimizer-cut program,
        DownpourSGD needs distributed lookup tables).

        Crash-resumable training (TPU-native extension, reference:
        checkpoint_notify + trainer restart from persistables — here
        exact to a step): ``checkpoint_dir`` + ``checkpoint_every=N``
        commits an atomic checkpoint every N completed steps — the
        program's persistables, the PS sparse tables (when the program
        is bound to a ``PSClient``), and the dataset cursor, all via
        tmp+rename (``paddle_tpu.faults.checkpoint.TrainCheckpoint``).
        A SIGKILLed run restarted with ``resume_from=<same dir>``
        restores all three and SKIPS the already-consumed batches, so it
        continues within one checkpoint interval of where it died;
        ``last_resume_step`` reports the restored cursor.  Async PS
        state (the overlapped pull, the Communicator's queued pushes) is
        quiesced before each save so the checkpoint is consistent.
        ``checkpoint_async=True`` moves serialization off the critical
        path: the step pays only a quiesce + copy-on-write gather and a
        background snapshot thread writes/commits (same tmp+rename
        atomicity; the epoch joins the tail save before returning).

        Request-scoped tracing (TPU-native extension): the epoch mints a
        trace id (or joins ``trace_id``) readable back via
        ``last_train_trace_id``; while a trace session or flight
        recorder is live, every step runs under that id inside an
        ``executor/train_step`` span parented to one
        ``executor/train_epoch`` span — a training epoch is correlatable
        in ``/tracez``/the merged Chrome trace exactly like a serving
        request.

        Training control tower (monitor/train.py):
        ``phase_ledger=True`` (or a ``StepPhaseLedger`` instance) arms
        the step-phase ledger — every wall-clock second of the epoch is
        attributed to data_wait / h2d / device_execute / ps_wait /
        checkpoint / restore_fallback / other, exported as
        ``train_phase_seconds_total{phase=}`` plus throughput and MFU
        gauges, and asserted to sum to the elapsed time within 1%.
        ``watchdog=True`` (or a ``TrainWatchdog``) runs EWMA + z-score
        anomaly detection per step (NaN/Inf loss, loss spikes,
        grad-norm blowups, step-time stragglers), emitting
        ``train/anomaly`` events and raising ``TrainAnomalyError`` for
        kinds in its ``halt_on``.  ``train_log=<path>`` streams one
        JSONL record per step (phases, loss, anomalies, trace id),
        replayable offline via ``monitor.train.replay_step_log`` /
        ``train_top --replay``.  ``start_train_admin()`` serves it all
        at ``/trainz``."""
        n_prefetch = int(thread)
        if trainer_desc is not None:
            worker = trainer_desc._worker
            if worker.worker_kind == "Section" and not getattr(program, "_pipeline_plan", None):
                raise ValueError(
                    "Section worker needs a PipelineOptimizer(cut_list=...) program"
                )
            if worker.worker_kind == "DownpourSGD" and not getattr(program, "_distributed_tables", None):
                raise ValueError(
                    "DownpourSGD worker needs embedding(is_distributed=True) tables"
                )
            # worker-specific runtime behavior: Hogwild flips a dense-PS
            # program to async rounds, DownpourSGD installs the async
            # Communicator, Section validates the microbatch plan
            worker._prepare(program)
            fetch_list = fetch_list or trainer_desc._fetch_vars
            fetch_info = fetch_info or trainer_desc._fetch_info
            print_period = trainer_desc._print_period
            n_prefetch = n_prefetch or int(getattr(trainer_desc, "thread_num", 0))
        compiled = (
            program if program is not None
            and getattr(program, "_is_compiled_program", False) else None)
        prog_obj = compiled._program if compiled is not None else (
            program if program is not None else framework.default_main_program())
        # training control tower: build/adopt the ledger, watchdog and
        # step log for this epoch.  The ledger's epoch window opens HERE
        # so a resume restore below is attributed (restore_fallback)
        # inside the same wall-clock the 1% sum contract covers.
        led = None
        if phase_ledger:
            led = (phase_ledger
                   if isinstance(phase_ledger, _mon_train.StepPhaseLedger)
                   else _mon_train.StepPhaseLedger())
            self.last_train_ledger = led
            led.begin_epoch()
        wd = None
        if watchdog:
            wd = (watchdog
                  if isinstance(watchdog, _mon_train.TrainWatchdog)
                  else _mon_train.TrainWatchdog())
            self.last_train_watchdog = wd
        steplog = None
        if train_log:
            steplog = _mon_train.StepLog(train_log)
            self.last_train_log = train_log
        # crash-resume: restore persistables + PS tables + the dataset
        # cursor BEFORE the first batch, then skip the consumed prefix
        ckpt = None
        start_step = 0
        self.last_resume_step = None
        # reset the restore bookkeeping every call — a plain run after a
        # resumed one must not keep reporting the old run's restore
        self.last_restore_path = None
        self.last_restore_fallbacks = 0
        self.last_restore_stats = None
        if checkpoint_dir is not None or resume_from is not None:
            from paddle_tpu.faults.checkpoint import TrainCheckpoint

            ckpt = TrainCheckpoint(checkpoint_dir or resume_from,
                                   every_n_steps=int(checkpoint_every))
            if resume_from is not None:
                # restore from resume_from even when NEW checkpoints go
                # to a different checkpoint_dir (fork-a-run semantics)
                src = (ckpt if checkpoint_dir in (None, resume_from)
                       else TrainCheckpoint(resume_from))
                _led_tok = led.window_begin() if led is not None else None
                cursor = src.restore(
                    prog_obj, scope or global_scope(),
                    ps_client=getattr(prog_obj, "_ps_client", None),
                    compiled=compiled)
                if _led_tok is not None:
                    led.window_end(_led_tok, "restore_fallback")
                # which checkpoint actually served (integrity fallback
                # may have skipped corrupt/pruned ones — the drills and
                # operators read these alongside last_resume_step)
                self.last_restore_path = src.last_restore_path
                self.last_restore_fallbacks = src.last_restore_fallbacks
                self.last_restore_stats = src.last_restore_stats
                if cursor is not None:
                    start_step = int(cursor.get("step", 0))
                    self.last_resume_step = start_step
                # resume/fallback history belongs in /eventz and the
                # step log, not stdout: one severity-tagged event per
                # resume (warning when integrity fallbacks were taken)
                _mon_events.emit(
                    "train/resume",
                    severity=("warning" if self.last_restore_fallbacks
                              else "info"),
                    message="resumed from %s at step %d (%d fallback(s))"
                    % (self.last_restore_path, start_step,
                       self.last_restore_fallbacks),
                    cat="train", step=start_step,
                    path=self.last_restore_path,
                    fallbacks=self.last_restore_fallbacks)
        batches = iter(dataset)
        if start_step:
            import itertools as _itertools

            batches = _itertools.islice(batches, start_step, None)
        if n_prefetch > 1:
            # the reference's reader threads feeding device workers
            # (trainer.h thread_num): a bounded background prefetcher
            # stages batches ON DEVICE ahead of the compiled step
            # (reader.device_buffered), so the run() h2d phase is a
            # passthrough.  A CompiledProgram upgrades this to SHARDED
            # prefetch: each replica's batch slice is device_put straight
            # into its own HBM, and run()'s _shard_inputs passes the
            # pre-placed arrays through.  The prefetcher shuts its
            # producer down when the consumer exits early (exception or
            # break) — the old inline queue left the thread blocked on
            # q.put forever.
            from paddle_tpu import reader as _reader

            if compiled is not None:
                batches = _reader.device_buffered(
                    batches, size=n_prefetch, compiled=compiled)()
            else:
                try:
                    device = self._device_cached()
                except Exception:
                    device = None  # no jax backend: prefetch host-side only
                batches = _reader.device_buffered(
                    batches, size=n_prefetch, device=device)()
        # overlapped SPARSE prefetch: in async (Communicator) mode batch
        # N+1's per-table PS pulls run behind batch N's device compute
        # (the sparse analog of the dense overlap below; same
        # ps_pull_overlap_s accounting, same bounded-staleness trade —
        # sync mode keeps the strict pull-after-push ordering)
        if (getattr(prog_obj, "_distributed_tables", None)
                and getattr(prog_obj, "_ps_communicator", None) is not None
                and getattr(prog_obj, "_sparse_overlap", True)):
            batches = self._sparse_overlap_iter(prog_obj, batches)
        if led is not None:
            # data_wait attribution: each next() on the (possibly
            # prefetch-wrapped) iterator, minus whatever the nested
            # sparse-prefetch join already charged to ps_wait
            batches = led.timed_iter(batches)
        # dense-PS async mode: overlap each step's host param pull with
        # the device compute (the pull thread runs while the chip works;
        # ps_pull_overlap_s counts the hidden seconds).  Sync mode keeps
        # the strict barrier ordering, so the flag only arms async runs.
        ps_ctx = getattr(prog_obj, "_dense_ps_ctx", None)
        overlap_prev = None
        if ps_ctx is not None and not ps_ctx.get("sync", True):
            overlap_prev = ps_ctx.get("overlap_pull")
            ps_ctx["overlap_pull"] = True
        # epoch trace id: minted per call (or joined via trace_id=) so a
        # training epoch's span chain is correlatable like a serving
        # request; the epoch span id parents every step span.  Gated per
        # step on the same single recording() flag the run() phases use —
        # the untraced loop pays two attribute checks, nothing else.
        from paddle_tpu.monitor import flight as _mon_flight

        tid = trace_id or _mon_flight.new_trace_id()
        self.last_train_trace_id = tid
        epoch_sid = None
        epoch_t0 = None
        n_steps = 0
        results = []
        _monitoring = (led is not None or wd is not None
                       or steplog is not None)
        self._train_ledger = led  # arm run()'s phase charges (or clear)
        _t_prev = time.perf_counter()
        try:
            for i, feed in enumerate(batches):
                step = start_step + i  # global step (resume-aware cursor)
                if _mon_spans.recording():
                    if epoch_sid is None:
                        epoch_sid = _mon_spans.new_span_id()
                        epoch_t0 = time.perf_counter()
                    _t0 = time.perf_counter()
                    with _mon_spans.trace_context((tid,)):
                        with _mon_spans.parent_scope(epoch_sid):
                            with _mon_spans.parent_scope() as step_sid:
                                out = self.run(
                                    program, feed=feed,
                                    fetch_list=fetch_list, scope=scope)
                            _mon_spans.record_span(
                                "executor/train_step", _t0,
                                time.perf_counter() - _t0, cat="train",
                                span_id=step_sid, step=step)
                else:
                    out = self.run(program, feed=feed, fetch_list=fetch_list, scope=scope)
                n_steps += 1
                _t_now = time.perf_counter()
                _dur = _t_now - _t_prev  # step period incl. data_wait
                _t_prev = _t_now
                _MON_TRAIN_STEP_HIST.observe(
                    _dur, exemplar={"trace_id": tid})
                if fetch_list:
                    results.append(out)
                    if debug and i % print_period == 0:
                        names = fetch_info or [ _as_fetch_name(f) for f in fetch_list]
                        # stdout stays (the chaos drills parse it); the
                        # event makes the same progress line scrapeable
                        # via /eventz and the step log
                        print("batch %d:" % step, dict(zip(names, [np.asarray(o) for o in out])))
                        _mon_events.emit(
                            "train/progress", severity="info",
                            message="batch %d: %s" % (step, {
                                n: float(np.mean(v))
                                for n, v in zip(names, out)
                                if np.issubdtype(
                                    np.asarray(v).dtype, np.number)
                            }),
                            cat="train", step=step)
                if _monitoring:
                    _ex = _mon_train.batch_examples(feed)
                    loss_val = None
                    if out and fetch_list:
                        _li = wd.loss_index if wd is not None else 0
                        try:
                            loss_val = float(np.mean(out[_li]))
                        except (TypeError, ValueError, IndexError):
                            loss_val = None
                    row = None
                    if led is not None:
                        if led.flops_per_step is None:
                            # static-FLOPs MFU numerator, resolved once
                            # against the first batch's leading dim
                            led.flops_per_step = (
                                _mon_train.estimate_block_flops(
                                    prog_obj, batch=max(1, _ex)))
                        row = led.step_done(
                            step, _dur, examples=_ex, loss=loss_val)
                    anomalies = ()
                    if wd is not None:
                        anomalies = wd.observe_step(
                            step, loss=loss_val, step_time_s=_dur)
                    if steplog is not None:
                        rec = (dict(row) if row is not None
                               else {"step": step,
                                     "duration_s": round(_dur, 6),
                                     "examples": _ex})
                        if loss_val is not None and "loss" not in rec:
                            rec["loss"] = loss_val
                        if anomalies:
                            rec["anomalies"] = list(anomalies)
                        rec["trace_id"] = tid
                        steplog.write(rec)
                    if wd is not None and anomalies:
                        # typed halt (TrainAnomalyError) for kinds in
                        # halt_on — after the step is logged, so the
                        # fatal step is in the record
                        wd.raise_if_halt(anomalies)
                if ckpt is not None and ckpt.should_save(step + 1):
                    _led_tok = (led.window_begin()
                                if led is not None else None)
                    self._train_checkpoint(
                        ckpt, prog_obj, scope or global_scope(),
                        step + 1, int(checkpoint_epoch), ps_ctx,
                        async_=bool(checkpoint_async), compiled=compiled)
                    if _led_tok is not None:
                        # foreground cost only: quiesce + (sync) write or
                        # (async) copy-on-write snapshot.  The quiesce's
                        # dense-pull join stays in ps_wait (exclusive
                        # window) — checkpoint is the save itself.
                        led.window_end(_led_tok, "checkpoint",
                                       detail="sync")
            if ckpt is not None:
                # commit the tail background save before returning (a
                # write error surfaces here, on the epoch's own path)
                _led_tok = led.window_begin() if led is not None else None
                ckpt.wait()
                if _led_tok is not None:
                    # async-commit join: the tail of the background
                    # serialization the step loop didn't hide
                    led.window_end(_led_tok, "checkpoint",
                                   detail="commit")
            if led is not None:
                # clean exit: close the ledger strictly — the remainder
                # lands in `other` and the 1% sum contract is asserted
                led.finish_epoch()
        finally:
            self._train_ledger = None  # disarm run()'s phase charges
            if led is not None:
                # exceptional exit: close the ledger WITHOUT the sum
                # assert (the epoch's own error must propagate; a
                # partial ledger is still worth reading in /trainz)
                led.finish_epoch(strict=False)
            if steplog is not None:
                steplog.close()
            if ckpt is not None and ckpt.in_flight:
                # abnormal exit with a save still writing: join so the
                # writer can't race teardown; the epoch's primary error
                # stays the one that propagates
                try:
                    ckpt.wait()
                except BaseException:  # noqa: BLE001 — deliberate
                    pass
            if epoch_sid is not None:
                with _mon_spans.trace_context((tid,)):
                    _mon_spans.record_span(
                        "executor/train_epoch", epoch_t0,
                        time.perf_counter() - epoch_t0, cat="train",
                        span_id=epoch_sid, steps=n_steps)
            closer = getattr(batches, "close", None)
            if closer is not None:
                closer()  # stop the prefetch producer (GeneratorExit path)
            if ps_ctx is not None:
                # drain the in-flight pull so the scope leaves with the
                # freshest params and no dangling thread, then CLOSE the
                # pull thread's dedicated client — its sockets must not
                # outlive the epoch on any exit path (a fresh epoch
                # redials)
                try:
                    self._dense_ps_join_pending(ps_ctx, scope or global_scope())
                finally:
                    if overlap_prev is None:
                        ps_ctx.pop("overlap_pull", None)
                    else:
                        ps_ctx["overlap_pull"] = overlap_prev
                    pull_client = ps_ctx.get("_pull_client")
                    if pull_client is not None:
                        pull_client.close()  # next epoch redials
        return results

    def _train_checkpoint(self, ckpt, program, scope, step, epoch,
                          ps_ctx, async_: bool = False,
                          compiled=None) -> None:
        """Quiesce async PS state, then commit one atomic checkpoint.
        The overlapped dense-PS pull is joined (its params land in the
        scope first) and the async Communicator is flushed (every queued
        sparse grad reaches the server) so the saved params, PS rows,
        and cursor describe the SAME step.  ``async_``: snapshot on this
        thread (copy-on-write gather), serialize + commit on the
        checkpoint's background writer — the step resumes immediately.
        ``compiled``: the CompiledProgram of a mesh-sharded run — its
        state then checkpoints SHARD-wise (each device's addressable
        shards; no full-tensor host gather)."""
        if ps_ctx is not None:
            self._dense_ps_join_pending(ps_ctx, scope)
        comm = getattr(program, "_ps_communicator", None)
        if comm is not None:
            comm.flush()
        saver = ckpt.save_async if async_ else ckpt.save
        saver(program, scope, step=step, epoch=epoch,
              ps_client=getattr(program, "_ps_client", None),
              compiled=compiled)

    # ------------------------------------------------------------------
    # training control tower: the trainer's scrapeable surface
    # ------------------------------------------------------------------
    def start_train_admin(self, host: str = "127.0.0.1", port: int = 0):
        """Serve this trainer's observability surface over HTTP
        (``port=0`` = ephemeral; returns the bound ``(host, port)``):
        ``/metrics`` (Prometheus/OpenMetrics with exemplars),
        ``/trainz`` (ledger snapshot + last-N step table + watchdog
        state + checkpoint/resume history), ``/statusz``, ``/tracez``,
        ``/eventz``, ``/healthz``.  The same document shapes the fleet
        federation scraper consumes — register the returned address via
        ``FleetBalancer.add_scrape_target`` and the trainer shows up in
        the fleet pane next to the serving backends."""
        return _mon_train.start_train_admin(self, host=host, port=port)

    def stop_train_admin(self) -> None:
        _mon_train.stop_train_admin(self)

    @property
    def train_admin_address(self):
        srv = self._train_admin
        return srv.server_address if srv is not None else None

    def trainz(self):
        """The ``/trainz`` document (see ``monitor.train.trainz_doc``)."""
        return _mon_train.trainz_doc(self)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        return self.train_from_dataset(
            program, dataset, scope, thread, debug, fetch_list, fetch_info, print_period
        )

    # ------------------------------------------------------------------
    def jit_cache_stats(self) -> Dict[str, int]:
        """Compile-cache accounting for this executor.

        ``misses`` counts newly-built jitted entries (each one is an XLA
        compile on its first dispatch); ``hits`` counts runs served by an
        existing entry; ``entries`` is the live cache size.  Serving's
        zero-recompiles-after-warmup assertion diffs ``misses`` across a
        workload (paddle_tpu/serving/server.py).  ``plan_*`` mirror the
        same accounting for the run-plan cache (the hoisted per-run block
        analysis), and ``dispatch_overhead_s`` accumulates the host-side
        seconds run() spent before each jitted dispatch.
        """
        return {
            "entries": len(self._cache),
            "hits": self._cache_stats["hits"],
            "misses": self._cache_stats["misses"],
            "jit_evictions": self._cache_stats["jit_evictions"],
            "plan_entries": len(self._plans),
            "plan_hits": self._cache_stats["plan_hits"],
            "plan_misses": self._cache_stats["plan_misses"],
            "plan_evictions": self._cache_stats["plan_evictions"],
            "dispatch_overhead_s": self._cache_stats["dispatch_overhead_s"],
            "ps_pull_overlap_s": self._cache_stats["ps_pull_overlap_s"],
            "ps_pull_wait_s": self._cache_stats["ps_pull_wait_s"],
        }

    # ------------------------------------------------------------------
    def close(self):
        self._cache.clear()
        self._plans.clear()


class AsyncExecutor:
    """Legacy filelist-driven trainer facade (reference:
    framework/async_executor.h:62 + executor_thread_worker.cc — pre-
    Trainer API that ran ExecutorThreadWorker threads over a Dataset).

    On TPU the compiled step IS the device worker, so this delegates to
    Executor.train_from_dataset over a Dataset built from the filelist —
    same API shape, one compiled module instead of thread workers.
    """

    def __init__(self, place=None):
        self._exe = Executor(place)

    def run(self, program, data_feed, filelist, thread_num=1, fetch_list=None,
            fetch_info=None, debug=False, mode="", scope=None):
        from paddle_tpu.fluid_dataset import DatasetFactory

        slots = getattr(data_feed, "slots", None)
        if not slots:
            raise ValueError(
                "AsyncExecutor needs a data_feed with a .slots list of the "
                "program's input Variables (DataFeedDesc analog)"
            )
        if isinstance(filelist, str):
            filelist = [filelist]
        dataset = DatasetFactory().create_dataset("InMemoryDataset")
        dataset.set_use_var(slots)
        dataset.set_filelist(list(filelist))
        if hasattr(dataset, "load_into_memory"):
            dataset.load_into_memory()
        return self._exe.train_from_dataset(
            program=program, dataset=dataset, scope=scope,
            fetch_list=fetch_list, fetch_info=fetch_info, debug=debug,
        )
