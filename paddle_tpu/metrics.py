"""Streaming Python-side metrics (reference: python/paddle/fluid/metrics.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "Accuracy", "Auc", "Precision", "Recall", "CompositeMetric", "ChunkEvaluator", "DetectionMAP", "EditDistance"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).item()) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated into Accuracy metric")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        a = self.tp + self.fn
        return float(self.tp) / a if a else 0.0


class Auc(MetricBase):
    """Streaming AUC via threshold histogram (reference: metrics.py Auc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, dtype=np.int64)
        self._stat_neg = np.zeros(n, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bins = np.minimum((pos_prob * self._num_thresholds).astype(int), self._num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).item())
        self.num_label_chunks += int(np.asarray(num_label_chunks).item())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).item())

    def eval(self):
        precision = self.num_correct_chunks / self.num_infer_chunks if self.num_infer_chunks else 0.0
        recall = self.num_correct_chunks / self.num_label_chunks if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) if precision + recall else 0.0
        return precision, recall, f1


class DetectionMAP(MetricBase):
    """Streaming mean-average-precision over batches (reference:
    python/paddle/fluid/metrics.py DetectionMAP + evaluator.py; the
    per-batch matching mirrors operators/detection/detection_map_op.cc).

    ``update(detections, gt_labels, gt_boxes)`` consumes the padded
    convention: detections [N, K, 6] (label, score, x1, y1, x2, y2 with
    label -1 padding, e.g. multiclass_nms output), gt_labels [N, B],
    gt_boxes [N, B, 4] (zero-area rows are padding).  ``eval()`` returns
    the mAP over every class seen so far.
    """

    def __init__(self, class_num, overlap_threshold=0.5,
                 ap_version="integral", background_label=0, name=None):
        super().__init__(name)
        if ap_version not in ("integral", "11point"):
            raise ValueError("ap_version must be 'integral' or '11point'")
        self.class_num = int(class_num)
        self.overlap_threshold = float(overlap_threshold)
        self.ap_version = ap_version
        self.background_label = background_label
        self.reset()

    def reset(self):
        # per class: number of gt boxes + (score, is_tp) records
        self._n_gt = np.zeros(self.class_num, np.int64)
        self._records = [[] for _ in range(self.class_num)]

    @staticmethod
    def _iou(a, b):
        ix = min(a[2], b[2]) - max(a[0], b[0])
        iy = min(a[3], b[3]) - max(a[1], b[1])
        inter = max(ix, 0.0) * max(iy, 0.0)
        ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
        return inter / ua if ua > 0 else 0.0

    def update(self, detections, gt_labels, gt_boxes):
        det = np.asarray(detections)
        gl = np.asarray(gt_labels)
        gb = np.asarray(gt_boxes)
        if gl.ndim == 3:
            gl = gl[..., 0]
        N = det.shape[0]
        for n in range(N):
            valid_gt = (gb[n, :, 2] - gb[n, :, 0] > 1e-6) & (
                gb[n, :, 3] - gb[n, :, 1] > 1e-6
            )
            for c in range(self.class_num):
                if c == self.background_label:
                    continue  # excluded from mAP, like the detection_map op
                gt_idx = np.nonzero(valid_gt & (gl[n] == c))[0]
                self._n_gt[c] += len(gt_idx)
                dets_c = [
                    (float(d[1]), d[2:6])
                    for d in det[n]
                    if int(d[0]) == c and d[1] > -1
                ]
                dets_c.sort(key=lambda t: -t[0])
                used = set()
                for score, box in dets_c:
                    # VOC matching (detection_map_op.cc): judge against
                    # the overall max-IoU gt; if it's taken -> FP (no
                    # fall-through to the next-best gt)
                    best, best_iou = -1, 0.0
                    for gi in gt_idx:
                        iou = self._iou(box, gb[n, gi])
                        if iou > best_iou:
                            best, best_iou = gi, iou
                    if (
                        best >= 0
                        and best_iou >= self.overlap_threshold
                        and best not in used
                    ):
                        used.add(best)
                        self._records[c].append((score, 1))
                    else:
                        self._records[c].append((score, 0))

    def eval(self):
        aps, n_classes = [], 0
        for c in range(self.class_num):
            if self._n_gt[c] == 0 or c == self.background_label:
                continue
            n_classes += 1
            recs = sorted(self._records[c], key=lambda t: -t[0])
            tp = np.cumsum([r[1] for r in recs]) if recs else np.zeros(0)
            fp = np.cumsum([1 - r[1] for r in recs]) if recs else np.zeros(0)
            if len(recs) == 0:
                aps.append(0.0)
                continue
            recall = tp / max(self._n_gt[c], 1)
            precision = tp / np.maximum(tp + fp, 1e-10)
            if self.ap_version == "11point":
                ap = np.mean([
                    max(precision[recall >= r], default=0.0)
                    if (recall >= r).any() else 0.0
                    for r in np.linspace(0, 1, 11)
                ])
            else:
                drecall = np.diff(recall, prepend=0.0)
                ap = float(np.sum(precision * drecall))
            aps.append(float(ap))
        return float(np.mean(aps)) if n_classes else 0.0


class EditDistance(MetricBase):
    """Streaming average edit distance (reference: fluid/metrics.py
    EditDistance) — feed the edit_distance op's (distances,
    seq_num) per batch."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        import numpy as np

        d = np.asarray(distances).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(np.asarray(seq_num))
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError("no data fed to EditDistance")
        return (self.total_distance / self.seq_num,
                self.instance_error / self.seq_num)
