"""Streaming Python-side metrics (reference: python/paddle/fluid/metrics.py)."""
from __future__ import annotations

import numpy as np

__all__ = ["MetricBase", "Accuracy", "Auc", "Precision", "Recall", "CompositeMetric", "ChunkEvaluator"]


class MetricBase:
    def __init__(self, name=None):
        self._name = name or self.__class__.__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError


class Accuracy(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight):
        self.value += float(np.asarray(value).item()) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("no data updated into Accuracy metric")
        return self.value / self.weight


class Precision(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        ap = self.tp + self.fp
        return float(self.tp) / ap if ap else 0.0


class Recall(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(int).reshape(-1)
        labels = np.asarray(labels).astype(int).reshape(-1)
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        a = self.tp + self.fn
        return float(self.tp) / a if a else 0.0


class Auc(MetricBase):
    """Streaming AUC via threshold histogram (reference: metrics.py Auc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        n = self._num_thresholds + 1
        self._stat_pos = np.zeros(n, dtype=np.int64)
        self._stat_neg = np.zeros(n, dtype=np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        if preds.ndim == 2 and preds.shape[1] == 2:
            pos_prob = preds[:, 1]
        else:
            pos_prob = preds.reshape(-1)
        bins = np.minimum((pos_prob * self._num_thresholds).astype(int), self._num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (tot_pos + new_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0


class CompositeMetric(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks, num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).item())
        self.num_label_chunks += int(np.asarray(num_label_chunks).item())
        self.num_correct_chunks += int(np.asarray(num_correct_chunks).item())

    def eval(self):
        precision = self.num_correct_chunks / self.num_infer_chunks if self.num_infer_chunks else 0.0
        recall = self.num_correct_chunks / self.num_label_chunks if self.num_label_chunks else 0.0
        f1 = 2 * precision * recall / (precision + recall) if precision + recall else 0.0
        return precision, recall, f1
