"""Runtime Scope: name -> device array store.

Reference: paddle/fluid/framework/scope.h:46 (hierarchical Variable maps)
and variable.h:26.  On TPU only *persistable* values (parameters, optimizer
state, LR) ever live in the scope — intermediates stay inside the compiled
XLA module and never materialize in HBM as named buffers.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

__all__ = ["Scope", "global_scope", "scope_guard"]


class _TensorView:
    """Mimics the reference's LoDTensor pybind surface (get_tensor())."""

    def __init__(self, scope: "Scope", name: str):
        self._scope = scope
        self._name = name

    def __array__(self, dtype=None):
        arr = np.asarray(self._scope.vars[self._name])
        return arr.astype(dtype) if dtype is not None else arr

    def set(self, value, place=None):
        import jax.numpy as jnp

        self._scope.vars[self._name] = jnp.asarray(value)

    def shape(self):
        return list(np.shape(self._scope.vars[self._name]))


class _VarView:
    def __init__(self, scope: "Scope", name: str):
        self._scope = scope
        self._name = name

    def get_tensor(self) -> _TensorView:
        return _TensorView(self._scope, self._name)


class Scope:
    def __init__(self, parent: Optional["Scope"] = None):
        self.vars: Dict[str, Any] = {}
        self.parent = parent
        self.kids = []

    def new_scope(self) -> "Scope":
        s = Scope(self)
        self.kids.append(s)
        return s

    def find_var(self, name: str) -> Optional[_VarView]:
        s = self
        while s is not None:
            if name in s.vars:
                return _VarView(s, name)
            s = s.parent
        return None

    def var(self, name: str) -> _VarView:
        if self._lookup(name) is None and name not in self.vars:
            self.vars[name] = None
        return _VarView(self, name)

    def _lookup(self, name: str):
        s = self
        while s is not None:
            if name in s.vars:
                return s.vars[name]
            s = s.parent
        return None

    def get(self, name: str):
        return self._lookup(name)

    def set(self, name: str, value):
        self.vars[name] = value

    def drop_kids(self):
        self.kids = []

    def local_var_names(self):
        return list(self.vars.keys())


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope() -> Scope:
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope: Scope):
        self._scope = scope

    def __enter__(self):
        _scope_stack.append(self._scope)
        return self._scope

    def __exit__(self, *exc):
        _scope_stack.pop()
