"""Multi-process launcher.

Reference: python/paddle/distributed/launch.py:132-214 — computes
``PADDLE_TRAINER_ENDPOINTS / PADDLE_TRAINER_ID / PADDLE_CURRENT_ENDPOINT``
and spawns one worker process per device.  On TPU the unit is one process
per *host* (a process owns all local chips through the jax runtime), so
``--nproc_per_node`` defaults to 1; the env contract is kept verbatim so
fleet role makers (parallel/fleet.py PaddleCloudRoleMaker) port
unchanged.

Usage:  python -m paddle_tpu.distributed.launch --cluster_node_ips=a,b \
            --node_ip=a train.py --args
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
from typing import List

__all__ = ["launch", "start_procs"]


def _parse_args(argv=None):
    p = argparse.ArgumentParser(description="paddle_tpu distributed launcher")
    p.add_argument("--cluster_node_ips", type=str, default="127.0.0.1")
    p.add_argument("--node_ip", type=str, default="127.0.0.1")
    p.add_argument("--started_port", type=int, default=6170)
    p.add_argument("--nproc_per_node", type=int, default=1)
    p.add_argument("--log_dir", type=str, default=None)
    p.add_argument("training_script", type=str)
    p.add_argument("training_script_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def start_procs(args) -> List[subprocess.Popen]:
    """reference: launch.py:132."""
    node_ips = [ip for ip in args.cluster_node_ips.split(",") if ip]
    node_id = node_ips.index(args.node_ip)
    n_local = args.nproc_per_node

    all_endpoints = []
    for ip in node_ips:
        for i in range(n_local):
            all_endpoints.append("%s:%d" % (ip, args.started_port + i))

    if args.log_dir:
        os.makedirs(args.log_dir, exist_ok=True)

    procs = []
    for local_rank in range(n_local):
        rank = node_id * n_local + local_rank
        env = dict(os.environ)
        env.update(
            {
                "PADDLE_TRAINER_ID": str(rank),
                "PADDLE_CURRENT_ENDPOINT": all_endpoints[rank],
                "PADDLE_TRAINERS_NUM": str(len(all_endpoints)),
                "PADDLE_TRAINER_ENDPOINTS": ",".join(all_endpoints),
                "FLAGS_selected_tpus": str(local_rank),
            }
        )
        cmd = [sys.executable, "-u", args.training_script] + args.training_script_args
        out = None
        if args.log_dir:
            out = open(os.path.join(args.log_dir, "workerlog.%d" % rank), "w")
        procs.append(subprocess.Popen(cmd, env=env, stdout=out, stderr=out))
    return procs


def launch(argv=None):
    """reference: launch.py:214."""
    args = _parse_args(argv)
    procs = start_procs(args)

    def terminate(signum, frame):
        for p in procs:
            if p.poll() is None:
                p.terminate()

    signal.signal(signal.SIGTERM, terminate)
    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    return rc


if __name__ == "__main__":
    sys.exit(launch())
