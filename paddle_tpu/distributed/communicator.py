"""Async parameter-server communication (reference:
operators/distributed/communicator.h:160 — background send threads with
per-var queues and merge-before-send) and geo-SGD (reference:
DistributeTranspilerConfig geo mode, distribute_transpiler.py:131 —
periodic parameter-delta sync instead of per-step grad push).

TPU-native role: the compiled step stays synchronous on-device; what
goes async is the HOST side — sparse grad pushes drain through a
background thread so the next step's compute overlaps the PS round
trip, at the cost of bounded staleness (the reference's async mode
trade, listen_and_serv RunAsyncLoop).
"""
from __future__ import annotations

import queue
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.distributed.ps import PSClient
from paddle_tpu.faults.retry import RetryPolicy

__all__ = ["Communicator", "GeoSGD"]


class Communicator:
    """Background sparse-grad pusher with per-table merge queues.

    ``push`` enqueues and returns immediately; the send thread drains a
    table's queue, merges duplicate ids (grad sum — the reference's
    merge-before-send), and issues one PS push.  ``max_merge`` bounds
    staleness: at most that many batches are merged into one send.

    The send thread owns a DEDICATED ``PSClient`` (opened at thread
    start, closed in its ``finally`` on every exit path — a stopped or
    crashed communicator must not leak sockets) so its pushes never
    interleave frames with ``flush()``'s on the caller's client.
    """

    def __init__(self, client: PSClient, max_merge: int = 20, capacity: int = 200,
                 max_retries: int = 3):
        self._client = client
        self._queues: Dict[str, queue.Queue] = {}
        self._max_merge = max_merge
        self._capacity = capacity
        # bounded transient-failure retry (reference: grpc_client.cc send
        # deadline + retry) — shared RetryPolicy semantics: exponential
        # backoff with full jitter, one budget per merged send
        self._retry_policy = RetryPolicy(
            max_attempts=max(1, int(max_retries)),
            base_delay_s=0.2, multiplier=2.0, max_delay_s=2.0)
        self._dropped = 0  # batches lost to a full queue after retries
        self._lock = threading.Lock()
        # serializes PS pushes between the send thread and flush() — the
        # merge queues' pop-and-push must stay atomic for the flush
        # barrier even though each side pushes on its own client
        self._send_lock = threading.Lock()
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._send_client: Optional[PSClient] = None  # the thread's own
        self._error: Optional[BaseException] = None
        # post-apply hook: called (table, ids) AFTER a merged push has
        # landed server-side — the embedding cache invalidates here, not
        # at enqueue time (the rows only change when the send applies)
        self.on_pushed = None

    # -- lifecycle (reference: Communicator::Start/Stop) --
    def start(self):
        self._running = True
        self._thread = threading.Thread(target=self._send_loop, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=30)
            self._thread = None
        self.flush()

    def push(self, table: str, ids: np.ndarray, grads: np.ndarray):
        if self._error is not None:
            # surface but DON'T clear: a concurrent flush() must also see
            # it; only flush() (the barrier) acknowledges and resets
            raise self._error
        with self._lock:
            q = self._queues.setdefault(table, queue.Queue(self._capacity))
        try:
            q.put((np.asarray(ids).reshape(-1), np.asarray(grads)), timeout=60)
        except queue.Full:
            raise RuntimeError(
                "Communicator queue for %r full for 60s — PS unreachable?" % table
            )

    def flush(self):
        """Drain everything synchronously (barrier before eval/save).
        Loops until each queue is empty; the send lock serializes with
        any in-flight background push, so on return all enqueued grads
        are on the server."""
        for table in list(self._queues):
            while self._drain(table, block=False):
                pass
        # wait out an in-flight background push
        with self._send_lock:
            pass
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def pending(self) -> int:
        return sum(q.qsize() for q in self._queues.values())

    @property
    def dropped(self) -> int:
        """Batches lost because the re-enqueue after a failed send found
        the queue full — nonzero means grads were lost."""
        return self._dropped

    # -- internals --
    def _drain(self, table: str, block: bool, client: Optional[PSClient] = None) -> bool:
        # pop AND push under the send lock: flush()'s empty-queue +
        # lock-acquire check must never observe a popped-but-unpushed
        # batch (that would break its barrier guarantee)
        q = self._queues[table]
        client = client if client is not None else self._client
        with self._send_lock:
            batch: List = []
            try:
                batch.append(q.get(timeout=0.05 if block else 0))
            except queue.Empty:
                return False
            while len(batch) < self._max_merge:
                try:
                    batch.append(q.get_nowait())
                except queue.Empty:
                    break
            ids = np.concatenate([b[0] for b in batch])
            grads = np.concatenate([b[1].reshape(len(b[0]), -1) for b in batch])
            # PSClient.push_sparse dedups+sums — the merge.  Transient PS
            # errors get a RetryPolicy budget (exponential backoff + full
            # jitter); if the send still fails the merged batch
            # re-enqueues so no grads are lost, and only when the queue
            # itself is full do we count a drop.
            budget = self._retry_policy.budget(op="communicator.push")
            try:
                budget.call(
                    lambda: client.push_sparse(table, ids, grads))
                if self.on_pushed is not None:
                    self.on_pushed(table, ids)
                return True
            except Exception:  # noqa: BLE001 — network layer
                try:
                    q.put_nowait((ids, grads))
                except queue.Full:
                    self._dropped += len(batch)
                raise

    def _send_loop(self):
        import time

        # the thread's own client: concurrent flush() pushes ride the
        # caller's client, this one closes in the finally on EVERY exit
        # path (stop, crash) — no socket leak per abandoned communicator.
        # A duck-typed client (tests, in-memory stubs) has no endpoints
        # to redial: share it and own nothing.
        if isinstance(self._client, PSClient):
            client = self._send_client = PSClient(list(self._client.endpoints))
            own = True
        else:
            client = self._send_client = self._client
            own = False
        try:
            while self._running:
                any_sent = False
                for table in list(self._queues):
                    try:
                        any_sent |= self._drain(table, block=True,
                                                client=client)
                    except Exception as e:
                        # surface on next push/flush but KEEP the thread
                        # alive — a transient PS error must not turn into a
                        # silent dead queue (the batch re-enqueued in _drain)
                        self._error = e
                        time.sleep(0.5)
                if not any_sent and not self._queues:
                    time.sleep(0.01)
        finally:
            if own:
                client.close()


class GeoSGD:
    """Geo-SGD periodic delta sync for dense params (reference: geo mode
    of DistributeTranspiler — trainers run local SGD and every
    ``sync_every`` steps push (param - snapshot)/num_trainers to the PS
    and pull the merged global params back).

    Each param maps to one PS table (rows = flattened param chunks);
    the server applies the delta with lr=1 sgd, so pushes from all
    trainers accumulate.
    """

    def __init__(self, program, scope, client_or_endpoints, num_trainers: int = 1,
                 trainer_id: int = 0, sync_every: int = 10, table_prefix: str = "geo"):
        self._program = program
        self._scope = scope
        self._client = (
            client_or_endpoints
            if isinstance(client_or_endpoints, PSClient)
            else PSClient(list(client_or_endpoints))
        )
        self._n = max(1, int(num_trainers))
        self._trainer_id = int(trainer_id)
        self._every = max(1, int(sync_every))
        self._prefix = table_prefix
        self._params = [p.name for p in program.all_parameters()]
        self._shapes = {}
        self._snap: Dict[str, np.ndarray] = {}
        self._step = 0

    def _table(self, name: str) -> str:
        return "%s/%s" % (self._prefix, name)

    _SEED_FLAG = "__seeded__"

    def init_worker(self, timeout: float = 60.0):
        """Create tables; trainer 0 seeds the server with its initial
        params and raises a 'seeded' flag table, other trainers WAIT for
        the flag then pull — deterministic rank-0 init broadcast like the
        reference's pserver startup, no barrier-count guessing."""
        import time

        for n in self._params:
            val = np.asarray(self._scope.get(n), np.float32)
            self._shapes[n] = val.shape
            flat = val.reshape(val.shape[0], -1) if val.ndim > 1 else val.reshape(1, -1)
            self._client.create_table(
                self._table(n), flat.shape[1], initializer="zeros",
                optimizer="sgd", lr=1.0,
            )
            self._snap[n] = val.copy()
        flag = self._table(self._SEED_FLAG)
        self._client.create_table(flag, 1, initializer="zeros", optimizer="sgd", lr=1.0)
        if self._trainer_id == 0:
            for n in self._params:
                val = self._snap[n]
                flat = val.reshape(val.shape[0], -1) if val.ndim > 1 else val.reshape(1, -1)
                ids = np.arange(flat.shape[0], dtype=np.int64)
                self._client.push_sparse(self._table(n), ids, -flat)  # row -= 1*(-v)
            self._client.push_sparse(flag, np.zeros(1, np.int64), -np.ones((1, 1), np.float32))
        else:
            deadline = time.time() + timeout
            while True:
                rows = self._client.pull_sparse(flag, np.zeros(1, np.int64))
                if rows is not None and float(rows[0, 0]) > 0:
                    break
                if time.time() > deadline:
                    raise RuntimeError("geo-SGD: trainer 0 never seeded the server")
                time.sleep(0.05)
            self.pull_all()
            for n in self._params:
                self._snap[n] = np.asarray(self._scope.get(n), np.float32).copy()
        return self

    def pull_all(self):
        import jax.numpy as jnp

        for n in self._params:
            shape = self._shapes[n]
            rows = shape[0] if len(shape) > 1 else 1
            ids = np.arange(rows, dtype=np.int64)
            flat = self._client.pull_sparse(self._table(n), ids)
            self._scope.set(n, jnp.asarray(flat.reshape(shape)))

    def step(self):
        """Call after each local train step; every sync_every steps the
        delta goes up and the merged params come down."""
        self._step += 1
        if self._step % self._every:
            return False
        import jax.numpy as jnp

        for n in self._params:
            cur = np.asarray(self._scope.get(n), np.float32)
            delta = (cur - self._snap[n]) / self._n
            flat = delta.reshape(delta.shape[0], -1) if delta.ndim > 1 else delta.reshape(1, -1)
            ids = np.arange(flat.shape[0], dtype=np.int64)
            self._client.push_sparse(self._table(n), ids, -flat)  # row += delta
        self.pull_all()
        for n in self._params:
            self._snap[n] = np.asarray(self._scope.get(n), np.float32).copy()
        return True
