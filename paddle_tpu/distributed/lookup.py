"""Distributed lookup-table binding (reference:
transpiler/distribute_lookup_table.py + operators/distributed/
parameter_prefetch.cc).

``layers.embedding(is_distributed=True)`` records table metadata on the
program; this module connects those tables to parameter servers and the
executor does pull-before/push-after around each compiled step
(executor.py _prefetch_distributed_tables).  The server applies the
optimizer on push (listen_and_serv optimize sub-blocks analog), so pass
the lr that matches the trainer-side optimizer for the dense params.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

from paddle_tpu.distributed.ps import PSClient

__all__ = ["bind_distributed_tables"]


def bind_distributed_tables(
    program,
    endpoints_or_client: Union[Sequence[str], PSClient],
    optimizer: str = "sgd",
    lr: float = 0.1,
    initializer: str = "uniform",
    seed: int = 0,
    async_mode: bool = False,
    id_bucket_ladder: Optional[Sequence[int]] = None,
):
    """Create each of ``program``'s distributed tables on the servers and
    attach the client so the executor can prefetch/push.  Returns the
    client.

    ``async_mode``: grad pushes drain through a background Communicator
    (reference: communicator.h async PS) — next step's pull may miss the
    newest grads (bounded staleness); call
    ``program._ps_communicator.flush()`` before eval/save.  Async mode
    also arms the OVERLAPPED sparse prefetch in ``train_from_dataset``
    (batch N+1's pulls run behind batch N's device compute).

    ``id_bucket_ladder``: an explicit unique-id-count bucket ladder for
    the prefetch (the offline ``autotune.propose_id_bucket_ladder``
    output); without it unique counts pad to power-of-two buckets.
    Unique counts above the ladder's top rung fall back to power-of-two
    (a compile, so size the ladder from a representative histogram)."""
    tables = getattr(program, "_distributed_tables", None)
    if not tables:
        raise ValueError("program has no distributed lookup tables")
    client = (
        endpoints_or_client
        if isinstance(endpoints_or_client, PSClient)
        else PSClient(list(endpoints_or_client))
    )
    seen = set()
    for meta in tables.values():
        name = meta["table"]
        if name in seen:  # tied embeddings share one server table
            continue
        seen.add(name)
        client.create_table(
            name, meta["dim"], initializer=initializer, seed=seed,
            optimizer=optimizer, lr=lr,
        )
    program._ps_client = client
    if id_bucket_ladder is not None:
        program._sparse_id_ladder = sorted(
            int(b) for b in id_bucket_ladder)
    if async_mode:
        from paddle_tpu.distributed.communicator import Communicator

        # own connections: the send thread must not interleave frames on
        # the executor's pull sockets
        program._ps_communicator = Communicator(PSClient(client.endpoints)).start()
    return client
