"""Parameter server for sparse tables (host-side, over TCP).

Reference: the PS stack in paddle/fluid/operators/distributed/ — gRPC
SendRecvService (send_recv.proto.in:19-33 SendVariable/GetVariable/
PrefetchVariable), request_handler_impl.cc (server-side optimize),
parameter_prefetch.cc (row-wise sparse lookup), listen_and_serv_op.cc.

TPU-native role: dense parameters live in HBM and sync via ICI
collectives (no PS needed); the PS remains the right tool for *huge
sparse embedding tables* that exceed HBM — rows live on host-CPU servers
sharded by id, trainers prefetch rows before the compiled step and push
sparse grads after (BASELINE.md DeepFM config).

Wire format: length-framed messages of a JSON header plus raw ndarray
payload bytes — the gRPC+protobuf tensor serde analog (reference:
sendrecvop_utils.cc / variable_response.cc).  No pickle: nothing on the
wire can execute code, dtypes are whitelisted, and message size is
bounded, so an exposed port is a data-plane risk only (like the
reference's unauthenticated gRPC PS).  Swap in a C++ server without
changing the client API.
"""
from __future__ import annotations

import json
import socket
import socketserver
import struct
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import faults as _faults

__all__ = ["ParameterServer", "PSClient", "shard_ids"]

# bound per-message allocation (framing is attacker-controlled input)
_MAX_MSG = int(1 << 31)
_ALLOWED_DTYPES = {
    "float32", "float64", "float16", "bfloat16",
    "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool",
}


def _encode_msg(obj) -> bytes:
    """dict/list/scalars + ndarrays -> JSON header || payload bytes."""
    payloads: List[bytes] = []

    def conv(x):
        if isinstance(x, np.ndarray):
            x = np.ascontiguousarray(x)
            if x.dtype.name not in _ALLOWED_DTYPES:
                raise TypeError("dtype %s not wire-safe" % x.dtype)
            payloads.append(x.tobytes())
            return {"__nd__": len(payloads) - 1, "dtype": x.dtype.name,
                    "shape": list(x.shape)}
        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, dict):
            return {str(k): conv(v) for k, v in x.items()}
        if isinstance(x, (list, tuple)):
            return [conv(v) for v in x]
        if x is None or isinstance(x, (bool, int, float, str)):
            return x
        raise TypeError("%r not wire-safe" % type(x))

    header = json.dumps({"m": conv(obj), "p": [len(b) for b in payloads]}).encode()
    return struct.pack("<I", len(header)) + header + b"".join(payloads)


def _decode_msg(data: bytes):
    """Every malformation raises ValueError — the one exception type the
    server/client treat as 'corrupt frame from the peer'."""
    try:
        (hlen,) = struct.unpack_from("<I", data, 0)
        if hlen > len(data) - 4:
            raise ValueError("corrupt message header")
        meta = json.loads(data[4 : 4 + hlen].decode())
        sizes = meta["p"]
        if not isinstance(sizes, list):
            raise ValueError("corrupt payload index")
        views = []
        mv = memoryview(data)  # zero-copy payload slicing
        off = 4 + hlen
        for n in sizes:
            if not isinstance(n, int) or n < 0 or off + n > len(data):
                raise ValueError("corrupt message payload")
            views.append(mv[off : off + n])
            off += n

        def conv(x):
            if isinstance(x, dict):
                if "__nd__" in x:
                    dtype = str(x["dtype"])
                    if dtype not in _ALLOWED_DTYPES:
                        raise ValueError("dtype %s not wire-safe" % dtype)
                    if dtype == "bfloat16":
                        import ml_dtypes

                        np_dtype = np.dtype(ml_dtypes.bfloat16)
                    else:
                        np_dtype = np.dtype(dtype)
                    idx = int(x["__nd__"])
                    if not 0 <= idx < len(views):
                        raise ValueError("corrupt payload reference")
                    arr = np.frombuffer(views[idx], np_dtype)
                    return arr.reshape([int(d) for d in x["shape"]])
                return {k: conv(v) for k, v in x.items()}
            if isinstance(x, list):
                return [conv(v) for v in x]
            return x

        return conv(meta["m"])
    except ValueError:
        raise
    except Exception as e:  # struct.error, KeyError, json/unicode errors...
        raise ValueError("corrupt message: %s" % e) from e


def _send_msg(sock: socket.socket, obj) -> None:
    data = _encode_msg(obj)
    sock.sendall(struct.pack("<Q", len(data)) + data)


def _recv_msg(sock: socket.socket):
    hdr = b""
    while len(hdr) < 8:
        chunk = sock.recv(8 - len(hdr))
        if not chunk:
            raise ConnectionError("peer closed")
        hdr += chunk
    (n,) = struct.unpack("<Q", hdr)
    if n > _MAX_MSG:
        raise ValueError("message of %d bytes exceeds limit" % n)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(min(1 << 20, n - len(buf)))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return _decode_msg(bytes(buf))


def shard_ids(ids: np.ndarray, n_shards: int) -> List[np.ndarray]:
    """Round-robin id sharding (reference: split_ids_op.cc / ps_dispatcher
    RoundRobin)."""
    return [np.where(ids % n_shards == s)[0] for s in range(n_shards)]


class _Table:
    """One sparse table shard: id -> row, with lazy-initialized rows and
    a simple optimizer (sgd | adagrad) applied server-side on push —
    the reference's per-grad optimize sub-blocks (listen_and_serv)."""

    def __init__(self, dim: int, initializer: str = "uniform", seed: int = 0,
                 optimizer: str = "sgd", lr: float = 0.1):
        self.dim = dim
        self.rows: Dict[int, np.ndarray] = {}
        self.moments: Dict[int, np.ndarray] = {}
        self.initializer = initializer
        self.optimizer = optimizer
        self.lr = lr
        self._rng = np.random.RandomState(seed)
        self._lock = threading.Lock()

    def _init_row(self) -> np.ndarray:
        if self.initializer == "zeros":
            return np.zeros(self.dim, np.float32)
        return self._rng.uniform(-0.05, 0.05, self.dim).astype(np.float32)

    def pull(self, ids: Sequence[int]) -> np.ndarray:
        with self._lock:
            out = np.empty((len(ids), self.dim), np.float32)
            for i, idx in enumerate(ids):
                row = self.rows.get(int(idx))
                if row is None:
                    row = self.rows[int(idx)] = self._init_row()
                out[i] = row
            return out

    def push(self, ids: Sequence[int], grads: np.ndarray) -> None:
        with self._lock:
            for idx, g in zip(ids, grads):
                idx = int(idx)
                row = self.rows.get(idx)
                if row is None:
                    row = self.rows[idx] = self._init_row()
                if self.optimizer == "adagrad":
                    m = self.moments.get(idx)
                    if m is None:
                        m = self.moments[idx] = np.zeros(self.dim, np.float32)
                    m += g * g
                    row -= self.lr * g / (np.sqrt(m) + 1e-6)
                else:
                    row -= self.lr * g


class _DenseParam:
    """One dense parameter served by the legacy PS path (reference:
    listen_and_serv_op.cc:109 RunSyncLoop — the server owns the master
    copy AND the optimizer state, trainers send grads and recv params).

    Sync mode: pushes for round ``version`` accumulate until all
    ``n_trainers`` arrive, then the mean grad feeds the server-side
    optimizer exactly once and ``version`` bumps; ``pull(min_version)``
    blocks on that bump — the reference's per-step recv barrier.
    Async mode (Hogwild): every push applies immediately.
    """

    _OPTS = ("sgd", "momentum", "adagrad", "adam")

    def __init__(self, shape, optimizer: str = "sgd", attrs: Optional[dict] = None,
                 n_trainers: int = 1, sync: bool = True):
        if optimizer not in self._OPTS:
            raise ValueError(
                "dense PS optimizer %r not in %s" % (optimizer, self._OPTS))
        self.shape = tuple(int(s) for s in shape)
        self.value: Optional[np.ndarray] = None  # set by seed (trainer 0)
        self.optimizer = optimizer
        self.attrs = dict(attrs or {})
        self.n_trainers = max(1, int(n_trainers))
        self.sync = bool(sync)
        self.version = 0
        self._acc: Optional[np.ndarray] = None
        self._acc_count = 0
        self._state: Dict[str, np.ndarray] = {}
        self._cv = threading.Condition()

    def seed(self, value: np.ndarray) -> bool:
        """First writer wins (trainer 0 broadcast init); returns whether
        this call seeded."""
        with self._cv:
            if self.value is not None:
                return False
            v = np.asarray(value, np.float32).reshape(self.shape)
            self.value = v.copy()
            self._cv.notify_all()
            return True

    def _optimize(self, grad: np.ndarray, lr: float) -> None:
        # numpy mirror of ops/optimizer_ops.py kernels — the server is
        # host-side by design, so the update must not touch the chip
        p, s = self.value, self._state
        if self.optimizer == "sgd":
            p -= lr * grad
        elif self.optimizer == "momentum":
            mu = float(self.attrs.get("mu", 0.9))
            v = s.setdefault("velocity", np.zeros_like(p))
            v *= mu
            v += grad
            if self.attrs.get("use_nesterov", False):
                p -= (grad + mu * v) * lr
            else:
                p -= lr * v
        elif self.optimizer == "adagrad":
            eps = float(self.attrs.get("epsilon", 1e-6))
            m = s.setdefault("moment", np.zeros_like(p))
            m += grad * grad
            p -= lr * grad / (np.sqrt(m) + eps)
        elif self.optimizer == "adam":
            b1 = float(self.attrs.get("beta1", 0.9))
            b2 = float(self.attrs.get("beta2", 0.999))
            eps = float(self.attrs.get("epsilon", 1e-8))
            m = s.setdefault("m", np.zeros_like(p))
            v = s.setdefault("v", np.zeros_like(p))
            t = s.setdefault("t", np.zeros(()))
            t += 1
            m *= b1
            m += (1 - b1) * grad
            v *= b2
            v += (1 - b2) * grad * grad
            lr_t = lr * np.sqrt(1 - b2 ** float(t)) / (1 - b1 ** float(t))
            p -= lr_t * m / (np.sqrt(v) + eps)

    def push(self, grad: np.ndarray, lr: float, timeout: float = 60.0) -> int:
        grad = np.asarray(grad, np.float32).reshape(self.shape)
        with self._cv:
            if self.value is None:
                raise ValueError("dense param not seeded yet")
            if not self.sync:
                self._optimize(grad, lr)
                self.version += 1
                self._cv.notify_all()
                return self.version
            my_round = self.version
            if self._acc is None:
                self._acc = grad.copy()
            else:
                self._acc += grad
            self._acc_count += 1
            if self._acc_count == self.n_trainers:
                self._optimize(self._acc / self.n_trainers, lr)
                self._acc = None
                self._acc_count = 0
                self.version += 1
                self._cv.notify_all()
            return my_round + 1

    def pull(self, min_version: int = 0, timeout: float = 60.0) -> np.ndarray:
        import time as _time

        deadline = _time.monotonic() + timeout
        with self._cv:
            while self.value is None or self.version < min_version:
                remaining = deadline - _time.monotonic()
                if remaining <= 0 or not self._cv.wait(timeout=remaining):
                    raise ValueError(
                        "pull_dense timed out waiting for version %d (at %d)"
                        % (min_version, self.version))
            return self.value.copy()


class ParameterServer:
    """Sparse-table server (reference: listen_and_serv_op.cc:109 sync loop
    + request_handler_impl.cc handlers)."""

    def __init__(self, endpoint: str = "127.0.0.1:0"):
        host, port = endpoint.rsplit(":", 1)
        self._tables: Dict[str, _Table] = {}
        self._dense: Dict[str, _DenseParam] = {}
        self._tables_lock = threading.Lock()
        self._barrier_count = 0
        self._barrier_lock = threading.Lock()
        # rendezvous state for the host allreduce collective
        self._coll: Dict[str, dict] = {}
        self._coll_cv = threading.Condition()
        outer = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        msg = _recv_msg(self.request)
                    except ValueError:
                        # corrupt/over-limit frame: drop the connection
                        # (protocol error from the peer, not a server bug)
                        return
                    except (ConnectionError, OSError):
                        return
                    # application errors go back to the caller as an error
                    # response (the gRPC status analog), not a dropped socket
                    try:
                        resp = outer._dispatch(msg)
                    except Exception as e:
                        resp = {"_error": "%s: %s" % (type(e).__name__, e)}
                    try:
                        _send_msg(self.request, resp)
                    except (ConnectionError, OSError):
                        return

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, int(port)), Handler)
        self.endpoint = "%s:%d" % self._server.server_address
        self._thread: Optional[threading.Thread] = None

    # --- server ops ---
    def create_table(self, name: str, dim: int, **kwargs):
        # idempotent AND race-free: concurrent trainers joining must not
        # wipe rows another already trained/seeded (reference: pserver
        # tables are created once by the transpiled startup program)
        with self._tables_lock:
            existing = self._tables.get(name)
            if existing is not None:
                if existing.dim != dim:
                    raise ValueError(
                        "table %r exists with dim %d != %d" % (name, existing.dim, dim)
                    )
                return
            self._tables[name] = _Table(dim, **kwargs)

    def _dispatch(self, msg):
        op = msg["op"]
        if op == "pull":
            return {"rows": self._tables[msg["table"]].pull(msg["ids"])}
        if op == "push":
            self._tables[msg["table"]].push(msg["ids"], msg["grads"])
            return {"ok": True}
        if op == "create_table":
            self.create_table(msg["table"], msg["dim"], **msg.get("kwargs", {}))
            return {"ok": True}
        if op == "tables":
            # table directory for chunked checkpointing ("moments": rows
            # with live optimizer state — adagrad accumulators — so a
            # checkpoint knows whether a moment dump is needed at all)
            return {
                "tables": {
                    n: {"dim": t.dim, "size": len(t.rows),
                        "moments": len(t.moments)}
                    for n, t in self._tables.items()
                }
            }
        if op == "assign":
            # checkpoint RESTORE: set rows by VALUE, bypassing the
            # optimizer (push applies -lr*grad; a restored row must land
            # exactly as saved).  An optional "moments" payload restores
            # the adagrad accumulators the same way, so a resumed sparse
            # optimizer continues with the exact per-row step sizes it
            # died with instead of restarting from zero
            t = self._tables[msg["table"]]
            rows = np.asarray(msg["rows"], np.float32)
            moments = msg.get("moments")
            if moments is not None:
                moments = np.asarray(moments, np.float32)
            with t._lock:
                for k, idx in enumerate(np.asarray(msg["ids"]).reshape(-1)):
                    t.rows[int(idx)] = np.array(rows[k], np.float32)
                    if moments is not None:
                        t.moments[int(idx)] = np.array(
                            moments[k], np.float32)
            return {"ok": True}
        if op == "pull_moments":
            # checkpoint SAVE: optimizer accumulators for the given ids,
            # zeros where absent (zero IS adagrad's initial state, so
            # the dump stays exact and id-aligned with the row pull)
            t = self._tables[msg["table"]]
            ids = np.asarray(msg["ids"]).reshape(-1)
            with t._lock:
                out = np.zeros((len(ids), t.dim), np.float32)
                for i, idx in enumerate(ids):
                    m = t.moments.get(int(idx))
                    if m is not None:
                        out[i] = m
            return {"rows": out}
        if op == "keys":
            # paged, sorted key listing so huge shards fit the wire cap
            t = self._tables[msg["table"]]
            start = int(msg.get("start", 0))
            limit = msg.get("limit")
            with t._lock:
                ids = np.fromiter(t.rows.keys(), np.int64, len(t.rows))
            ids.sort()
            page = ids[start : start + int(limit)] if limit is not None else ids[start:]
            return {"ids": page, "total": int(len(ids))}
        if op == "create_dense":
            with self._tables_lock:
                existing = self._dense.get(msg["name"])
                if existing is not None:
                    if existing.shape != tuple(msg["shape"]):
                        raise ValueError(
                            "dense param %r exists with shape %s != %s"
                            % (msg["name"], existing.shape, msg["shape"]))
                else:
                    self._dense[msg["name"]] = _DenseParam(
                        msg["shape"], optimizer=msg.get("optimizer", "sgd"),
                        attrs=msg.get("attrs"), n_trainers=msg.get("n_trainers", 1),
                        sync=msg.get("sync", True))
            return {"ok": True}
        if op == "seed_dense":
            return {"seeded": self._dense[msg["name"]].seed(msg["value"])}
        if op == "push_dense":
            v = self._dense[msg["name"]].push(msg["grad"], float(msg.get("lr", 0.1)))
            return {"version": v}
        if op == "pull_dense":
            d = self._dense[msg["name"]]
            val = d.pull(int(msg.get("min_version", 0)),
                         timeout=float(msg.get("timeout", 60.0)))
            return {"value": val, "version": d.version}
        if op == "allreduce":
            # blocking sum-allreduce rendezvous: nranks callers post
            # tensors under one key; all get the sum (the TCP collective
            # the reference's dygraph NCCLParallelContext bootstraps —
            # here the host ring IS the transport, a Gloo analog)
            key = str(msg["key"])
            nranks = int(msg["nranks"])
            arr = np.asarray(msg["value"], np.float32)
            import time as _time

            deadline = _time.monotonic() + 60.0
            with self._coll_cv:
                ent = self._coll.get(key)
                if ent is None:
                    ent = self._coll[key] = {"sum": arr.copy(), "count": 1, "left": nranks}
                else:
                    ent["sum"] = ent["sum"] + arr
                    ent["count"] += 1
                self._coll_cv.notify_all()
                while ent["count"] < nranks:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0 or not self._coll_cv.wait(timeout=remaining):
                        # drop OUR partial entry so a retry starts clean —
                        # but never a fresh entry later arrivals recreated
                        if self._coll.get(key) is ent:
                            del self._coll[key]
                        raise ValueError("allreduce %r timed out" % key)
                out = ent["sum"]
                ent["left"] -= 1
                if ent["left"] == 0:
                    self._coll.pop(key, None)
            return {"sum": out}
        if op == "barrier":  # counted barrier (rpc_server.cc analog)
            with self._barrier_lock:
                self._barrier_count += 1
                return {"count": self._barrier_count}
        if op == "stats":
            return {n: len(t.rows) for n, t in self._tables.items()}
        raise ValueError("unknown PS op %r" % op)

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


class PSClient:
    """Trainer-side client (reference: distributed/grpc_client.cc +
    parameter_prefetch.cc).  Ids shard across servers round-robin."""

    def __init__(self, endpoints: Sequence[str]):
        self.endpoints = list(endpoints)
        self._socks: List[Optional[socket.socket]] = [None] * len(self.endpoints)

    # connect retry: peers start concurrently and the server process may
    # still be booting (real rendezvous semantics; a refused connection
    # fails instantly otherwise) — deadline-bounded, jittered backoff
    CONNECT_TIMEOUT_S = 60.0

    def _sock(self, i) -> socket.socket:
        if self._socks[i] is None:
            import time

            from paddle_tpu.faults.retry import RetryPolicy

            host, port = self.endpoints[i].rsplit(":", 1)
            budget = RetryPolicy(
                max_attempts=None, base_delay_s=0.2, multiplier=1.5,
                max_delay_s=2.0,
            ).budget(deadline=time.monotonic() + self.CONNECT_TIMEOUT_S,
                     op="ps.connect")
            self._socks[i] = budget.call(
                lambda: socket.create_connection((host, int(port)),
                                                 timeout=30),
                retryable=(ConnectionRefusedError,))
        return self._socks[i]

    def _call(self, i, msg):
        s = self._sock(i)
        _send_msg(s, msg)
        resp = _recv_msg(s)
        if isinstance(resp, dict) and "_error" in resp:
            raise RuntimeError(
                "PS %s: %s" % (self.endpoints[i], resp["_error"])
            )
        return resp

    def create_table(self, name: str, dim: int, **kwargs):
        for i in range(len(self.endpoints)):
            self._call(i, {"op": "create_table", "table": name, "dim": dim, "kwargs": kwargs})

    def pull_sparse(self, table: str, ids: np.ndarray) -> np.ndarray:
        """Row lookup for a flat id array -> [len(ids), dim]."""
        if _faults.active is not None:  # disarmed: one is-None gate
            _faults.active.faultpoint("ps.pull", table=table)
        ids = np.asarray(ids).reshape(-1)
        n = len(self.endpoints)
        parts = shard_ids(ids, n)
        out = None
        for i, pos in enumerate(parts):
            if len(pos) == 0:
                continue
            rows = self._call(i, {"op": "pull", "table": table, "ids": ids[pos]})["rows"]
            if out is None:
                out = np.empty((len(ids), rows.shape[1]), np.float32)
            out[pos] = rows
        return out

    def push_sparse(self, table: str, ids: np.ndarray, grads: np.ndarray) -> None:
        if _faults.active is not None:  # disarmed: one is-None gate
            _faults.active.faultpoint("ps.push", table=table)
        ids = np.asarray(ids).reshape(-1)
        grads = np.asarray(grads).reshape(len(ids), -1)
        # de-duplicate ids, summing grads (reference merge_ids_op)
        uniq, inv = np.unique(ids, return_inverse=True)
        merged = np.zeros((len(uniq), grads.shape[1]), np.float32)
        np.add.at(merged, inv, grads)
        parts = shard_ids(uniq, len(self.endpoints))
        for i, pos in enumerate(parts):
            if len(pos) == 0:
                continue
            self._call(i, {"op": "push", "table": table, "ids": uniq[pos], "grads": merged[pos]})

    def barrier(self):
        for i in range(len(self.endpoints)):
            self._call(i, {"op": "barrier"})

    # ---- dense legacy PS (reference: send_op/recv_op around the step) ----
    def shard_for(self, name: str) -> int:
        """Dense params dispatch whole to one server by name hash (the
        reference slices big vars into blocks; whole-param placement keeps
        the optimizer update atomic per param)."""
        import zlib

        return zlib.crc32(name.encode()) % len(self.endpoints)

    def create_dense(self, name: str, shape, optimizer: str = "sgd",
                     attrs: Optional[dict] = None, n_trainers: int = 1,
                     sync: bool = True):
        self._call(self.shard_for(name), {
            "op": "create_dense", "name": name, "shape": list(shape),
            "optimizer": optimizer, "attrs": attrs or {},
            "n_trainers": n_trainers, "sync": sync,
        })

    def seed_dense(self, name: str, value: np.ndarray) -> bool:
        r = self._call(self.shard_for(name),
                       {"op": "seed_dense", "name": name,
                        "value": np.asarray(value, np.float32)})
        return bool(r["seeded"])

    def push_dense(self, name: str, grad: np.ndarray, lr: float) -> int:
        if _faults.active is not None:  # disarmed: one is-None gate
            _faults.active.faultpoint("ps.push", param=name)
        r = self._call(self.shard_for(name),
                       {"op": "push_dense", "name": name,
                        "grad": np.asarray(grad, np.float32), "lr": float(lr)})
        return int(r["version"])

    def pull_dense(self, name: str, min_version: int = 0, timeout: float = 60.0):
        if _faults.active is not None:  # disarmed: one is-None gate
            _faults.active.faultpoint("ps.pull", param=name)
        r = self._call(self.shard_for(name),
                       {"op": "pull_dense", "name": name,
                        "min_version": int(min_version), "timeout": timeout})
        return np.asarray(r["value"], np.float32)

    # stay well under _MAX_MSG per frame (header + payload slack)
    _SAVE_BYTES_PER_CHUNK = 256 << 20

    def save(self, chunk_rows: Optional[int] = None,
             include_moments: bool = False):
        """Checkpoint every table across all shards (reference:
        checkpoint_notify_op.cc / RequestCheckpoint).  Keys page and rows
        stream in chunks sized by the row width, so any shard checkpoints
        within the wire-frame cap.  Returns {table: (ids[N], rows[N, dim])}.

        ``include_moments=True`` additionally dumps the server-side
        optimizer accumulators (adagrad moments) for any table that has
        them, id-aligned with the row dump: values become
        ``(ids, rows, moments_or_None)`` 3-tuples, and a restore through
        :meth:`load_tables` is then EXACT for sparse optimizers (the
        per-row step sizes resume, not restart)."""
        out: Dict[str, List] = {}
        # one directory pass up front: a table whose moments live on ANY
        # shard dumps moments from EVERY shard (zeros where absent), so
        # the concatenated dump stays id-aligned across shards
        shard_tables = [
            self._call(i, {"op": "tables"})["tables"]
            for i in range(len(self.endpoints))
        ]
        has_moments = set()
        if include_moments:
            for tables in shard_tables:
                for name, info in tables.items():
                    if int(info.get("moments", 0)) > 0:
                        has_moments.add(name)
        for i in range(len(self.endpoints)):
            for name, info in shard_tables[i].items():
                dim = max(1, int(info["dim"]))
                rows_per_chunk = chunk_rows or max(
                    1, self._SAVE_BYTES_PER_CHUNK // (dim * 4)
                )
                keys_per_page = max(1, self._SAVE_BYTES_PER_CHUNK // 8)
                id_pages = []
                start = 0
                while True:
                    resp = self._call(
                        i, {"op": "keys", "table": name, "start": start, "limit": keys_per_page}
                    )
                    page = resp["ids"]
                    if len(page):
                        id_pages.append(page)
                    start += len(page)
                    if start >= resp["total"] or len(page) == 0:
                        break
                ids = np.concatenate(id_pages) if id_pages else np.zeros(0, np.int64)
                chunks = []
                mchunks = []
                for s in range(0, len(ids), rows_per_chunk):
                    part = ids[s : s + rows_per_chunk]
                    chunks.append(
                        self._call(i, {"op": "pull", "table": name, "ids": part})["rows"]
                    )
                    if name in has_moments:
                        mchunks.append(self._call(
                            i, {"op": "pull_moments", "table": name,
                                "ids": part})["rows"])
                rows = (
                    np.concatenate(chunks)
                    if chunks
                    else np.zeros((0, dim), np.float32)
                )
                out.setdefault(name, [[], [], []])
                out[name][0].append(ids)
                out[name][1].append(rows)
                if name in has_moments:
                    out[name][2].append(
                        np.concatenate(mchunks) if mchunks
                        else np.zeros((0, dim), np.float32))
        state = {}
        for n, v in out.items():
            ids = np.concatenate(v[0]) if v[0] else np.zeros(0, np.int64)
            rows = (np.concatenate(v[1]) if v[1]
                    else np.zeros((0, 0), np.float32))
            if not include_moments:
                state[n] = (ids, rows)
            else:
                moments = np.concatenate(v[2]) if v[2] else None
                state[n] = (ids, rows, moments)
        return state

    def load_tables(self, state, chunk_rows: Optional[int] = None):
        """Restore a :meth:`save` dump: create any missing table and
        ASSIGN the saved rows by value (the server-side ``assign`` op
        bypasses the optimizer — a restored row lands exactly as saved;
        table optimizer config comes from whoever creates the tables,
        normally the program binding).  Values may be ``(ids, rows)``
        pairs or ``(ids, rows, moments)`` triples from
        ``save(include_moments=True)`` — a moments array restores the
        adagrad accumulators by value too, making SIGKILL-resume exact
        for sparse optimizers.  Rows stream in wire-cap-sized chunks
        like :meth:`save`."""
        for name, value in state.items():
            if len(value) == 3:
                ids, rows, moments = value
            else:
                ids, rows = value
                moments = None
            ids = np.asarray(ids, np.int64).reshape(-1)
            rows = np.asarray(rows, np.float32).reshape(len(ids), -1)
            if moments is not None:
                moments = np.asarray(moments, np.float32).reshape(
                    len(ids), -1)
            if not len(ids):
                continue
            dim = rows.shape[1]
            self.create_table(name, dim)
            per_chunk = chunk_rows or max(
                1, self._SAVE_BYTES_PER_CHUNK // (dim * 4))
            parts = shard_ids(ids, len(self.endpoints))
            for i, pos in enumerate(parts):
                if len(pos) == 0:
                    continue
                for s in range(0, len(pos), per_chunk):
                    sel = pos[s:s + per_chunk]
                    msg = {"op": "assign", "table": name,
                           "ids": ids[sel], "rows": rows[sel]}
                    if moments is not None:
                        msg["moments"] = moments[sel]
                    self._call(i, msg)

    def close(self):
        for s in self._socks:
            if s is not None:
                s.close()
        self._socks = [None] * len(self.endpoints)
