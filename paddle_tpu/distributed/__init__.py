"""Distributed runtime: launcher + parameter server.

Reference: python/paddle/distributed/launch.py (process launcher),
paddle/fluid/operators/distributed/ (gRPC/BRPC parameter-server RPC).
"""
from paddle_tpu.distributed import launch  # noqa: F401
from paddle_tpu.distributed.communicator import Communicator, GeoSGD  # noqa: F401
from paddle_tpu.distributed.lookup import bind_distributed_tables  # noqa: F401
from paddle_tpu.distributed.ps import ParameterServer, PSClient  # noqa: F401
