"""Filesystem shim (reference: paddle/fluid/framework/io/fs.cc + shell.cc
— local + HDFS file ops used by Dataset/Fleet file-sharding).

Local paths work natively; ``hdfs://`` paths route through the ``hadoop
fs`` CLI when present (the reference shells out the same way,
io/shell.cc), else raise with a clear message.  The API mirrors fs.cc:
``fs_ls / fs_exists / fs_mkdir / fs_rm / fs_mv / open_read /
open_write / file_shard``.
"""
from __future__ import annotations

import glob as _glob
import os
import shutil
import subprocess
from typing import IO, List

__all__ = [
    "fs_ls", "fs_exists", "fs_mkdir", "fs_rm", "fs_mv",
    "open_read", "open_write", "file_shard",
]


def _is_hdfs(path: str) -> bool:
    return path.startswith(("hdfs://", "afs://"))


def _hadoop(*args: str) -> str:
    exe = shutil.which("hadoop")
    if exe is None:
        raise RuntimeError(
            "hdfs:// path requires the 'hadoop' CLI on PATH (reference "
            "io/fs.cc shells out identically); not present in this image"
        )
    return subprocess.run(
        [exe, "fs", *args], check=True, capture_output=True, text=True
    ).stdout


def fs_ls(path: str) -> List[str]:
    if _is_hdfs(path):
        out = _hadoop("-ls", path)
        return [ln.split()[-1] for ln in out.splitlines() if ln.startswith(("-", "d"))]
    if os.path.isdir(path):
        return sorted(os.path.join(path, p) for p in os.listdir(path))
    return sorted(_glob.glob(path))


def fs_exists(path: str) -> bool:
    if _is_hdfs(path):
        try:
            _hadoop("-test", "-e", path)
            return True
        except subprocess.CalledProcessError:
            return False
    return os.path.exists(path)


def fs_mkdir(path: str) -> None:
    if _is_hdfs(path):
        _hadoop("-mkdir", "-p", path)
        return
    os.makedirs(path, exist_ok=True)


def fs_rm(path: str) -> None:
    if _is_hdfs(path):
        _hadoop("-rm", "-r", path)
        return
    if os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)


def fs_mv(src: str, dst: str) -> None:
    if _is_hdfs(src) or _is_hdfs(dst):
        _hadoop("-mv", src, dst)
        return
    shutil.move(src, dst)


class _ProcReader:
    """File-like over a subprocess pipe that reaps the process on close
    and surfaces a nonzero exit status (an empty stream must not be
    mistaken for an empty file)."""

    def __init__(self, proc: subprocess.Popen, stream):
        self._proc = proc
        self._stream = stream

    def __getattr__(self, name):
        return getattr(self._stream, name)

    def __iter__(self):
        return iter(self._stream)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        self._stream.close()
        rc = self._proc.wait()
        if rc != 0:
            raise RuntimeError("hadoop fs -cat exited with status %d" % rc)


def open_read(path: str, mode: str = "r") -> IO:
    if _is_hdfs(path):
        import io as _iomod

        exe = shutil.which("hadoop")
        if exe is None:
            raise RuntimeError("hdfs:// read requires the 'hadoop' CLI")
        proc = subprocess.Popen([exe, "fs", "-cat", path], stdout=subprocess.PIPE)
        stream = proc.stdout if "b" in mode else _iomod.TextIOWrapper(proc.stdout)
        return _ProcReader(proc, stream)
    return open(path, mode)


def open_write(path: str, mode: str = "w") -> IO:
    if _is_hdfs(path):
        raise NotImplementedError("hdfs:// streaming write: stage locally, fs_mv after")
    return open(path, mode)


def file_shard(paths: List[str], trainer_id: int, trainer_num: int) -> List[str]:
    """Round-robin file sharding across trainers (reference:
    fleet file_list split / data_set.cc SetFileList distribution)."""
    return [p for i, p in enumerate(sorted(paths)) if i % trainer_num == trainer_id]
