"""Filesystem shim (reference: paddle/fluid/framework/io/fs.cc + shell.cc
— local + HDFS file ops used by Dataset/Fleet file-sharding).

Local paths work natively; ``hdfs://`` paths route through the ``hadoop
fs`` CLI — the reference's HDFS support is EXACTLY the same design
(fs.cc:208 ``hdfs_command() = "hadoop fs"`` run via shell_popen; there
is no native protocol client in the reference either).  When the CLI is
absent the hdfs ops raise with a clear message.  The API mirrors fs.cc:
``fs_ls / fs_exists / fs_mkdir / fs_rm / fs_mv / fs_tail /
fs_file_size / open_read / open_write / file_shard /
set_hdfs_command``, including fs.cc's converter behavior (``.gz`` reads
decompress — ``-text`` on hdfs, gzip locally — and ``.gz`` writes
compress) and the streaming ``-put -`` write pipe.
"""
from __future__ import annotations

import glob as _glob
import gzip as _gzip
import os
import shutil
import subprocess
from typing import IO, List

__all__ = [
    "fs_ls", "fs_exists", "fs_mkdir", "fs_rm", "fs_mv", "fs_tail",
    "fs_file_size", "open_read", "open_write", "file_shard",
    "set_hdfs_command",
]

# reference: fs.cc:208 hdfs_command_internal() = "hadoop fs",
# overridable via hdfs_set_command (e.g. to add -D options)
_HDFS_COMMAND = ["hadoop", "fs"]


def set_hdfs_command(cmd: str) -> None:
    """reference: fs.cc:215 hdfs_set_command."""
    global _HDFS_COMMAND
    parts = cmd.split()
    if not parts:
        raise ValueError("empty hdfs command")
    _HDFS_COMMAND = parts


def _is_hdfs(path: str) -> bool:
    return path.startswith(("hdfs://", "afs://"))


def _hdfs_argv(*args: str) -> List[str]:
    exe = shutil.which(_HDFS_COMMAND[0])
    if exe is None:
        raise RuntimeError(
            "hdfs:// path requires the %r CLI on PATH (the reference "
            "shells out identically, io/fs.cc:208); not present in this "
            "image" % _HDFS_COMMAND[0]
        )
    return [exe, *_HDFS_COMMAND[1:], *args]


def _hadoop(*args: str) -> str:
    return subprocess.run(
        _hdfs_argv(*args), check=True, capture_output=True, text=True
    ).stdout


def fs_ls(path: str, files_only: bool = False) -> List[str]:
    if _is_hdfs(path):
        out = _hadoop("-ls", path)
        kinds = ("-",) if files_only else ("-", "d")
        return [ln.split()[-1] for ln in out.splitlines() if ln.startswith(kinds)]
    if os.path.isdir(path):
        entries = sorted(os.path.join(path, p) for p in os.listdir(path))
    else:
        entries = sorted(_glob.glob(path))
    if files_only:
        entries = [p for p in entries if os.path.isfile(p)]
    return entries


def fs_exists(path: str) -> bool:
    if _is_hdfs(path):
        try:
            _hadoop("-test", "-e", path)
            return True
        except subprocess.CalledProcessError:
            return False
    return os.path.exists(path)


def fs_mkdir(path: str) -> None:
    if _is_hdfs(path):
        _hadoop("-mkdir", "-p", path)
        return
    os.makedirs(path, exist_ok=True)


def fs_rm(path: str) -> None:
    if _is_hdfs(path):
        _hadoop("-rm", "-r", path)
        return
    if os.path.isdir(path):
        shutil.rmtree(path)
    elif os.path.exists(path):
        os.remove(path)


def fs_mv(src: str, dst: str) -> None:
    if _is_hdfs(src) or _is_hdfs(dst):
        _hadoop("-mv", src, dst)
        return
    shutil.move(src, dst)


def fs_file_size(path: str) -> int:
    """reference: fs.cc fs_file_size (hdfs: -du first column)."""
    if _is_hdfs(path):
        out = _hadoop("-du", path)
        lines = [ln.split() for ln in out.splitlines() if ln.strip()]
        if not lines:
            raise FileNotFoundError(path)
        return sum(int(ln[0]) for ln in lines)
    return os.path.getsize(path)


def fs_tail(path: str) -> str:
    """Last line of the file (reference: fs.cc fs_tail — hdfs pipes
    ``-text path | tail -1``).  Plain local files seek from the end
    (milliseconds on multi-GB logs); hdfs/gz streams read incrementally
    holding one line."""
    if not _is_hdfs(path) and not path.endswith(".gz"):
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            chunk = b""
            pos = size
            while pos > 0:
                step = min(65536, pos)
                pos -= step
                f.seek(pos)
                chunk = f.read(step) + chunk
                # same semantics as the streaming branch: the last
                # NON-blank line (whitespace-only tails are skipped)
                lines = [ln for ln in chunk.split(b"\n") if ln.strip()]
                if len(lines) > 1 or (lines and pos == 0):
                    return lines[-1].decode().rstrip("\n")
            return ""
    last = b""
    with open_read(path, "rb") as f:
        for line in f:
            if line.strip():
                last = line
    return last.decode().rstrip("\n")


class _ProcStream:
    """File-like over a subprocess pipe that reaps the process on close
    and surfaces a nonzero exit status (an empty stream must not be
    mistaken for an empty file; a failed write must not look flushed)."""

    def __init__(self, proc: subprocess.Popen, stream, what: str,
                 reader: bool = False):
        self._proc = proc
        self._stream = stream
        self._what = what
        self._reader = reader

    def __getattr__(self, name):
        return getattr(self._stream, name)

    def __iter__(self):
        return iter(self._stream)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    def close(self):
        # an early-exiting child makes the final flush raise
        # BrokenPipeError — reap the process FIRST so (a) it never
        # leaks unreaped and (b) the caller gets the exit-status
        # RuntimeError this class documents, not the pipe error
        flush_err = None
        try:
            self._stream.close()
        except (BrokenPipeError, OSError) as e:
            flush_err = e
        rc = self._proc.wait()
        # a READ stream closed before EOF kills the producer with
        # SIGPIPE (rc -13 / 141) — that's a normal partial read of a
        # large file, not a failure; writers still report every nonzero
        if rc != 0 and not (self._reader and rc in (-13, 141)):
            raise RuntimeError("%s exited with status %d" % (self._what, rc))
        if flush_err is not None:
            raise flush_err


def open_read(path: str, mode: str = "r", raw: bool = False) -> IO:
    """reference: fs.cc fs_open_read — ``.gz`` paths decompress on the
    way in (hdfs ``-text``; locally gzip).  ``raw=True`` bypasses the
    converter and returns the stored bytes verbatim (the ``-get``
    semantics a byte-for-byte download needs — decompressing into a
    ``.gz``-named local copy would corrupt it)."""
    if _is_hdfs(path):
        import io as _iomod

        op = "-text" if (path.endswith(".gz") and not raw) else "-cat"
        proc = subprocess.Popen(_hdfs_argv(op, path), stdout=subprocess.PIPE)
        stream = proc.stdout if "b" in mode else _iomod.TextIOWrapper(proc.stdout)
        return _ProcStream(proc, stream, "hadoop fs %s" % op, reader=True)
    if path.endswith(".gz") and not raw:
        return _gzip.open(path, mode if "b" in mode else mode + "t")
    return open(path, mode)


def open_write(path: str, mode: str = "w") -> IO:
    """reference: fs.cc fs_open_write — hdfs streams through
    ``-put - <path>`` (fs.cc:234); ``.gz`` paths compress."""
    if _is_hdfs(path):
        import io as _iomod

        if path.endswith(".gz"):
            raise NotImplementedError(
                "hdfs .gz streaming write: stage locally (gzip), fs_mv after"
            )
        proc = subprocess.Popen(_hdfs_argv("-put", "-", path),
                                stdin=subprocess.PIPE)
        stream = proc.stdin if "b" in mode else _iomod.TextIOWrapper(proc.stdin)
        return _ProcStream(proc, stream, "hadoop fs -put")
    if path.endswith(".gz"):
        return _gzip.open(path, mode if "b" in mode else mode + "t")
    return open(path, mode)


def file_shard(paths: List[str], trainer_id: int, trainer_num: int) -> List[str]:
    """Round-robin file sharding across trainers (reference:
    fleet file_list split / data_set.cc SetFileList distribution)."""
    return [p for i, p in enumerate(sorted(paths)) if i % trainer_num == trainer_id]
