"""``paddle_tpu.fluid`` — alias namespace so reference-style scripts
(``import paddle.fluid as fluid``) port by changing one import line."""
import sys

import paddle_tpu

sys.modules[__name__] = paddle_tpu
