"""Graph IR: Program / Block / Operator / Variable.

TPU-native re-design of the reference's graph builder
(reference: python/paddle/fluid/framework.py:383,992,1443,2782 and
paddle/fluid/framework/framework.proto:43-184).  Instead of a protobuf
ProgramDesc interpreted op-by-op by a C++ executor, the Program here is a
lightweight Python IR that the executor lowers *wholesale* into a single
jitted XLA module (see paddle_tpu/core/lowering.py) — no per-op dispatch at
runtime, which is what lets XLA fuse the whole training step for the MXU.
"""
from __future__ import annotations

import collections
import contextlib
import copy
import itertools
import json
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu import unique_name
from paddle_tpu.core import types as core_types
from paddle_tpu.core.types import VarType

__all__ = [
    "Variable",
    "Parameter",
    "Operator",
    "Block",
    "Program",
    "default_main_program",
    "default_startup_program",
    "program_guard",
    "name_scope",
    "grad_var_name",
    "in_dygraph_mode",
    "cpu_places",
    "CPUPlace",
    "TPUPlace",
]

GRAD_SUFFIX = "@GRAD"


def grad_var_name(name: str) -> str:
    """reference: paddle/fluid/framework/grad_op_desc_maker.h (GradVarName)."""
    return name + GRAD_SUFFIX


# ---------------------------------------------------------------------------
# Places.  The reference models devices as a boost::variant Place
# (paddle/fluid/platform/place.h:79).  Here a Place selects a jax backend.
# ---------------------------------------------------------------------------
class Place:
    backend: Optional[str] = None  # None = jax default

    def __repr__(self):
        return type(self).__name__ + "()"


class CPUPlace(Place):
    backend = "cpu"


class TPUPlace(Place):
    """The TPU device place (the reference's CUDAPlace analog, place.h:58)."""

    backend = "tpu"

    def __init__(self, device_id: int = 0):
        self.device_id = device_id


class CUDAPlace(TPUPlace):
    """Alias so reference-style scripts run unmodified; maps to the
    accelerator backend."""


class _DefaultPlace(Place):
    """Process-default device (no backend pin): Executor(place=None)."""

    backend = None


def cpu_places(device_count=None):
    return [CPUPlace()]


def cuda_places(device_ids=None):
    """reference: framework.py cuda_places — accelerator places.  On
    this build the accelerator is the TPU: returns one TPUPlace per
    visible chip (or per requested id)."""
    if device_ids is None:
        try:
            import jax

            n = max(
                1, len([d for d in jax.devices() if d.platform != "cpu"])
            )
        except Exception:  # noqa: BLE001 — no accelerator visible
            n = 1
        device_ids = range(n)
    return [TPUPlace(int(i)) for i in device_ids]


def cuda_pinned_places(device_count=None):
    """reference: framework.py cuda_pinned_places — pinned host staging
    memory.  PJRT owns transfer staging on TPU; host-side places are
    plain CPUPlaces."""
    return [CPUPlace() for _ in range(device_count or 1)]


def is_compiled_with_cuda() -> bool:
    """reference: framework.py is_compiled_with_cuda.  This build
    targets TPU via XLA, never CUDA — always False (reference code
    gating on it falls back to its portable path, which is correct
    here)."""
    return False


# ---------------------------------------------------------------------------
# Dygraph mode switch (reference: framework.py:60-110)
# ---------------------------------------------------------------------------
_dygraph_tracer_ = None


def in_dygraph_mode() -> bool:
    return _dygraph_tracer_ is not None


def _dygraph_tracer():
    return _dygraph_tracer_


@contextlib.contextmanager
def _dygraph_guard(tracer):
    global _dygraph_tracer_
    prev = _dygraph_tracer_
    _dygraph_tracer_ = tracer
    try:
        yield
    finally:
        _dygraph_tracer_ = prev


# ---------------------------------------------------------------------------
# Variable
# ---------------------------------------------------------------------------
class Variable:
    """A named tensor in a Block (reference: framework.py:383).

    ``shape`` may contain -1 (unknown/batch dims); concrete shapes are bound
    at executor trace time.  LoD (ragged sequence) information is carried as
    an optional companion length tensor — see paddle_tpu/ops/sequence_ops.py
    for the padded+mask TPU encoding of the reference's LoDTensor
    (paddle/fluid/framework/lod_tensor.h:110).
    """

    def __init__(
        self,
        block: "Block",
        name: str,
        shape: Optional[Sequence[int]] = None,
        dtype: str = "float32",
        type: int = VarType.LOD_TENSOR,
        persistable: bool = False,
        stop_gradient: bool = False,
        lod_level: int = 0,
        is_data: bool = False,
        **kwargs,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = core_types.canonical_dtype(dtype)
        self.type = type
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.lod_level = lod_level
        self.is_data = is_data
        # op that most recently produced this var (set by append_op)
        self.op: Optional["Operator"] = None

    # --- persistable participates in the executor's cached run-plan
    # (state_mut/ro/out derive from it), and the plan key is
    # (uid, version, op count, ...) — so a flag toggle AFTER a run (the
    # classic mark-before-save pattern) must bump the program version or
    # the stale plan would keep routing the var around the scope
    @property
    def persistable(self) -> bool:
        return self._persistable
    @persistable.setter
    def persistable(self, value) -> None:
        value = bool(value)
        if value == getattr(self, "_persistable", None):
            return  # idempotent re-mark: no analysis change, no recompile
        self._persistable = value
        prog = getattr(getattr(self, "block", None), "program", None)
        if prog is not None:
            prog.version += 1

    # --- sugar mirroring the reference Variable API ---
    def astype(self, dtype):
        from paddle_tpu.layers import tensor as ltensor

        return ltensor.cast(self, dtype)

    # --- dygraph surface (reference: framework.py:550 Variable.backward,
    # .numpy/.gradient on VarBase) ---
    def numpy(self):
        if getattr(self, "_dy_value", None) is None:
            raise RuntimeError("Variable.numpy() requires dygraph mode")
        import numpy as _np

        return _np.asarray(self._dy_value)

    def backward(self, backward_strategy=None):
        tracer = _dygraph_tracer()
        if tracer is None:
            raise RuntimeError("Variable.backward() requires dygraph mode")
        tracer.run_backward(self)

    def gradient(self):
        g = getattr(self, "_dy_grad", None)
        if g is None:
            return None
        import numpy as _np

        return _np.asarray(g)

    def clear_gradient(self):
        self._dy_grad = None

    @property
    def grad_name(self):
        return grad_var_name(self.name)

    def __repr__(self):
        return "Variable(name=%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    __str__ = __repr__

    def _binary(self, other, op, reverse=False):
        from paddle_tpu.layers import math_helper

        return math_helper.binary_op(self, other, op, reverse)

    def __add__(self, other):
        return self._binary(other, "elementwise_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "elementwise_sub")

    def __rsub__(self, other):
        return self._binary(other, "elementwise_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "elementwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "elementwise_div")

    def __rtruediv__(self, other):
        return self._binary(other, "elementwise_div", reverse=True)

    def __neg__(self):
        from paddle_tpu.layers import tensor as ltensor

        return ltensor.scale(self, scale=-1.0)

    def to_dict(self):
        return {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "type": int(self.type),
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "lod_level": self.lod_level,
            "is_data": self.is_data,
            "is_parameter": isinstance(self, Parameter),
            "trainable": getattr(self, "trainable", True),
        }


class Parameter(Variable):
    """A persistable, trainable Variable (reference: framework.py:3597)."""

    def __init__(self, block, name, shape, dtype, **kwargs):
        kwargs.setdefault("persistable", True)
        self.trainable = kwargs.pop("trainable", True)
        self.optimize_attr = kwargs.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.pop("regularizer", None)
        self.gradient_clip_attr = kwargs.pop("gradient_clip_attr", None)
        self.do_model_average = kwargs.pop("do_model_average", None)
        super().__init__(block, name, shape=shape, dtype=dtype, **kwargs)
        self.stop_gradient = not self.trainable


# ---------------------------------------------------------------------------
# Operator
# ---------------------------------------------------------------------------
class Operator:
    """An op node: type + named input/output var lists + attrs
    (reference: framework.py:992, framework.proto:105).

    Unlike the reference there is no OpProto validation against a C++
    registry; validation happens against the Python op registry
    (paddle_tpu/core/registry.py) which also holds the JAX kernel used at
    lowering time.
    """

    def __init__(
        self,
        block: "Block",
        type: str,
        inputs: Optional[Dict[str, List[str]]] = None,
        outputs: Optional[Dict[str, List[str]]] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        self.block = block
        self.type = type
        self.inputs = {k: list(_names(v)) for k, v in (inputs or {}).items() if v is not None}
        self.outputs = {k: list(_names(v)) for k, v in (outputs or {}).items() if v is not None}
        self.attrs = dict(attrs or {})

    def input(self, slot: str) -> List[str]:
        return self.inputs.get(slot, [])

    def output(self, slot: str) -> List[str]:
        return self.outputs.get(slot, [])

    @property
    def input_arg_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    @property
    def output_arg_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def _rename_input(self, old, new):
        for ns in self.inputs.values():
            for i, n in enumerate(ns):
                if n == old:
                    ns[i] = new

    def _rename_output(self, old, new):
        for ns in self.outputs.values():
            for i, n in enumerate(ns):
                if n == old:
                    ns[i] = new

    def to_dict(self):
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": _jsonable_attrs(self.attrs),
        }

    def __repr__(self):
        return "{%s} <- %s(%s)" % (
            ", ".join("%s=%s" % kv for kv in self.outputs.items()),
            self.type,
            ", ".join("%s=%s" % kv for kv in self.inputs.items()),
        )


def _names(v):
    if isinstance(v, (Variable, str)):
        v = [v]
    return [x.name if isinstance(x, Variable) else x for x in v]


def _jsonable_attrs(attrs):
    out = {}
    for k, v in attrs.items():
        if isinstance(v, np.ndarray):
            out[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
        elif isinstance(v, Block):
            out[k] = {"__block__": v.idx}
        elif isinstance(v, (np.integer,)):
            out[k] = int(v)
        elif isinstance(v, (np.floating,)):
            out[k] = float(v)
        else:
            out[k] = v
    return out


# ---------------------------------------------------------------------------
# Block
# ---------------------------------------------------------------------------
class Block:
    """An ordered op list + var symbol table, possibly nested
    (reference: framework.py:1443, framework.proto:165)."""

    def __init__(self, program: "Program", idx: int, parent_idx: int = -1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: Dict[str, Variable] = collections.OrderedDict()
        self.ops: List[Operator] = []

    @property
    def parent_block(self) -> Optional["Block"]:
        if self.parent_idx < 0:
            return None
        return self.program.blocks[self.parent_idx]

    # --- var management ---
    def create_var(self, name=None, **kwargs) -> Variable:
        if name is None:
            name = unique_name.generate("_generated_var")
        if name in self.vars:
            return self.vars[name]
        var = Variable(self, name, **kwargs)
        self.vars[name] = var
        return var

    def create_parameter(self, name, shape, dtype, **kwargs) -> Parameter:
        param = Parameter(self, name, shape, dtype, **kwargs)
        # parameters live in the outermost (global) block, like the reference
        self.program.global_block().vars[name] = param
        if self is not self.program.global_block():
            self.vars[name] = param
        return param

    def var(self, name: str) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise ValueError("Variable %r not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name: str) -> bool:
        return self._find_var_recursive(name) is not None

    def has_var_local(self, name: str) -> bool:
        return name in self.vars

    def _find_var_recursive(self, name: str) -> Optional[Variable]:
        blk = self
        while blk is not None:
            if name in blk.vars:
                return blk.vars[name]
            blk = blk.parent_block
        return None

    def all_parameters(self) -> List[Parameter]:
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # --- op management ---
    def append_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        from paddle_tpu.core import registry

        if in_dygraph_mode():
            return _dygraph_tracer_.trace_op(type, inputs, outputs, attrs, block=self)
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        for ns in op.outputs.values():
            for n in ns:
                if n in self.vars:
                    self.vars[n].op = op
        registry.infer_shape(op, self)
        return op

    def _insert_op(self, index, type, inputs=None, outputs=None, attrs=None) -> Operator:
        from paddle_tpu.core import registry

        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(index, op)
        registry.infer_shape(op, self)
        return op

    def prepend_op(self, type, inputs=None, outputs=None, attrs=None) -> Operator:
        return self._insert_op(0, type, inputs, outputs, attrs)

    def _remove_op(self, index):
        del self.ops[index]

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [op.to_dict() for op in self.ops],
        }


def _program_uid(obj) -> int:
    """Monotonic identity for compile-cache keys (never-reused, unlike
    ``id()``).  Programs get theirs at construction; any other cache
    participant (e.g. a CompiledProgram wrapper) is stamped lazily on
    first use."""
    uid = getattr(obj, "_ptpu_uid", None)
    if uid is None:
        uid = next(Program._uid_counter)
        try:
            obj._ptpu_uid = uid
        except AttributeError:
            return id(obj)  # __slots__ object: fall back to id
    return uid


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------
class Program:
    """A list of Blocks; block 0 is global (reference: framework.py:2782).

    ``version`` is bumped on structural edits and participates in the
    executor's compile-cache key, together with ``_ptpu_uid`` — a
    process-monotonic program identity.  The executor used to key on
    ``id(program)``, but CPython reuses ids after GC, so two programs
    alive at different times could alias one jit-cache entry; the uid
    can never collide.
    """

    _uid_counter = itertools.count(1)

    def __init__(self):
        self.blocks: List[Block] = [Block(self, 0)]
        self.current_block_idx = 0
        self.version = 0
        self.random_seed = 0
        self._op_role = "forward"
        self._seed_counter = 0
        self._ptpu_uid = next(Program._uid_counter)

    # --- block management ---
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None) -> Block:
        parent = self.current_block_idx if parent_idx is None else parent_idx
        blk = Block(self, len(self.blocks), parent)
        self.blocks.append(blk)
        self.current_block_idx = blk.idx
        return blk

    def _rollback(self):
        self.current_block_idx = self.current_block().parent_idx

    def block(self, idx) -> Block:
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def all_parameters(self):
        return self.global_block().all_parameters()

    def list_vars(self):
        for blk in self.blocks:
            yield from blk.vars.values()

    def next_seed(self) -> int:
        """Deterministic per-op RNG seed derived from program.random_seed."""
        self._seed_counter += 1
        return (self.random_seed * 1000003 + self._seed_counter) & 0x7FFFFFFF

    def clone(self, for_test: bool = False) -> "Program":
        """reference: framework.py Program.clone — for_test drops optimize
        ops and switches is_test attrs."""
        p = copy.deepcopy(self)
        if for_test:
            for blk in p.blocks:
                kept = []
                for op in blk.ops:
                    role = op.attrs.get("op_role", "forward")
                    if for_test and role in ("backward", "optimize"):
                        continue
                    if "is_test" in op.attrs:
                        op.attrs["is_test"] = True
                    if op.type == "dropout":
                        op.attrs["is_test"] = True
                    kept.append(op)
                blk.ops = kept
        p.version += 1
        # deepcopy duplicated the source's uid; a clone is a DISTINCT
        # program and must never share a compile-cache identity with it
        p._ptpu_uid = next(Program._uid_counter)
        return p

    # --- serialization (the reference's ProgramDesc protobuf round-trip,
    # framework.proto:184; here a stable JSON encoding) ---
    def to_json(self) -> str:
        payload = {
            "version": 1,
            "random_seed": self.random_seed,
            "blocks": [b.to_dict() for b in self.blocks],
        }
        # distributed lookup-table metadata (layers.embedding
        # is_distributed=True) must survive serde — without it a
        # saved/loaded huge-table program can no longer prefetch/push
        dist = getattr(self, "_distributed_tables", None)
        if dist:
            payload["distributed_tables"] = dist
        return json.dumps(payload)

    @staticmethod
    def from_json(text: str) -> "Program":
        data = json.loads(text)
        prog = Program()
        prog.random_seed = data.get("random_seed", 0)
        if data.get("distributed_tables"):
            prog._distributed_tables = data["distributed_tables"]
        prog.blocks = []
        for bd in data["blocks"]:
            blk = Block(prog, bd["idx"], bd["parent_idx"])
            prog.blocks.append(blk)
        for bd, blk in zip(data["blocks"], prog.blocks):
            for vd in bd["vars"]:
                cls = Parameter if vd.pop("is_parameter", False) else Variable
                trainable = vd.pop("trainable", True)
                name = vd.pop("name")
                shape = vd.pop("shape")
                if cls is Parameter:
                    v = Parameter(blk, name, shape, vd.pop("dtype"), trainable=trainable, **vd)
                else:
                    v = Variable(blk, name, shape=shape, **vd)
                blk.vars[name] = v
            for od in bd["ops"]:
                attrs = {}
                for k, v in od["attrs"].items():
                    if isinstance(v, dict) and "__ndarray__" in v:
                        attrs[k] = np.array(v["__ndarray__"], dtype=v["dtype"])
                    elif isinstance(v, dict) and "__block__" in v:
                        attrs[k] = prog.blocks[v["__block__"]]
                    else:
                        attrs[k] = v
                blk.ops.append(Operator(blk, od["type"], od["inputs"], od["outputs"], attrs))
        return prog

    def __repr__(self):
        lines = []
        for blk in self.blocks:
            lines.append("-- block %d (parent %d) --" % (blk.idx, blk.parent_idx))
            for v in blk.vars.values():
                lines.append("  " + repr(v))
            for op in blk.ops:
                lines.append("  " + repr(op))
        return "\n".join(lines)

    __str__ = __repr__


# ---------------------------------------------------------------------------
# Default program singletons & guards (reference: framework.py:3692-3725)
# ---------------------------------------------------------------------------
_main_program_ = Program()
_startup_program_ = Program()


def default_main_program() -> Program:
    return _main_program_


def default_startup_program() -> Program:
    return _startup_program_


def switch_main_program(program: Program) -> Program:
    global _main_program_
    prev, _main_program_ = _main_program_, program
    return prev


def switch_startup_program(program: Program) -> Program:
    global _startup_program_
    prev, _startup_program_ = _startup_program_, program
    return prev


@contextlib.contextmanager
def program_guard(main_program: Program, startup_program: Optional[Program] = None):
    prev_main = switch_main_program(main_program)
    prev_startup = None
    if startup_program is not None:
        prev_startup = switch_startup_program(startup_program)
    try:
        yield
    finally:
        switch_main_program(prev_main)
        if prev_startup is not None:
            switch_startup_program(prev_startup)


@contextlib.contextmanager
def name_scope(prefix: str):
    with unique_name.guard_prefix(prefix):
        yield


@contextlib.contextmanager
def op_role_guard(program: Program, role: str):
    prev = program._op_role
    program._op_role = role
    try:
        yield
    finally:
        program._op_role = prev
