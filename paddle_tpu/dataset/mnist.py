"""MNIST readers (reference: python/paddle/dataset/mnist.py).

Samples: (image float32[784] in [-1,1], label int64 scalar).
Synthetic mode: class-conditional Gaussian blobs — linearly separable
enough that LeNet/MLP book tests show decreasing loss and >chance
accuracy, deterministic per (split, seed).
"""
from __future__ import annotations

import os

import numpy as np

TRAIN_SIZE = 60000
TEST_SIZE = 10000


def _load_real(split):
    home = os.environ.get("PADDLE_TPU_DATA_HOME")
    if not home:
        return None
    path = os.path.join(home, "mnist", split + ".npz")
    if not os.path.exists(path):
        return None
    d = np.load(path)
    return d["images"], d["labels"]


def _synthetic(n, seed):
    rng = np.random.RandomState(seed)
    centers = np.random.RandomState(1234).uniform(-0.6, 0.6, (10, 784)).astype("float32")
    labels = rng.randint(0, 10, n).astype("int64")
    imgs = centers[labels] + rng.normal(0, 0.35, (n, 784)).astype("float32")
    return np.clip(imgs, -1, 1).astype("float32"), labels


def _reader(split, n, seed):
    def reader():
        real = _load_real(split)
        if real is not None:
            imgs, labels = real
        else:
            imgs, labels = _synthetic(n, seed)
        for i in range(len(labels)):
            yield imgs[i], int(labels[i])

    return reader


def train(size: int = 2048):
    return _reader("train", min(size, TRAIN_SIZE), seed=0)


def test(size: int = 512):
    return _reader("test", min(size, TEST_SIZE), seed=1)
