"""UCI housing readers (reference: python/paddle/dataset/uci_housing.py).

Samples: (features float32[13], price float32[1]).  Synthetic mode: a
fixed random linear model + noise, so fit-a-line style tests converge.
"""
from __future__ import annotations

import numpy as np


def _make(n, seed):
    rng = np.random.RandomState(seed)
    w = np.random.RandomState(7).uniform(-1, 1, (13, 1)).astype("float32")
    x = rng.uniform(-1, 1, (n, 13)).astype("float32")
    y = x @ w + rng.normal(0, 0.1, (n, 1)).astype("float32")
    return x, y.astype("float32")


def _reader(n, seed):
    def reader():
        x, y = _make(n, seed)
        for i in range(n):
            yield x[i], y[i]

    return reader


def train(size: int = 404):
    return _reader(size, seed=0)


def test(size: int = 102):
    return _reader(size, seed=1)
