"""MovieLens-1M readers (reference: python/paddle/dataset/movielens.py).

Samples (reference order): (user_id, gender_id, age_id, job_id,
movie_id, category_ids seq, title_ids seq, rating float).  Synthetic:
ratings follow a low-rank user x movie preference structure (learnable
by the recommender book model).
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "train", "test", "max_user_id", "max_movie_id", "max_job_id",
    "age_table", "movie_categories",
]

_MAX_USER = 6040
_MAX_MOVIE = 3952
_N_CAT = 18
_TITLE_VOCAB = 5175


def max_user_id():
    return _MAX_USER


def max_movie_id():
    return _MAX_MOVIE


def max_job_id():
    return 20


def age_table():
    return [1, 18, 25, 35, 45, 50, 56]


def movie_categories():
    return {i: "cat%d" % i for i in range(_N_CAT)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        u_factor = np.random.RandomState(99).randn(_MAX_USER + 1, 4)
        m_factor = np.random.RandomState(98).randn(_MAX_MOVIE + 1, 4)
        for _ in range(n):
            u = int(rng.randint(1, _MAX_USER + 1))
            m = int(rng.randint(1, _MAX_MOVIE + 1))
            gender = int(rng.randint(0, 2))
            age = int(rng.randint(0, 7))
            job = int(rng.randint(0, 21))
            cats = rng.randint(0, _N_CAT, rng.randint(1, 4)).astype("int64")
            title = rng.randint(0, _TITLE_VOCAB, rng.randint(2, 8)).astype("int64")
            score = float(np.clip(3.0 + u_factor[u] @ m_factor[m], 1.0, 5.0))
            yield u, gender, age, job, m, cats, title, score

    return reader


def train(size: int = 2048):
    return _reader(size, 0)


def test(size: int = 256):
    return _reader(size, 1)
