"""WMT16 en-de NMT readers (reference: python/paddle/dataset/wmt16.py).

Samples: (src ids int64 seq, trg ids int64 seq, trg_next ids int64 seq)
with <s>=0, <e>=1, <unk>=2 conventions like the reference.  Synthetic:
target is a deterministic per-token mapping of the source (learnable by
a seq2seq model — the copy-task family used in tests/book NMT).
"""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "validation", "get_dict"]

BOS, EOS, UNK = 0, 1, 2


def get_dict(lang: str, dict_size: int = 10000, reverse: bool = False):
    d = {i: i for i in range(dict_size)}
    return d


def _reader(n, seed, src_dict_size, trg_dict_size):
    def reader():
        rng = np.random.RandomState(seed)
        lo = 3
        for _ in range(n):
            length = int(rng.randint(4, 16))
            src = rng.randint(lo, src_dict_size, length).astype("int64")
            # deterministic token mapping -> learnable translation
            trg_body = ((src * 7 + 13) % (trg_dict_size - lo) + lo).astype("int64")
            trg = np.concatenate([[BOS], trg_body]).astype("int64")
            trg_next = np.concatenate([trg_body, [EOS]]).astype("int64")
            yield src, trg, trg_next

    return reader


def train(src_dict_size=10000, trg_dict_size=10000, src_lang="en", size=2048):
    return _reader(size, 0, src_dict_size, trg_dict_size)


def test(src_dict_size=10000, trg_dict_size=10000, src_lang="en", size=256):
    return _reader(size, 1, src_dict_size, trg_dict_size)


def validation(src_dict_size=10000, trg_dict_size=10000, src_lang="en", size=256):
    return _reader(size, 2, src_dict_size, trg_dict_size)
