"""CIFAR readers (reference: python/paddle/dataset/cifar.py).

Samples: (image float32[3072] in [0,1], label int64).  Synthetic:
class-conditional colored-noise blobs.
"""
from __future__ import annotations

import numpy as np


def _synthetic(n, classes, seed):
    rng = np.random.RandomState(seed)
    centers = np.random.RandomState(99).uniform(0.2, 0.8, (classes, 3072)).astype("float32")
    labels = rng.randint(0, classes, n).astype("int64")
    imgs = centers[labels] + rng.normal(0, 0.15, (n, 3072)).astype("float32")
    return np.clip(imgs, 0, 1).astype("float32"), labels


def _reader(n, classes, seed):
    def reader():
        imgs, labels = _synthetic(n, classes, seed)
        for i in range(n):
            yield imgs[i], int(labels[i])

    return reader


def train10(size: int = 1024):
    return _reader(size, 10, seed=0)


def test10(size: int = 256):
    return _reader(size, 10, seed=1)


def train100(size: int = 1024):
    return _reader(size, 100, seed=0)


def test100(size: int = 256):
    return _reader(size, 100, seed=1)
