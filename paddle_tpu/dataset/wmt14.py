"""WMT14 fr-en readers (reference: python/paddle/dataset/wmt14.py) —
same sample contract as wmt16 ((src, trg, trg_next) id sequences)."""
from __future__ import annotations

from paddle_tpu.dataset import wmt16 as _w16

__all__ = ["train", "test"]


def train(dict_size=30000, size=2048):
    return _w16._reader(size, 10, dict_size, dict_size)


def test(dict_size=30000, size=256):
    return _w16._reader(size, 11, dict_size, dict_size)
