"""IMDB sentiment readers (reference: python/paddle/dataset/imdb.py).

Samples: (word-id int64 sequence of variable length, label int64 {0,1}).
Synthetic: two token distributions (positive/negative vocab halves bias)
— learnable by bag-of-embeddings models; sequences are variable length to
exercise the padded+length LoD path.
"""
from __future__ import annotations

import numpy as np

VOCAB_SIZE = 5147  # reference's imdb.word_dict() size ballpark


def word_dict():
    return {i: i for i in range(VOCAB_SIZE)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = int(rng.randint(8, 64))
            half = VOCAB_SIZE // 2
            bias_lo = 0 if label == 0 else half
            ids = np.where(
                rng.uniform(size=length) < 0.7,
                rng.randint(bias_lo, bias_lo + half, length),
                rng.randint(0, VOCAB_SIZE, length),
            ).astype("int64")
            yield ids, label

    return reader


def train(word_idx=None, size: int = 1024):
    return _reader(size, seed=0)


def test(word_idx=None, size: int = 256):
    return _reader(size, seed=1)
