"""Oxford-102 flowers readers (reference: python/paddle/dataset/flowers.py).

Samples: (image float32 [3, 224, 224] normalized, label int64 [0, 102)).
Synthetic: class-conditioned color/texture statistics (learnable by a
small CNN).
"""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "valid"]

N_CLASSES = 102


def _reader(n, seed, use_xmap=True):
    def reader():
        rng = np.random.RandomState(seed)
        means = np.random.RandomState(77).uniform(-0.8, 0.8, (N_CLASSES, 3))
        for _ in range(n):
            label = int(rng.randint(0, N_CLASSES))
            img = rng.normal(0.0, 0.3, (3, 224, 224)).astype("float32")
            img += means[label][:, None, None]
            yield img.astype("float32"), label

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, size: int = 512):
    return _reader(size, 0)


def test(mapper=None, buffered_size=1024, use_xmap=True, size: int = 128):
    return _reader(size, 1)


def valid(mapper=None, buffered_size=1024, use_xmap=True, size: int = 128):
    return _reader(size, 2)
