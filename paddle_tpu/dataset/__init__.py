"""Dataset zoo with the reference's reader API.

Reference: python/paddle/dataset/ (mnist, cifar, uci_housing, imdb, ...)
— each module exposes ``train()``/``test()`` returning sample-tuple
generators consumed by ``paddle_tpu.reader`` decorators.

This environment has no network egress, so the zoo generates
*deterministic synthetic* data with the exact shapes/dtypes/ranges of the
real datasets (documented per module).  Swap in real data by pointing
``PADDLE_TPU_DATA_HOME`` at pre-downloaded copies; modules check it first.
"""
from paddle_tpu.dataset import (  # noqa: F401
    cifar, flowers, imdb, mnist, movielens, uci_housing, voc2012, wmt14, wmt16,
)
