"""PASCAL VOC2012 segmentation readers (reference:
python/paddle/dataset/voc2012.py).

Samples: (image float32 [3, H, W], segmentation mask int32 [H, W] with
class ids 0..20 and 255=ignore).  Synthetic: rectangular object blobs on
background — enough structure for a tiny FCN to overfit.
"""
from __future__ import annotations

import numpy as np

__all__ = ["train", "test", "val"]

N_CLASSES = 21
_H = _W = 96


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            img = rng.normal(0, 0.25, (3, _H, _W)).astype("float32")
            mask = np.zeros((_H, _W), np.int32)
            for _obj in range(int(rng.randint(1, 4))):
                cls = int(rng.randint(1, N_CLASSES))
                y0, x0 = rng.randint(0, _H - 16), rng.randint(0, _W - 16)
                hh, ww = rng.randint(8, 16), rng.randint(8, 16)
                mask[y0 : y0 + hh, x0 : x0 + ww] = cls
                img[:, y0 : y0 + hh, x0 : x0 + ww] += cls / N_CLASSES - 0.5
            yield img, mask

    return reader


def train(size: int = 256):
    return _reader(size, 0)


def test(size: int = 64):
    return _reader(size, 1)


def val(size: int = 64):
    return _reader(size, 2)
