"""Parameter initializers — append init ops to the startup program.

Reference: python/paddle/fluid/initializer.py (ConstantInitializer,
UniformInitializer, NormalInitializer, XavierInitializer, MSRAInitializer,
NumpyArrayInitializer).  RNG ops take deterministic seeds from the
program (framework.Program.next_seed) so startup is reproducible and
jit-cacheable.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = [
    "Bilinear", "BilinearInitializer", "init_on_cpu",
    "Constant",
    "Uniform",
    "Normal",
    "TruncatedNormal",
    "Xavier",
    "MSRA",
    "NumpyArrayInitializer",
    "force_init_on_cpu",
]


def force_init_on_cpu():
    return False


class Initializer:
    def __call__(self, var, block):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value=0.0, force_cpu=False):
        self.value = value

    def __call__(self, var, block):
        return block.append_op(
            type="fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "dtype": var.dtype, "value": float(self.value)},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, block):
        seed = self.seed or block.program.next_seed()
        return block.append_op(
            type="uniform_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "min": self.low,
                "max": self.high,
                "seed": seed,
            },
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, block):
        seed = self.seed or block.program.next_seed()
        return block.append_op(
            type="gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": seed,
            },
        )


class TruncatedNormalInitializer(NormalInitializer):
    def __call__(self, var, block):
        seed = self.seed or block.program.next_seed()
        return block.append_op(
            type="truncated_gaussian_random",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(var.shape),
                "dtype": var.dtype,
                "mean": self.loc,
                "std": self.scale,
                "seed": seed,
            },
        )


def _fan_in_out(var):
    shape = var.shape
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv filter OIHW
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierInitializer(Initializer):
    """Glorot (reference: initializer.py XavierInitializer)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out, self.seed = uniform, fan_in, fan_out, seed

    def __call__(self, var, block):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        seed = self.seed or block.program.next_seed()
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            attrs = {"min": -limit, "max": limit}
            op = "uniform_random"
        else:
            std = math.sqrt(2.0 / (fi + fo))
            attrs = {"mean": 0.0, "std": std}
            op = "gaussian_random"
        attrs.update({"shape": list(var.shape), "dtype": var.dtype, "seed": seed})
        return block.append_op(type=op, outputs={"Out": [var.name]}, attrs=attrs)


class MSRAInitializer(Initializer):
    """He init (reference: initializer.py MSRAInitializer)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in, self.seed = uniform, fan_in, seed

    def __call__(self, var, block):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in if self.fan_in is not None else fi
        seed = self.seed or block.program.next_seed()
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            attrs = {"min": -limit, "max": limit}
            op = "uniform_random"
        else:
            std = math.sqrt(2.0 / fi)
            attrs = {"mean": 0.0, "std": std}
            op = "gaussian_random"
        attrs.update({"shape": list(var.shape), "dtype": var.dtype, "seed": seed})
        return block.append_op(type=op, outputs={"Out": [var.name]}, attrs=attrs)


class NumpyArrayInitializer(Initializer):
    def __init__(self, value):
        self.value = np.asarray(value)

    def __call__(self, var, block):
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={
                "shape": list(self.value.shape),
                "dtype": var.dtype,
                "values": self.value.flatten().tolist(),
            },
        )


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
TruncatedNormal = TruncatedNormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer


class BilinearInitializer(Initializer):
    """reference: initializer.py BilinearInitializer — seeds a
    conv_transpose filter [C_out, C_in, kh, kw] with bilinear
    upsampling kernels (used to warm-start learnable upsampling)."""

    def __call__(self, var, block):
        shape = [int(s) for s in var.shape]
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D filter")
        weight = np.zeros(shape, dtype="float32")
        kh, kw = shape[2], shape[3]
        f_h, f_w = np.ceil(kh / 2.0), np.ceil(kw / 2.0)
        c_h, c_w = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h), (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        yy, xx = np.meshgrid(np.arange(kh), np.arange(kw), indexing="ij")
        kern = (1 - np.abs(yy / f_h - c_h)) * (1 - np.abs(xx / f_w - c_w))
        for i in range(shape[0]):
            for j in range(shape[1]):
                weight[i, j] = kern
        return block.append_op(
            type="assign_value",
            outputs={"Out": [var.name]},
            attrs={"shape": shape, "dtype": "float32",
                   "values": weight.flatten().tolist()},
        )


import contextlib as _contextlib


@_contextlib.contextmanager
def init_on_cpu():
    """reference: initializer.py init_on_cpu — placement hint; XLA owns
    placement on this build, so this is a documented no-op context."""
    yield


Bilinear = BilinearInitializer
