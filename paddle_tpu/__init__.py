"""paddle_tpu: a TPU-native deep-learning framework with the capability
surface of PaddlePaddle Fluid (reference mounted at /root/reference).

The user-facing API mirrors ``paddle.fluid``:

    import paddle_tpu.fluid as fluid
    x = fluid.layers.data('x', [784])
    y = fluid.layers.fc(x, 10, act='softmax')
    ...
    exe = fluid.Executor(fluid.TPUPlace(0))

Design: a Python graph IR (framework.py) lowers wholesale into single
jitted XLA modules (core/lowering.py, executor.py); distributed training
uses jax.sharding meshes + GSPMD instead of NCCL rings (parallel/).
"""
from paddle_tpu import framework
from paddle_tpu.framework import (
    CPUPlace,
    CUDAPlace,
    Place,
    Program,
    TPUPlace,
    cpu_places,
    cuda_pinned_places,
    cuda_places,
    is_compiled_with_cuda,
    default_main_program,
    default_startup_program,
    in_dygraph_mode,
    name_scope,
    program_guard,
)
from paddle_tpu.executor import AsyncExecutor, Executor
from paddle_tpu.scope import Scope, global_scope, scope_guard

from paddle_tpu import (
    backward,
    clip,
    initializer,
    layers,
    metrics,
    optimizer,
    regularizer,
    unique_name,
)
from paddle_tpu.backward import append_backward, gradients
from paddle_tpu.param_attr import ParamAttr, WeightNormParamAttr
from paddle_tpu import parallel
from paddle_tpu import dygraph
from paddle_tpu import distributed
from paddle_tpu import transpiler
from paddle_tpu.transpiler import (
    DistributeTranspiler,
    DistributeTranspilerConfig,
    InferenceTranspiler,
)
from paddle_tpu import contrib
from paddle_tpu import inference
from paddle_tpu import native
from paddle_tpu.fluid_dataset import DatasetFactory, InMemoryDataset, QueueDataset
from paddle_tpu import monitor
from paddle_tpu import profiler
from paddle_tpu import serving
from paddle_tpu import sharding
from paddle_tpu import memory
from paddle_tpu import trainer_desc
from paddle_tpu.trainer_desc import TrainerFactory
from paddle_tpu import io_fs
from paddle_tpu import incubate
from paddle_tpu import io
from paddle_tpu import reader
from paddle_tpu import dataset
from paddle_tpu import flags
from paddle_tpu.flags import get_flags, set_flags
from paddle_tpu import nets
from paddle_tpu import dygraph_grad_clip
from paddle_tpu import recordio_writer
from paddle_tpu.parallel.compiled_program import ParallelExecutor
from paddle_tpu.optimizer import ExponentialMovingAverage
from paddle_tpu import install_check
from paddle_tpu.layers import learning_rate_scheduler as learning_rate_decay

# LoDTensor/Tensor surface: device arrays ARE the tensors on this build;
# the scope's tensor view carries the set/shape API (reference
# lod_tensor.h analog lives in the padded encoding, SURVEY.md §7)
from paddle_tpu.scope import _TensorView as Tensor

LoDTensor = Tensor
LoDTensorArray = list
from paddle_tpu.reader import PyReader, batch
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.io import (
    load_inference_model,
    load_params,
    load_persistables,
    load_vars,
    save_inference_model,
    save_params,
    save_persistables,
    save_program,
    save_vars,
)
from paddle_tpu.parallel.compiled_program import CompiledProgram
from paddle_tpu.parallel.strategy import (
    BuildStrategy,
    DistributedStrategy,
    ExecutionStrategy,
)

__version__ = "0.1.0"


def CUDAPinnedPlace():  # API parity shim
    return CPUPlace()
