"""Dygraph checkpointing (reference: python/paddle/fluid/dygraph/
checkpoint.py save_dygraph/load_dygraph)."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path: str):
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    np.savez(model_path + ".pdparams.npz", **arrays)


def load_dygraph(model_path: str):
    data = np.load(model_path + ".pdparams.npz")
    return {k: data[k] for k in data.files}, None
