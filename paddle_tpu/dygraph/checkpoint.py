"""Dygraph checkpointing (reference: python/paddle/fluid/dygraph/
checkpoint.py save_dygraph/load_dygraph)."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["save_dygraph", "load_dygraph"]


def save_dygraph(state_dict, model_path: str):
    os.makedirs(os.path.dirname(model_path) or ".", exist_ok=True)
    arrays = {k: np.asarray(v) for k, v in state_dict.items()}
    np.savez(model_path + ".pdparams.npz", **arrays)


def load_dygraph(model_path: str):
    data = np.load(model_path + ".pdparams.npz")
    return {k: data[k] for k in data.files}, None


def save_persistables(model_dict, dirname="save_dir", optimizers=None):
    """reference: dygraph/checkpoint.py save_persistables (legacy alias
    of save_dygraph over a state dict)."""
    return save_dygraph(model_dict, dirname)


def load_persistables(dirname="save_dir"):
    """reference: dygraph/checkpoint.py load_persistables."""
    return load_dygraph(dirname)
