"""Dygraph Layer base class (reference: python/paddle/fluid/dygraph/
layers.py:31)."""
from __future__ import annotations

import collections
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from paddle_tpu import framework, unique_name
from paddle_tpu.framework import Parameter, Variable
from paddle_tpu.layer_helper import LayerHelper

__all__ = ["Layer"]


class Layer:
    def __init__(self, name_scope: Optional[str] = None, dtype: str = "float32"):
        self._full_name = unique_name.generate(
            (name_scope or self.__class__.__name__.lower()).split("/")[-1]
        )
        self._dtype = dtype
        self._parameters: "collections.OrderedDict[str, Parameter]" = collections.OrderedDict()
        self._sub_layers: "collections.OrderedDict[str, Layer]" = collections.OrderedDict()
        self.training = True

    def full_name(self) -> str:
        return self._full_name

    # --- parameter management ---
    def create_parameter(self, attr, shape, dtype="float32", is_bias=False, default_initializer=None):
        helper = LayerHelper(self._full_name, param_attr=attr)
        return helper.create_parameter(attr, shape, dtype, is_bias, default_initializer)

    def add_parameter(self, name: str, parameter: Parameter) -> Parameter:
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer") -> "Layer":
        self._sub_layers[name] = sublayer
        return sublayer

    def parameters(self, include_sublayers: bool = True) -> List[Parameter]:
        out = list(self._parameters.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.parameters())
        return out

    def sublayers(self, include_sublayers: bool = True) -> List["Layer"]:
        out = list(self._sub_layers.values())
        if include_sublayers:
            for l in self._sub_layers.values():
                out.extend(l.sublayers())
        return out

    def named_parameters(self, prefix: str = "") -> Iterator[Tuple[str, Parameter]]:
        for name, p in self._parameters.items():
            yield (prefix + name if not prefix else prefix + "." + name), p
        for lname, l in self._sub_layers.items():
            sub_prefix = lname if not prefix else prefix + "." + lname
            yield from l.named_parameters(sub_prefix)

    # --- train/eval ---
    def train(self):
        self.training = True
        for l in self._sub_layers.values():
            l.train()

    def eval(self):
        self.training = False
        for l in self._sub_layers.values():
            l.eval()

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_gradient()

    # --- state dict ---
    def state_dict(self, prefix: str = "") -> Dict[str, np.ndarray]:
        return {name: p.numpy() for name, p in self.named_parameters(prefix)}

    def set_dict(self, state: Dict[str, np.ndarray]):
        import jax.numpy as jnp

        named = dict(self.named_parameters())
        for name, value in state.items():
            if name in named:
                named[name]._dy_value = jnp.asarray(value)

    load_dict = set_dict

    # --- call protocol ---
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        return self.forward(*args, **kwargs)

    def __setattr__(self, name, value):
        if isinstance(value, Parameter):
            self.__dict__.setdefault("_parameters", collections.OrderedDict())[name] = value
        elif isinstance(value, Layer):
            self.__dict__.setdefault("_sub_layers", collections.OrderedDict())[name] = value
        object.__setattr__(self, name, value)
