"""Dygraph layer zoo (reference: python/paddle/fluid/dygraph/nn.py:34-2533
— Conv2D, FC, BatchNorm, Embedding, LayerNorm, Pool2D...).

Each Layer owns eagerly-initialized parameters and calls the functional
``paddle_tpu.layers`` ops, which dispatch through the dygraph tracer.
"""
from __future__ import annotations

from typing import Optional

from paddle_tpu import layers
from paddle_tpu.dygraph.layers import Layer

__all__ = [
    "Conv2D", "FC", "Linear", "BatchNorm", "Embedding", "LayerNorm",
    "Pool2D", "Conv2DTranspose", "GroupNorm", "PRelu", "SpectralNorm",
    "GRUUnit", "NCE", "BilinearTensorProduct", "Conv3D",
    "Conv3DTranspose", "TreeConv", "RowConv", "SequenceConv",
]


class Conv2D(Layer):
    def __init__(
        self,
        name_scope=None,
        num_filters=None,
        filter_size=None,
        stride=1,
        padding=0,
        dilation=1,
        groups=1,
        param_attr=None,
        bias_attr=None,
        act=None,
        dtype="float32",
        num_channels=None,
    ):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act

    def forward(self, input):
        # parameters are created on first forward (shape depends on input
        # channels, like the reference) and cached after
        if not hasattr(self, "_built"):
            import numpy as np

            from paddle_tpu.layer_helper import LayerHelper

            num_channels = input.shape[1]
            fsize = self._filter_size if isinstance(self._filter_size, (list, tuple)) else [self._filter_size] * 2
            filter_shape = [self._num_filters, num_channels // self._groups] + list(fsize)
            helper = LayerHelper(self._full_name, param_attr=self._param_attr, bias_attr=self._bias_attr)
            from paddle_tpu import initializer

            fan_in = (num_channels // self._groups) * int(np.prod(fsize))
            std = (2.0 / fan_in) ** 0.5
            self.weight = helper.create_parameter(
                self._param_attr, shape=filter_shape, dtype=self._dtype,
                default_initializer=initializer.Normal(0.0, std),
            )
            self.bias = helper.create_parameter(
                self._bias_attr, shape=[self._num_filters], dtype=self._dtype, is_bias=True
            )
            self._built = True
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="conv2d",
            inputs={"Input": [input], "Filter": [self.weight]},
            outputs={"Output": [out]},
            attrs={
                "strides": [self._stride] * 2 if isinstance(self._stride, int) else list(self._stride),
                "paddings": [self._padding] * 2 if isinstance(self._padding, int) else list(self._padding),
                "dilations": [self._dilation] * 2 if isinstance(self._dilation, int) else list(self._dilation),
                "groups": self._groups,
            },
        )
        if self.bias is not None:
            tmp = helper.create_variable_for_type_inference(self._dtype)
            helper.append_op(
                type="elementwise_add",
                inputs={"X": [out], "Y": [self.bias]},
                outputs={"Out": [tmp]},
                attrs={"axis": 1},
            )
            out = tmp
        return helper.append_activation(out)


class Linear(Layer):
    """Modern Linear (dygraph FC with explicit input_dim)."""

    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(None, dtype)
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, param_attr=param_attr, bias_attr=bias_attr)
        self.weight = helper.create_parameter(param_attr, shape=[input_dim, output_dim], dtype=dtype)
        self.bias = helper.create_parameter(bias_attr, shape=[output_dim], dtype=dtype, is_bias=True)
        self._act = act

    def forward(self, input):
        out = layers.matmul(input, self.weight)
        if self.bias is not None:
            out = out + self.bias
        if self._act:
            out = getattr(layers, self._act)(out)
        return out


class FC(Linear):
    """reference dygraph FC (size-only; input dim bound on first call)."""

    def __init__(self, name_scope=None, size=None, param_attr=None, bias_attr=None,
                 num_flatten_dims=1, dtype="float32", act=None):
        Layer.__init__(self, name_scope, dtype)
        self._size = size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._num_flatten_dims = num_flatten_dims
        self._act = act

    def forward(self, input):
        import numpy as np

        if not hasattr(self, "weight"):
            in_dim = int(np.prod(input.shape[self._num_flatten_dims :]))
            from paddle_tpu.layer_helper import LayerHelper

            helper = LayerHelper(self._full_name, param_attr=self._param_attr, bias_attr=self._bias_attr)
            self.weight = helper.create_parameter(self._param_attr, shape=[in_dim, self._size], dtype=self._dtype)
            self.bias = helper.create_parameter(self._bias_attr, shape=[self._size], dtype=self._dtype, is_bias=True)
        out = layers.mul(input, self.weight, x_num_col_dims=self._num_flatten_dims)
        if self.bias is not None:
            out = out + self.bias
        if self._act:
            out = getattr(layers, self._act)(out)
        return out


class BatchNorm(Layer):
    def __init__(self, name_scope=None, num_channels=None, act=None, is_test=False,
                 momentum=0.9, epsilon=1e-5, param_attr=None, bias_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        from paddle_tpu import initializer
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, param_attr=param_attr, bias_attr=bias_attr)
        self.weight = helper.create_parameter(
            param_attr, shape=[num_channels], dtype=dtype,
            default_initializer=initializer.Constant(1.0),
        )
        self.bias = helper.create_parameter(bias_attr, shape=[num_channels], dtype=dtype, is_bias=True)
        self._mean = helper.create_parameter(
            None, shape=[num_channels], dtype=dtype, default_initializer=initializer.Constant(0.0)
        )
        self._variance = helper.create_parameter(
            None, shape=[num_channels], dtype=dtype, default_initializer=initializer.Constant(1.0)
        )
        self._mean.stop_gradient = True
        self._variance.stop_gradient = True
        self._momentum = momentum
        self._epsilon = epsilon
        self._act = act
        self._is_test = is_test

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        saved_mean = helper.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        saved_var = helper.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        helper.append_op(
            type="batch_norm",
            inputs={
                "X": [input], "Scale": [self.weight], "Bias": [self.bias],
                "Mean": [self._mean], "Variance": [self._variance],
            },
            outputs={
                "Y": [out], "MeanOut": [self._mean], "VarianceOut": [self._variance],
                "SavedMean": [saved_mean], "SavedVariance": [saved_var],
            },
            attrs={
                "momentum": self._momentum, "epsilon": self._epsilon,
                "is_test": self._is_test or not self.training,
            },
        )
        return helper.append_activation(out)


class Embedding(Layer):
    def __init__(self, name_scope=None, size=None, is_sparse=False, param_attr=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, param_attr=param_attr)
        self.weight = helper.create_parameter(param_attr, shape=size, dtype=dtype)

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="lookup_table",
            inputs={"W": [self.weight], "Ids": [input]},
            outputs={"Out": [out]},
            attrs={"padding_idx": -1},
        )
        return out


class LayerNorm(Layer):
    def __init__(self, name_scope=None, normalized_shape=None, scale=True, shift=True,
                 epsilon=1e-5, param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        from paddle_tpu import initializer
        from paddle_tpu.layer_helper import LayerHelper

        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._shape = list(normalized_shape)
        helper = LayerHelper(self._full_name, param_attr=param_attr, bias_attr=bias_attr)
        self.weight = helper.create_parameter(
            param_attr, shape=self._shape, dtype=dtype,
            default_initializer=initializer.Constant(1.0),
        ) if scale else None
        self.bias = helper.create_parameter(bias_attr, shape=self._shape, dtype=dtype, is_bias=True) if shift else None
        self._epsilon = epsilon
        self._act = act

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        mean = helper.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        var = helper.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        inputs = {"X": [input]}
        if self.weight is not None:
            inputs["Scale"] = [self.weight]
        if self.bias is not None:
            inputs["Bias"] = [self.bias]
        helper.append_op(
            type="layer_norm",
            inputs=inputs,
            outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
            attrs={"epsilon": self._epsilon, "begin_norm_axis": len(input.shape) - len(self._shape)},
        )
        return helper.append_activation(out)


class Pool2D(Layer):
    def __init__(self, name_scope=None, pool_size=2, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._pool_size = pool_size
        self._pool_type = pool_type
        self._pool_stride = pool_stride
        self._pool_padding = pool_padding
        self._global_pooling = global_pooling

    def forward(self, input):
        return layers.pool2d(
            input,
            pool_size=self._pool_size,
            pool_type=self._pool_type,
            pool_stride=self._pool_stride,
            pool_padding=self._pool_padding,
            global_pooling=self._global_pooling,
        )


class Conv2DTranspose(Layer):
    """reference: dygraph/nn.py Conv2DTranspose — filter [in_c,
    out_c//groups, kh, kw] created on first forward (needs in channels)."""

    def __init__(self, name_scope=None, num_filters=None, filter_size=None,
                 output_size=None, padding=0, stride=1, dilation=1, groups=1,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = filter_size
        self._output_size = output_size
        self._padding = padding
        self._stride = stride
        self._dilation = dilation
        self._groups = groups
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        if not hasattr(self, "weight"):
            num_channels = int(input.shape[1])
            fsize = (self._filter_size if isinstance(self._filter_size, (list, tuple))
                     else [self._filter_size] * 2)
            helper = LayerHelper(self._full_name, param_attr=self._param_attr,
                                 bias_attr=self._bias_attr)
            self.weight = helper.create_parameter(
                self._param_attr,
                shape=[num_channels, self._num_filters // self._groups] + list(fsize),
                dtype=self._dtype,
            )
            self.bias = helper.create_parameter(
                self._bias_attr, shape=[self._num_filters], dtype=self._dtype,
                is_bias=True,
            )
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="conv2d_transpose",
            inputs={"Input": [input], "Filter": [self.weight]},
            outputs={"Output": [out]},
            attrs={
                "strides": [self._stride] * 2 if isinstance(self._stride, int) else list(self._stride),
                "paddings": [self._padding] * 2 if isinstance(self._padding, int) else list(self._padding),
                "dilations": [self._dilation] * 2 if isinstance(self._dilation, int) else list(self._dilation),
                "groups": self._groups,
            },
        )
        if self.bias is not None:
            tmp = helper.create_variable_for_type_inference(self._dtype)
            helper.append_op(
                type="elementwise_add",
                inputs={"X": [out], "Y": [self.bias]},
                outputs={"Out": [tmp]}, attrs={"axis": 1},
            )
            out = tmp
        return helper.append_activation(out)


class GroupNorm(Layer):
    """reference: dygraph/nn.py GroupNorm."""

    def __init__(self, name_scope=None, groups=None, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None, channels=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._groups = groups
        self._epsilon = epsilon
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        if channels is not None:
            self._build(channels)

    def _build(self, channels):
        from paddle_tpu import initializer
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, param_attr=self._param_attr,
                             bias_attr=self._bias_attr)
        self.weight = helper.create_parameter(
            self._param_attr, shape=[channels], dtype=self._dtype,
            default_initializer=initializer.Constant(1.0))
        self.bias = helper.create_parameter(
            self._bias_attr, shape=[channels], dtype=self._dtype, is_bias=True)

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        if not hasattr(self, "weight"):
            self._build(int(input.shape[1]))
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        mean = helper.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        var = helper.create_variable_for_type_inference(self._dtype, stop_gradient=True)
        helper.append_op(
            type="group_norm",
            inputs={"X": [input], "Scale": [self.weight], "Bias": [self.bias]},
            outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
            attrs={"epsilon": self._epsilon, "groups": self._groups},
        )
        return helper.append_activation(out)


class PRelu(Layer):
    """reference: dygraph/nn.py PRelu — mode all/channel/element; the
    channel/element alpha shape binds on first forward."""

    def __init__(self, name_scope=None, mode="all", param_attr=None,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        if mode not in ("all", "channel", "element"):
            raise ValueError("mode should be 'all', 'channel' or 'element'")
        self._mode = mode
        self._param_attr = param_attr
        if mode == "all":
            self._build([1])

    def _build(self, alpha_shape):
        from paddle_tpu import initializer
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, param_attr=self._param_attr)
        self.weight = helper.create_parameter(
            self._param_attr, shape=alpha_shape, dtype=self._dtype,
            default_initializer=initializer.Constant(0.25))

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        if not hasattr(self, "weight"):
            self._build([int(input.shape[1])] if self._mode == "channel"
                        else [int(s) for s in input.shape[1:]])
        helper = LayerHelper(self._full_name)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="prelu", inputs={"X": [input], "Alpha": [self.weight]},
            outputs={"Out": [out]}, attrs={"mode": self._mode},
        )
        return out


class SpectralNorm(Layer):
    """reference: dygraph/nn.py SpectralNorm — U/V power-iteration
    buffers bind to the weight's shape on first forward."""

    def __init__(self, name_scope=None, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps

    def forward(self, weight):
        import numpy as np

        from paddle_tpu import initializer
        from paddle_tpu.layer_helper import LayerHelper
        from paddle_tpu.param_attr import ParamAttr

        if not hasattr(self, "weight_u"):
            if any(int(s) < 0 for s in weight.shape):
                raise ValueError(
                    "SpectralNorm requires a fully static weight shape, got %s"
                    % (weight.shape,))
            h = int(weight.shape[self._dim])
            w = int(np.prod([int(s) for i, s in enumerate(weight.shape)
                             if i != self._dim]))
            helper = LayerHelper(self._full_name)
            self.weight_u = helper.create_parameter(
                ParamAttr(trainable=False), shape=[h], dtype=self._dtype,
                default_initializer=initializer.Normal(0.0, 1.0))
            self.weight_v = helper.create_parameter(
                ParamAttr(trainable=False), shape=[w], dtype=self._dtype,
                default_initializer=initializer.Normal(0.0, 1.0))
        helper = LayerHelper(self._full_name)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="spectral_norm",
            inputs={"Weight": [weight], "U": [self.weight_u], "V": [self.weight_v]},
            outputs={"Out": [out]},
            attrs={"dim": int(self._dim), "power_iters": int(self._power_iters),
                   "eps": float(self._eps)},
        )
        return out


class GRUUnit(Layer):
    """reference: dygraph/nn.py GRUUnit — one GRU step over a
    pre-projected input [B, 3H]; returns (hidden, reset_hidden_prev,
    gate) like the op."""

    def __init__(self, name_scope=None, size=None, param_attr=None,
                 bias_attr=None, activation="tanh",
                 gate_activation="sigmoid", origin_mode=False,
                 dtype="float32"):
        super().__init__(name_scope, dtype)
        from paddle_tpu.layer_helper import LayerHelper

        h = size // 3
        helper = LayerHelper(self._full_name, param_attr=param_attr,
                             bias_attr=bias_attr)
        self.weight = helper.create_parameter(param_attr, shape=[h, 3 * h],
                                              dtype=dtype)
        self.bias = helper.create_parameter(bias_attr, shape=[1, 3 * h],
                                            dtype=dtype, is_bias=True)
        self._activation = activation
        self._gate_activation = gate_activation
        self._origin_mode = origin_mode

    def forward(self, input, hidden):
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name)
        gate = helper.create_variable_for_type_inference(self._dtype)
        reset_h = helper.create_variable_for_type_inference(self._dtype)
        out_h = helper.create_variable_for_type_inference(self._dtype)
        ins = {"Input": [input], "HiddenPrev": [hidden], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        helper.append_op(
            type="gru_unit", inputs=ins,
            outputs={"Gate": [gate], "ResetHiddenPrev": [reset_h],
                     "Hidden": [out_h]},
            attrs={"activation": self._activation,
                   "gate_activation": self._gate_activation,
                   "origin_mode": self._origin_mode},
        )
        return out_h, reset_h, gate


class NCE(Layer):
    """reference: dygraph/nn.py NCE — noise-contrastive estimation loss
    head owning the [num_total_classes, dim] weight table."""

    def __init__(self, name_scope=None, num_total_classes=None, dim=None,
                 sample_weight=None, param_attr=None, bias_attr=None,
                 num_neg_samples=10, sampler="uniform", seed=0,
                 is_sparse=False, dtype="float32", custom_dist=None):
        super().__init__(name_scope, dtype)
        if custom_dist is not None:
            sampler = "custom_dist"
        if sampler not in ("uniform", "log_uniform", "custom_dist"):
            raise ValueError("NCE: unknown sampler %r" % sampler)
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, param_attr=param_attr,
                             bias_attr=bias_attr)
        self.weight = helper.create_parameter(
            param_attr, shape=[num_total_classes, dim], dtype=dtype)
        self.bias = helper.create_parameter(
            bias_attr, shape=[num_total_classes], dtype=dtype, is_bias=True)
        self._sample_weight = sample_weight
        self._attrs = {"num_neg_samples": num_neg_samples, "seed": seed,
                       "sampler": sampler}
        if custom_dist is not None:
            import numpy as _np

            dist = _np.asarray(custom_dist, dtype=_np.float32).reshape(-1)
            if dist.shape[0] != num_total_classes:
                raise ValueError(
                    "NCE: custom_dist length %d != num_total_classes %d"
                    % (dist.shape[0], num_total_classes))
            self._attrs["custom_dist"] = dist

    def forward(self, input, label, sample_weight=None):
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name)
        cost = helper.create_variable_for_type_inference(self._dtype)
        ins = {"Input": [input], "Label": [label], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        sw = sample_weight if sample_weight is not None else self._sample_weight
        if sw is not None:
            ins["SampleWeight"] = [sw]
        helper.append_op(type="nce", inputs=ins, outputs={"Cost": [cost]},
                         attrs=dict(self._attrs))
        return cost


class BilinearTensorProduct(Layer):
    """reference: dygraph/nn.py BilinearTensorProduct —
    out[b, k] = x[b]^T W[k] y[b] + bias."""

    def __init__(self, name_scope=None, size=None, name=None, act=None,
                 param_attr=None, bias_attr=None, input1_dim=None,
                 input2_dim=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._size = size
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act
        if input1_dim is not None and input2_dim is not None:
            self._build(input1_dim, input2_dim)

    def _build(self, m, n):
        from paddle_tpu.layer_helper import LayerHelper

        helper = LayerHelper(self._full_name, param_attr=self._param_attr,
                             bias_attr=self._bias_attr)
        self.weight = helper.create_parameter(
            self._param_attr, shape=[self._size, m, n], dtype=self._dtype)
        self.bias = helper.create_parameter(
            self._bias_attr, shape=[1, self._size], dtype=self._dtype,
            is_bias=True)

    def forward(self, x, y):
        from paddle_tpu.layer_helper import LayerHelper

        if not hasattr(self, "weight"):
            self._build(int(x.shape[-1]), int(y.shape[-1]))
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        ins = {"X": [x], "Y": [y], "Weight": [self.weight]}
        if self.bias is not None:
            ins["Bias"] = [self.bias]
        helper.append_op(type="bilinear_tensor_product", inputs=ins,
                         outputs={"Out": [out]}, attrs={})
        return helper.append_activation(out)


class Conv3D(Layer):
    """reference: dygraph/nn.py Conv3D — NCDHW."""

    def __init__(self, name_scope=None, num_filters=None, filter_size=None,
                 stride=1, padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._a = dict(num_filters=num_filters, filter_size=filter_size,
                       stride=stride, padding=padding, dilation=dilation,
                       groups=groups)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        a = self._a
        if not hasattr(self, "weight"):
            c = int(input.shape[1])
            fs = (a["filter_size"] if isinstance(a["filter_size"], (list, tuple))
                  else [a["filter_size"]] * 3)
            helper = LayerHelper(self._full_name, param_attr=self._param_attr,
                                 bias_attr=self._bias_attr)
            self.weight = helper.create_parameter(
                self._param_attr,
                shape=[a["num_filters"], c // a["groups"]] + list(fs),
                dtype=self._dtype)
            self.bias = helper.create_parameter(
                self._bias_attr, shape=[a["num_filters"]], dtype=self._dtype,
                is_bias=True)
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="conv3d", inputs={"Input": [input], "Filter": [self.weight]},
            outputs={"Output": [out]},
            attrs={"strides": a["stride"], "paddings": a["padding"],
                   "dilations": a["dilation"], "groups": a["groups"]})
        if self.bias is not None:
            tmp = helper.create_variable_for_type_inference(self._dtype)
            helper.append_op(type="elementwise_add",
                             inputs={"X": [out], "Y": [self.bias]},
                             outputs={"Out": [tmp]}, attrs={"axis": 1})
            out = tmp
        return helper.append_activation(out)


class Conv3DTranspose(Layer):
    """reference: dygraph/nn.py Conv3DTranspose."""

    def __init__(self, name_scope=None, num_filters=None, filter_size=None,
                 stride=1, padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._a = dict(num_filters=num_filters, filter_size=filter_size,
                       stride=stride, padding=padding, dilation=dilation,
                       groups=groups)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act

    def forward(self, input):
        from paddle_tpu.layer_helper import LayerHelper

        a = self._a
        if not hasattr(self, "weight"):
            c = int(input.shape[1])
            fs = (a["filter_size"] if isinstance(a["filter_size"], (list, tuple))
                  else [a["filter_size"]] * 3)
            helper = LayerHelper(self._full_name, param_attr=self._param_attr,
                                 bias_attr=self._bias_attr)
            self.weight = helper.create_parameter(
                self._param_attr,
                shape=[c, a["num_filters"] // a["groups"]] + list(fs),
                dtype=self._dtype)
            self.bias = helper.create_parameter(
                self._bias_attr, shape=[a["num_filters"]], dtype=self._dtype,
                is_bias=True)
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="conv3d_transpose",
            inputs={"Input": [input], "Filter": [self.weight]},
            outputs={"Output": [out]},
            attrs={"strides": a["stride"], "paddings": a["padding"],
                   "dilations": a["dilation"], "groups": a["groups"]})
        if self.bias is not None:
            tmp = helper.create_variable_for_type_inference(self._dtype)
            helper.append_op(type="elementwise_add",
                             inputs={"X": [out], "Y": [self.bias]},
                             outputs={"Out": [tmp]}, attrs={"axis": 1})
            out = tmp
        return helper.append_activation(out)


class TreeConv(Layer):
    """reference: dygraph/nn.py TreeConv — TBCNN over (nodes, edges)."""

    def __init__(self, name_scope=None, output_size=None, num_filters=1,
                 max_depth=2, act="tanh", param_attr=None, bias_attr=None,
                 name=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._output_size = output_size
        self._num_filters = num_filters
        self._max_depth = max_depth
        self._act = act
        self._param_attr = param_attr
        self._bias_attr = bias_attr

    def forward(self, nodes_vector, edge_set):
        from paddle_tpu.layer_helper import LayerHelper

        if not hasattr(self, "weight"):
            f = int(nodes_vector.shape[-1])
            helper = LayerHelper(self._full_name, param_attr=self._param_attr,
                                 bias_attr=self._bias_attr)
            self.weight = helper.create_parameter(
                self._param_attr,
                shape=[f, 3, self._output_size, self._num_filters],
                dtype=self._dtype)
            self.bias = helper.create_parameter(
                self._bias_attr, shape=[self._num_filters], dtype=self._dtype,
                is_bias=True)
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        helper.append_op(
            type="tree_conv",
            inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                    "Filter": [self.weight]},
            outputs={"Out": [out]},
            attrs={"max_depth": int(self._max_depth)})
        if self.bias is not None:
            tmp = helper.create_variable_for_type_inference(self._dtype)
            helper.append_op(type="elementwise_add",
                             inputs={"X": [out], "Y": [self.bias]},
                             outputs={"Out": [tmp]}, attrs={"axis": 3})
            out = tmp
        return helper.append_activation(out)


class RowConv(Layer):
    """reference: dygraph/nn.py RowConv — lookahead conv over padded
    sequences [B, T, D]."""

    def __init__(self, name_scope=None, future_context_size=2,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._k = int(future_context_size)
        self._param_attr = param_attr
        self._act = act

    def forward(self, input, seq_len=None):
        from paddle_tpu.layer_helper import LayerHelper

        if not hasattr(self, "weight"):
            d = int(input.shape[-1])
            helper = LayerHelper(self._full_name, param_attr=self._param_attr)
            self.weight = helper.create_parameter(
                self._param_attr, shape=[self._k + 1, d], dtype=self._dtype)
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        ins = {"X": [input], "Filter": [self.weight]}
        if seq_len is not None:
            ins["SeqLen"] = [seq_len]
        helper.append_op(type="row_conv", inputs=ins,
                         outputs={"Out": [out]}, attrs={})
        return helper.append_activation(out)


class SequenceConv(Layer):
    """reference: dygraph/nn.py SequenceConv — context-window conv over
    padded sequences [B, T, D]."""

    def __init__(self, name_scope=None, num_filters=None, filter_size=3,
                 filter_stride=1, padding=None, bias_attr=None,
                 param_attr=None, act=None, dtype="float32"):
        super().__init__(name_scope, dtype)
        self._num_filters = num_filters
        self._filter_size = int(filter_size)
        self._param_attr = param_attr
        self._bias_attr = bias_attr
        self._act = act

    def forward(self, input, seq_len=None):
        from paddle_tpu.layer_helper import LayerHelper

        if not hasattr(self, "weight"):
            d = int(input.shape[-1])
            helper = LayerHelper(self._full_name, param_attr=self._param_attr,
                                 bias_attr=self._bias_attr)
            self.weight = helper.create_parameter(
                self._param_attr, shape=[self._filter_size * d, self._num_filters],
                dtype=self._dtype)
            self.bias = helper.create_parameter(
                self._bias_attr, shape=[self._num_filters], dtype=self._dtype,
                is_bias=True)
        helper = LayerHelper(self._full_name, act=self._act)
        out = helper.create_variable_for_type_inference(self._dtype)
        ins = {"X": [input], "Filter": [self.weight]}
        if seq_len is not None:
            ins["SeqLen"] = [seq_len]
        helper.append_op(
            type="sequence_conv", inputs=ins, outputs={"Out": [out]},
            attrs={"contextStart": -(self._filter_size // 2),
                   "contextLength": self._filter_size, "contextStride": 1})
        if self.bias is not None:
            tmp = helper.create_variable_for_type_inference(self._dtype)
            helper.append_op(type="elementwise_add",
                             inputs={"X": [out], "Y": [self.bias]},
                             outputs={"Out": [tmp]}, attrs={"axis": 2})
            out = tmp
        return helper.append_activation(out)
