"""Dygraph data parallel (reference: python/paddle/fluid/dygraph/
parallel.py:84 — DataParallel scales the loss and allreduces grads via
``_allreduce`` ops; imperative/nccl_context.cc TCP-bootstraps NCCL).

TPU eager DP: one trainer process per device/host, grads averaged with a
REAL cross-process allreduce.  The transport is the host collective on
the parameter-server (distributed/ps.py op "allreduce" — the TCP
rendezvous that replaces the reference's TCP-bootstrapped NCCL ring;
eager per-op device collectives are not the TPU-efficient path, compile
the step instead — parallel/hybrid.py).  Rank 0 hosts the collective
server on its trainer endpoint; everyone connects.
"""
from __future__ import annotations

import os
from typing import Optional

from paddle_tpu.dygraph.layers import Layer

__all__ = ["Env", "DataParallel", "prepare_context"]


class Env:
    def __init__(self):
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dev_id = int(os.getenv("FLAGS_selected_gpus", "0"))
        self._trainer_endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._dev_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


class ParallelContext:
    """Cross-process collective context (reference: NCCLParallelContext,
    imperative/nccl_context.cc — rank 0 creates the id and TCP-bcasts;
    here rank 0 hosts the collective server itself)."""

    def __init__(self, env: Env):
        self.env = env
        self._server = None
        self._client = None
        self._seq = 0
        if env.nranks > 1:
            from paddle_tpu.distributed.ps import ParameterServer, PSClient

            root = env.trainer_endpoints[0]
            if env.local_rank == 0:
                host, port = root.rsplit(":", 1)
                # collective port = trainer port + 2000 (trainer ports are
                # taken by the launch contract)
                self._server = ParameterServer("%s:%d" % (host, int(port) + 2000)).start()
            host, port = root.rsplit(":", 1)
            self._client = PSClient(["%s:%d" % (host, int(port) + 2000)])

    def allreduce(self, value, name: str = ""):
        """Blocking sum-allreduce across all ranks.  Keys carry the
        caller-provided name plus a per-context step so different params
        can never rendezvous with each other even if one rank skips."""
        import numpy as np

        if self._client is None:
            return value
        out = self._client._call(
            0,
            {"op": "allreduce", "key": "dygraph/%d/%s" % (self._seq, name),
             "nranks": self.env.nranks, "value": np.asarray(value, np.float32)},
        )["sum"]
        return out

    def next_step(self):
        self._seq += 1

    def close(self):
        if self._client is not None:
            self._client.close()
        if self._server is not None:
            self._server.stop()


_ctx: Optional[ParallelContext] = None


def prepare_context(strategy=None):
    """reference: dygraph/parallel.py prepare_context — boots the host
    collective (rank 0 serves) and returns the env descriptor."""
    global _ctx
    env = Env()
    if _ctx is None:
        _ctx = ParallelContext(env)
    return env


class DataParallel(Layer):
    def __init__(self, layers_, strategy=None):
        super().__init__("data_parallel")
        self._layers = layers_
        self._strategy = strategy or Env()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def nranks(self):
        return getattr(self._strategy, "nranks", 1)

    def scale_loss(self, loss):
        if self.nranks <= 1:
            return loss
        from paddle_tpu import layers as L

        return L.scale(loss, scale=1.0 / self.nranks)

    def apply_collective_grads(self):
        """Sum gradients across ranks via the host collective (with
        scale_loss dividing by nranks, the result is the average —
        reference: apply_collective_grads calling _allreduce per grad)."""
        if self.nranks <= 1:
            return
        if _ctx is None or _ctx._client is None:
            raise RuntimeError(
                "call fluid.dygraph.parallel.prepare_context() before "
                "apply_collective_grads in multi-rank mode"
            )
        import jax.numpy as jnp
        import numpy as np

        _ctx.next_step()
        for p in self.parameters():
            g = getattr(p, "_dy_grad", None)
            if g is None:
                # every rank must post every param or the rendezvous
                # starves — a rank where the param was unused sends zeros
                # (reference: allreduce of zero grads)
                g = jnp.zeros(tuple(p.shape), "float32")
            dtype = getattr(g, "dtype", np.float32)
            summed = _ctx.allreduce(np.asarray(g, np.float32), name=p.name)
            p._dy_grad = jnp.asarray(summed).astype(dtype)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, prefix=""):
        return self._layers.state_dict(prefix)

    def set_dict(self, d):
        return self._layers.set_dict(d)

    def clear_gradients(self):
        self._layers.clear_gradients()
