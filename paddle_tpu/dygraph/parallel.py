"""Dygraph data parallel (reference: python/paddle/fluid/dygraph/
parallel.py:84 — DataParallel scales the loss and allreduces grads via
``_allreduce`` ops; imperative/nccl_context.cc TCP-bootstraps NCCL).

TPU eager DP runs one process per host with the jax runtime handling the
mesh; eager per-op collectives are not the TPU-efficient path (compile
the step instead — parallel/hybrid.py), so this class keeps the API:
loss scaling + grad averaging across ``Env.nranks`` (1 in-process)."""
from __future__ import annotations

import os

from paddle_tpu.dygraph.layers import Layer

__all__ = ["Env", "DataParallel", "prepare_context"]


class Env:
    def __init__(self):
        self._nranks = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
        self._local_rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self._dev_id = int(os.getenv("FLAGS_selected_gpus", "0"))
        self._trainer_endpoints = os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",")
        self._current_endpoint = os.getenv("PADDLE_CURRENT_ENDPOINT", "")

    @property
    def nranks(self):
        return self._nranks

    @property
    def local_rank(self):
        return self._local_rank

    @property
    def dev_id(self):
        return self._dev_id

    @property
    def current_endpoint(self):
        return self._current_endpoint

    @property
    def trainer_endpoints(self):
        return self._trainer_endpoints


def prepare_context(strategy=None):
    """reference: dygraph/parallel.py prepare_context — jax.distributed
    owns process-group bootstrap on TPU; returns the env descriptor."""
    return Env()


class DataParallel(Layer):
    def __init__(self, layers_, strategy=None):
        super().__init__("data_parallel")
        self._layers = layers_
        self._strategy = strategy or Env()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def nranks(self):
        return getattr(self._strategy, "nranks", 1)

    def scale_loss(self, loss):
        if self.nranks <= 1:
            return loss
        from paddle_tpu import layers as L

        return L.scale(loss, scale=1.0 / self.nranks)

    def apply_collective_grads(self):
        """Average gradients across ranks (psum/nranks). In-process
        single-rank eager mode this is the identity; the multi-rank path
        is the compiled hybrid engine."""
        if self.nranks <= 1:
            return

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def state_dict(self, prefix=""):
        return self._layers.state_dict(prefix)

    def set_dict(self, d):
        return self._layers.set_dict(d)

    def clear_gradients(self):
        self._layers.clear_gradients()
