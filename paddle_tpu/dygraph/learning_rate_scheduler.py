"""Dygraph learning-rate decay objects (reference: python/paddle/fluid/
dygraph/learning_rate_scheduler.py — NoamDecay, PiecewiseDecay, ...).

Each object is passed as ``learning_rate=`` to an optimizer; the
optimizer calls it once per minimize() and the schedule advances
(reference: optimizer calls LearningRateDecay.__call__ which steps)."""
from __future__ import annotations

import math

__all__ = [
    "LearningRateDecay", "PiecewiseDecay", "NaturalExpDecay",
    "ExponentialDecay", "InverseTimeDecay", "PolynomialDecay",
    "CosineDecay", "NoamDecay",
]


class LearningRateDecay:
    def __init__(self, begin=0, step=1):
        self.step_num = int(begin)
        self.step_size = int(step)

    def __call__(self):
        lr = self.step()
        self.step_num += self.step_size
        return float(lr)

    def __float__(self):
        return float(self.step())

    def step(self):
        raise NotImplementedError


class PiecewiseDecay(LearningRateDecay):
    """reference: dygraph/learning_rate_scheduler.py PiecewiseDecay."""

    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(begin, step)
        self.boundaries = [int(b) for b in boundaries]
        self.values = [float(v) for v in values]

    def step(self):
        for i, b in enumerate(self.boundaries):
            if self.step_num < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.base = float(learning_rate)
        self.decay_steps = int(decay_steps)
        self.decay_rate = float(decay_rate)
        self.staircase = staircase

    def step(self):
        p = self.step_num / self.decay_steps
        if self.staircase:
            p = math.floor(p)
        return self.base * math.exp(-self.decay_rate * p)


class ExponentialDecay(NaturalExpDecay):
    def step(self):
        p = self.step_num / self.decay_steps
        if self.staircase:
            p = math.floor(p)
        return self.base * (self.decay_rate ** p)


class InverseTimeDecay(NaturalExpDecay):
    def step(self):
        p = self.step_num / self.decay_steps
        if self.staircase:
            p = math.floor(p)
        return self.base / (1.0 + self.decay_rate * p)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(begin, step)
        self.base = float(learning_rate)
        self.decay_steps = int(decay_steps)
        self.end_lr = float(end_learning_rate)
        self.power = float(power)
        self.cycle = cycle

    def step(self):
        n = self.step_num
        d = self.decay_steps
        if self.cycle:
            mult = max(1.0, math.ceil(n / d)) if n else 1.0
            d = d * mult
        else:
            n = min(n, d)
        return (self.base - self.end_lr) * (1 - n / d) ** self.power + self.end_lr


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0, step=1):
        super().__init__(begin, step)
        self.base = float(learning_rate)
        self.step_each_epoch = int(step_each_epoch)
        self.epochs = int(epochs)

    def step(self):
        epoch = self.step_num // self.step_each_epoch
        return self.base * (math.cos(epoch * math.pi / self.epochs) + 1) / 2


class NoamDecay(LearningRateDecay):
    def __init__(self, d_model, warmup_steps, begin=1, step=1):
        super().__init__(begin, step)
        self.d_model = float(d_model)
        self.warmup_steps = float(warmup_steps)

    def step(self):
        n = max(self.step_num, 1)
        return self.d_model ** -0.5 * min(n ** -0.5,
                                          n * self.warmup_steps ** -1.5)
