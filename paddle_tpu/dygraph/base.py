"""Dygraph tracer + guard + to_variable.

Reference: imperative/tracer.cc:140 (Trace: run kernel immediately, record
grad descs), dygraph/base.py:98 (guard), :156 (to_variable).
"""
from __future__ import annotations

import contextlib
from typing import Any, Dict, List, Optional

import numpy as np

from paddle_tpu import framework
from paddle_tpu.core import registry
from paddle_tpu.core import types as core_types

__all__ = ["guard", "enabled", "to_variable", "Tracer"]


class TapeEntry:
    __slots__ = ("op_type", "attrs", "inputs", "outputs")

    def __init__(self, op_type, attrs, inputs, outputs):
        self.op_type = op_type
        self.attrs = attrs
        self.inputs = inputs    # slot -> [Variable]
        self.outputs = outputs  # slot -> [Variable]


def _val(var):
    v = getattr(var, "_dy_value", None)
    if v is None:
        raise RuntimeError(
            "dygraph: variable %r has no value (did it come from a static "
            "graph build?)" % getattr(var, "name", var)
        )
    return v


class BackwardStrategy:
    """reference: dygraph/backward_strategy.py (core.BackwardStrategy).

    ``sort_sum_gradient`` makes the reference's grad accumulation order
    deterministic; this build's tape replay accumulates in fixed reverse
    trace order, so execution is ALWAYS deterministic — the flag is
    accepted for API parity and recorded on the instance."""

    def __init__(self):
        self.sort_sum_gradient = False


class Tracer:
    """Eager executor + tape (reference: imperative/tracer.h:41)."""

    def __init__(self):
        self.tape: List[TapeEntry] = []
        self._no_grad = False

    # called from Block.append_op when in dygraph mode
    def trace_op(self, op_type, inputs, outputs, attrs, block=None):
        kernel = registry.get_kernel(op_type)
        attrs = dict(attrs or {})

        def resolve(v):
            if isinstance(v, str):
                if block is None:
                    raise RuntimeError("dygraph trace_op got name %r without a block" % v)
                return block.var(v)
            return v

        in_vars: Dict[str, List[Any]] = {}
        kin: Dict[str, List[Any]] = {}
        for slot, vs in (inputs or {}).items():
            if vs is None:
                continue
            vs = vs if isinstance(vs, (list, tuple)) else [vs]
            vs = [resolve(v) for v in vs if v is not None]
            if not vs:
                continue
            in_vars[slot] = list(vs)
            kin[slot] = [_val(v) for v in vs]
        outs = kernel(kin, attrs)
        outs = {k: (v if isinstance(v, (list, tuple)) else [v]) for k, v in (outs or {}).items()}
        out_vars: Dict[str, List[Any]] = {}
        for slot, names in (outputs or {}).items():
            vs = names if isinstance(names, (list, tuple)) else [names]
            vs = [resolve(v) if v is not None else None for v in vs]
            vals = outs.get(slot)
            if vals is None:
                continue
            kept = []
            for var, val in zip(vs, vals):
                if var is None or val is None:
                    continue
                var._dy_value = val
                var.shape = tuple(np.shape(val))
                kept.append(var)
            if kept:
                out_vars[slot] = kept
        if not self._no_grad:
            try:
                differentiable = registry.get_op(op_type).differentiable
            except KeyError:
                differentiable = False
            if differentiable:
                self.tape.append(TapeEntry(op_type, attrs, in_vars, out_vars))
        # return the op-like record (callers mostly ignore it)
        flat = [v for vs in out_vars.values() for v in vs]
        return flat[0] if len(flat) == 1 else None

    # ------------------------------------------------------------------
    def run_backward(self, loss):
        """Reverse tape walk (reference: VarBase::RunBackward layer.cc:377)."""
        import jax.numpy as jnp

        grads: Dict[int, Any] = {id(loss): jnp.ones(np.shape(_val(loss)), _val(loss).dtype)}
        var_by_id = {id(loss): loss}
        for entry in reversed(self.tape):
            out_grad_lists = {}
            any_grad = False
            for slot, vs in entry.outputs.items():
                gs = []
                for v in vs:
                    g = grads.get(id(v))
                    gs.append(g)
                    if g is not None:
                        any_grad = True
                out_grad_lists[slot] = gs
            if not any_grad:
                continue
            gkernel = registry.get_kernel(entry.op_type + "_grad")
            gin: Dict[str, List[Any]] = {}
            for slot, vs in entry.inputs.items():
                gin[slot] = [_val(v) for v in vs]
            fwd_out_slots = tuple(entry.outputs.keys())
            for slot, vs in entry.outputs.items():
                gin[slot] = [_val(v) for v in vs]
            mask = {}
            for slot, gs in out_grad_lists.items():
                if any(g is not None for g in gs):
                    gin[slot + "@GRAD"] = [g for g in gs if g is not None]
                    if any(g is None for g in gs):
                        mask[slot] = [g is None for g in gs]
            want = [
                s
                for s, vs in entry.inputs.items()
                if s not in registry.get_op(entry.op_type).no_grad_set
                and all(core_types.is_float_dtype(str(np.asarray(_val(v)).dtype)) or "float" in str(_val(v).dtype) for v in vs)
            ]
            gattrs = dict(entry.attrs)
            gattrs["__fwd_output_slots__"] = fwd_out_slots
            gattrs["__grad_input_slots__"] = tuple(want)
            if mask:
                gattrs["__empty_out_grad_mask__"] = mask
            gout = gkernel(gin, gattrs)
            for slot, vs in entry.inputs.items():
                gs = gout.get(slot + "@GRAD")
                if gs is None:
                    continue
                if not isinstance(gs, (list, tuple)):
                    gs = [gs]
                for v, g in zip(vs, gs):
                    if g is None or getattr(v, "stop_gradient", False):
                        continue
                    prev = grads.get(id(v))
                    grads[id(v)] = g if prev is None else prev + g
                    var_by_id[id(v)] = v
        # attach grads to variables
        for vid, g in grads.items():
            var_by_id[vid]._dy_grad = g

    def reset(self):
        self.tape.clear()


@contextlib.contextmanager
def no_grad():
    tr = framework._dygraph_tracer()
    if tr is None:
        yield
        return
    prev = tr._no_grad
    tr._no_grad = True
    try:
        yield
    finally:
        tr._no_grad = prev


def enabled() -> bool:
    return framework.in_dygraph_mode()


@contextlib.contextmanager
def guard(place=None):
    """reference: dygraph/base.py:98."""
    tracer = Tracer()
    with framework._dygraph_guard(tracer):
        yield


def to_variable(value, name: Optional[str] = None, block=None):
    """reference: dygraph/base.py:156 — ndarray -> eager Variable."""
    import jax.numpy as jnp

    if isinstance(value, framework.Variable):
        return value
    arr = np.asarray(value)
    dtype = core_types.canonical_dtype(str(arr.dtype))
    block = block or framework.default_main_program().current_block()
    var = framework.Variable(
        block,
        name or framework.unique_name.generate("generated_var"),
        shape=arr.shape,
        dtype=dtype,
        stop_gradient=True,
    )
    var._dy_value = jnp.asarray(arr)
    return var
