"""Dygraph (eager) mode.

Reference: paddle/fluid/imperative/ (Tracer tracer.cc:140, VarBase/OpBase
layer.h:133,334) + python/paddle/fluid/dygraph/.  TPU-native design: each
traced op runs its JAX kernel immediately (per-op dispatch, jit-cached by
XLA at the op level), a tape records (op, inputs, outputs) and
``loss.backward()`` replays it in reverse through the same generic vjp
grad kernels the static graph uses — one autodiff implementation for
both modes.
"""
from paddle_tpu.dygraph import nn  # noqa: F401
from paddle_tpu.dygraph.base import (  # noqa: F401
    BackwardStrategy,
    Tracer,
    enabled,
    guard,
    no_grad,
    to_variable,
)
from paddle_tpu.dygraph import learning_rate_scheduler  # noqa: F401
from paddle_tpu.dygraph.learning_rate_scheduler import (  # noqa: F401
    CosineDecay,
    ExponentialDecay,
    InverseTimeDecay,
    LearningRateDecay,
    NaturalExpDecay,
    NoamDecay,
    PiecewiseDecay,
    PolynomialDecay,
)
from paddle_tpu.dygraph.layers import Layer  # noqa: F401
from paddle_tpu.dygraph.nn import (  # noqa: F401
    BatchNorm,
    BilinearTensorProduct,
    Conv2D,
    Conv2DTranspose,
    Conv3D,
    Conv3DTranspose,
    TreeConv,
    Embedding,
    FC,
    GroupNorm,
    GRUUnit,
    LayerNorm,
    Linear,
    NCE,
    Pool2D,
    PRelu,
    RowConv,
    SequenceConv,
    SpectralNorm,
)
from paddle_tpu.dygraph.parallel import DataParallel, prepare_context  # noqa: F401
from paddle_tpu.dygraph.checkpoint import (  # noqa: F401
    load_dygraph,
    load_persistables,
    save_dygraph,
    save_persistables,
)


def start_gperf_profiler():
    """reference: dygraph/profiler.py start_gperf_profiler — maps to a
    jax.profiler trace (gperftools is CPU-host-only; the TPU story is
    the xplane trace)."""
    import jax

    jax.profiler.start_trace("/tmp/paddle_tpu_gperf")


def stop_gperf_profiler():
    import jax

    jax.profiler.stop_trace()
