"""Dygraph (eager) mode.

Reference: paddle/fluid/imperative/ (Tracer tracer.cc:140, VarBase/OpBase
layer.h:133,334) + python/paddle/fluid/dygraph/.  TPU-native design: each
traced op runs its JAX kernel immediately (per-op dispatch, jit-cached by
XLA at the op level), a tape records (op, inputs, outputs) and
``loss.backward()`` replays it in reverse through the same generic vjp
grad kernels the static graph uses — one autodiff implementation for
both modes.
"""
from paddle_tpu.dygraph import nn  # noqa: F401
from paddle_tpu.dygraph.base import guard, enabled, no_grad, to_variable  # noqa: F401
from paddle_tpu.dygraph.layers import Layer  # noqa: F401
from paddle_tpu.dygraph.nn import (  # noqa: F401
    BatchNorm,
    BilinearTensorProduct,
    Conv2D,
    Conv2DTranspose,
    Embedding,
    FC,
    GroupNorm,
    GRUUnit,
    LayerNorm,
    Linear,
    NCE,
    Pool2D,
    PRelu,
    SpectralNorm,
)
from paddle_tpu.dygraph.parallel import DataParallel, prepare_context  # noqa: F401
from paddle_tpu.dygraph.checkpoint import load_dygraph, save_dygraph  # noqa: F401
