"""Gradient clipping (reference: python/paddle/fluid/clip.py —
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm,
ErrorClipByValue, set_gradient_clip)."""
from __future__ import annotations

from typing import List, Optional, Tuple

from paddle_tpu import framework

__all__ = [
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "ErrorClipByValue",
    "set_gradient_clip",
    "append_gradient_clip_ops",
]

_global_clip = None


class BaseGradientClipAttr:
    def _create_operators(self, param, grad):
        raise NotImplementedError

    def _process_context(self, context, param, grad):
        pass


class ErrorClipByValue:
    def __init__(self, max, min=None):
        self.max = max
        self.min = min if min is not None else -max


class GradientClipByValue(BaseGradientClipAttr):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _create_operators(self, param, grad):
        from paddle_tpu.layers import nn

        return param, nn.clip(grad, self.min, self.max)


class GradientClipByNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _create_operators(self, param, grad):
        from paddle_tpu.layers import nn

        return param, nn.clip_by_norm(grad, self.clip_norm)


class GradientClipByGlobalNorm(BaseGradientClipAttr):
    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def _process_context(self, context, param, grad):
        ctx = context.setdefault(self.group_name, [])
        ctx.append((param, grad))

    @staticmethod
    def _apply_group(pairs, clip_norm):
        from paddle_tpu.layers import ops as lops
        from paddle_tpu.layers import tensor as ltensor

        sq_sums = []
        for _, g in pairs:
            sq = lops.square(g)
            sq_sums.append(ltensor.reduce_sum(sq))
        global_norm = lops.sqrt(ltensor.sums(sq_sums))
        clip_var = ltensor.fill_constant([1], "float32", clip_norm)
        scale = ltensor.elementwise_div(clip_var, ltensor.elementwise_max(global_norm, clip_var))
        out = []
        for p, g in pairs:
            out.append((p, ltensor.elementwise_mul(g, scale)))
        return out


def set_gradient_clip(clip, param_list=None, program=None):
    global _global_clip
    _global_clip = clip
    if param_list:
        for p in param_list:
            if isinstance(p, str):
                p = framework.default_main_program().global_block().var(p)
            p.gradient_clip_attr = clip


def append_gradient_clip_ops(params_grads) -> List[Tuple]:
    """reference: clip.py append_gradient_clip_ops."""
    clips = {}
    has_clip = False
    for p, g in params_grads:
        c = getattr(p, "gradient_clip_attr", None) or _global_clip
        if c is not None:
            has_clip = True
        clips[p.name] = c
    if not has_clip:
        return params_grads

    # global-norm groups first
    context = {}
    simple = []
    for p, g in params_grads:
        c = clips[p.name]
        if isinstance(c, GradientClipByGlobalNorm) and g is not None:
            c._process_context(context, p, g)
        else:
            simple.append((p, g, c))
    out = []
    for group_name, pairs in context.items():
        clip_norm = None
        for p, _ in pairs:
            c = clips[p.name]
            clip_norm = c.clip_norm
        out.extend(GradientClipByGlobalNorm._apply_group(pairs, clip_norm))
    for p, g, c in simple:
        if g is None or c is None:
            out.append((p, g))
        else:
            out.append(c._create_operators(p, g))
    return out
