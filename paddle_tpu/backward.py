"""Static autodiff: append_backward.

Reference: python/paddle/fluid/backward.py:558 — walks forward ops in
reverse, asks each op's C++ grad-maker for grad op descs
(core.get_grad_op_desc), renames and sums duplicated gradient
contributions (_addup_repetitive_outputs_:135), prunes branches that do
not need grad (:211).

TPU-native twist: the default grad "maker" emits a single ``<type>_grad``
op whose kernel is derived from the forward kernel via ``jax.vjp``
(core/registry.py make_vjp_grad_kernel) — per-op hand-written grad kernels
(the reference's *_grad CUDA kernels) are unnecessary because XLA
differentiates and fuses the recomputation.  Custom grad makers can still
be registered per-op for ops whose grads need special structure.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from paddle_tpu import framework
from paddle_tpu.core import registry
from paddle_tpu.core import types as core_types
from paddle_tpu.framework import Operator, Parameter, Variable, grad_var_name

__all__ = ["append_backward", "gradients"]


def _is_float_var(v: Variable) -> bool:
    return core_types.is_float_dtype(v.dtype)


def _requires_grad_vars(block, extra_no_grad: Set[str]) -> Set[str]:
    """Forward sweep: which vars can carry gradient back to a trainable leaf."""
    req: Set[str] = set()
    for v in block.vars.values():
        if v.name in extra_no_grad:
            continue
        if isinstance(v, Parameter) and v.trainable:
            req.add(v.name)
        elif not v.stop_gradient and v.op is None and _is_float_var(v):
            # explicitly created leaf (incl. data vars with stop_gradient=False)
            req.add(v.name)
    for op in block.ops:
        try:
            opdef = registry.get_op(op.type)
        except KeyError:
            continue
        if not opdef.differentiable:
            continue
        feeds_grad = False
        for slot, names in op.inputs.items():
            if slot in opdef.no_grad_set:
                continue
            if any(n in req for n in names):
                feeds_grad = True
                break
        if feeds_grad:
            for names in op.outputs.values():
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is not None and _is_float_var(v) and n not in extra_no_grad:
                        req.add(n)
    return req


def _make_grad_op_descs(op: Operator, opdef, out_grad_names: Dict[str, str], req: Set[str]):
    """Build the generic vjp grad-op desc for ``op``.

    ``out_grad_names``: forward output var name -> its (aggregated) grad var.
    Returns (inputs, outputs, attrs, grad_in_to_fwd_in) for one grad op.
    """
    g_inputs: Dict[str, List[str]] = {}
    for slot, names in op.inputs.items():
        g_inputs[slot] = list(names)
    grad_out_slots = []
    empty_mask = {}
    for slot, names in op.outputs.items():
        gnames = [out_grad_names.get(n) for n in names]
        if any(g is not None for g in gnames):
            g_inputs[slot + "@GRAD"] = [g if g is not None else registry.EMPTY_VAR_NAME for g in gnames]
            grad_out_slots.append(slot)
            if any(g is None for g in gnames):
                empty_mask[slot] = [g is None for g in gnames]
    g_outputs: Dict[str, List[str]] = {}
    want_slots = []
    for slot, names in op.inputs.items():
        if slot in opdef.no_grad_set:
            continue
        outs = []
        any_real = False
        for n in names:
            v = op.block._find_var_recursive(n)
            if v is not None and n in req and _is_float_var(v):
                outs.append(n)  # placeholder; caller renames to grad var
                any_real = True
            else:
                outs.append(None)
        if any_real:
            g_outputs[slot + "@GRAD"] = outs
            want_slots.append(slot)
    attrs = dict(op.attrs)
    attrs["__fwd_output_slots__"] = tuple(op.outputs.keys())
    attrs["__grad_input_slots__"] = tuple(want_slots)
    if empty_mask:
        # positions whose upstream grad is absent (EMPTY_VAR_NAME inputs
        # are dropped at trace time; the vjp kernel re-inserts zeros here)
        attrs["__empty_out_grad_mask__"] = empty_mask
    attrs["op_role"] = "backward"
    return g_inputs, g_outputs, attrs


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
):
    """Append grad ops for ``loss`` to its program; return [(param, grad)].

    Matches the reference contract (backward.py:558): loss must be a scalar
    (or shape-[1]) var in the main program's global block.
    """
    block = loss.block
    program = block.program
    extra_no_grad = set(no_grad_set or ())
    for v in program.list_vars():
        if v.stop_gradient and not isinstance(v, Parameter):
            extra_no_grad.add(v.name)
        if isinstance(v, Parameter) and not v.trainable:
            extra_no_grad.add(v.name)
    extra_no_grad.discard(loss.name)

    req = _requires_grad_vars(block, extra_no_grad - {loss.name})
    if loss.name not in req:
        raise ValueError(
            "loss %r does not depend on any trainable parameter" % loss.name
        )

    # locate the op producing the loss
    loss_op_idx = None
    for i in reversed(range(len(block.ops))):
        if loss.name in block.ops[i].output_arg_names:
            loss_op_idx = i
            break
    if loss_op_idx is None:
        raise ValueError("loss %r is not produced by any op" % loss.name)

    # init d(loss)/d(loss) = 1
    loss_grad = grad_var_name(loss.name)
    block.create_var(
        name=loss_grad, shape=loss.shape or (1,), dtype=loss.dtype, stop_gradient=True
    )
    block.append_op(
        type="fill_constant",
        outputs={"Out": [loss_grad]},
        attrs={
            "shape": list(loss.shape or (1,)),
            "value": 1.0,
            "dtype": loss.dtype,
            "op_role": "backward",
        },
    )

    # reverse walk, accumulating grad contributions per forward var
    contributions: Dict[str, List[str]] = {loss.name: [loss_grad]}
    finalized: Dict[str, str] = {}

    def aggregate(name: str) -> Optional[str]:
        """Sum multiple grad contributions (reference backward.py:135)."""
        if name in finalized:
            return finalized[name]
        contribs = contributions.get(name)
        if not contribs:
            return None
        if len(contribs) == 1:
            finalized[name] = contribs[0]
            return contribs[0]
        gname = grad_var_name(name)
        if gname in (c for c in contribs):
            gname = gname + "@SUM"
        fv = block._find_var_recursive(name)
        block.create_var(name=gname, shape=fv.shape if fv else None, dtype=fv.dtype if fv else "float32", stop_gradient=True)
        block.append_op(
            type="sum",
            inputs={"X": contribs},
            outputs={"Out": [gname]},
            attrs={"op_role": "backward"},
        )
        finalized[name] = gname
        return gname

    def add_contribution(fwd_name: str, grad_name: str):
        contributions.setdefault(fwd_name, []).append(grad_name)

    fwd_ops = list(block.ops[: loss_op_idx + 1])
    for op in reversed(fwd_ops):
        try:
            opdef = registry.get_op(op.type)
        except KeyError:
            continue
        if not opdef.differentiable:
            continue
        # does any output carry grad?
        out_has_grad = any(n in contributions for n in op.output_arg_names)
        if not out_has_grad:
            continue
        in_needs_grad = any(
            n in req and n not in extra_no_grad
            for slot, names in op.inputs.items()
            if slot not in opdef.no_grad_set
            for n in names
        )
        if not in_needs_grad:
            continue

        out_grad_names = {}
        for n in op.output_arg_names:
            g = aggregate(n)
            if g is not None:
                out_grad_names[n] = g

        if opdef.grad_maker is not None:
            descs = opdef.grad_maker(op, block, out_grad_names, req - extra_no_grad)
            for d in descs:
                block.append_op(**d)
                for slot, names in d.get("outputs", {}).items():
                    if not slot.endswith("@GRAD"):
                        continue
            # custom makers register contributions themselves via convention:
            # each output named grad_var_name(x)+suffix maps back by stripping
            for d in descs:
                for slot, names in d.get("outputs", {}).items():
                    if not slot.endswith("@GRAD"):
                        continue
                    for gn in names:
                        if gn and gn != registry.EMPTY_VAR_NAME:
                            base = gn.split("@GRAD")[0]
                            add_contribution(base, gn)
            continue

        g_inputs, g_outputs, g_attrs = _make_grad_op_descs(op, opdef, out_grad_names, req - extra_no_grad)
        if not g_outputs:
            continue
        # name grad outputs uniquely, register contributions
        final_outputs: Dict[str, List[str]] = {}
        for slot, names in g_outputs.items():
            outs = []
            for fwd_name in names:
                if fwd_name is None:
                    outs.append(registry.EMPTY_VAR_NAME)
                    continue
                base = grad_var_name(fwd_name)
                k = len(contributions.get(fwd_name, []))
                gname = base if k == 0 else "%s@RENAME@%d" % (base, k)
                fv = block._find_var_recursive(fwd_name)
                block.create_var(
                    name=gname,
                    shape=fv.shape if fv else None,
                    dtype=fv.dtype if fv else "float32",
                    stop_gradient=True,
                )
                add_contribution(fwd_name, gname)
                outs.append(gname)
            final_outputs[slot] = outs
        block.append_op(type=op.type + "_grad", inputs=g_inputs, outputs=final_outputs, attrs=g_attrs)

    # collect (param, grad) pairs
    if parameter_list is not None:
        params = [
            block._find_var_recursive(p) if isinstance(p, str) else p
            for p in parameter_list
        ]
    else:
        params = [p for p in program.global_block().all_parameters() if p.trainable]
    result = []
    for p in params:
        if p is None or p.name in extra_no_grad:
            continue
        g = aggregate(p.name)
        if g is None:
            continue
        gvar = block._find_var_recursive(g)
        result.append((p, gvar))
    program.version += 1
    return result


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: backward.py:939 — d(targets)/d(inputs)."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if len(targets) != 1:
        raise NotImplementedError("gradients() currently supports one target")
    pg = append_backward(targets[0], no_grad_set=no_grad_set, parameter_list=None)
    block = targets[0].block
    out = []
    for iv in inputs:
        g = block._find_var_recursive(grad_var_name(iv.name))
        out.append(g)
    return out
