"""Static autodiff: append_backward.

Reference: python/paddle/fluid/backward.py:558 — walks forward ops in
reverse, asks each op's C++ grad-maker for grad op descs
(core.get_grad_op_desc), renames and sums duplicated gradient
contributions (_addup_repetitive_outputs_:135), prunes branches that do
not need grad (:211).

TPU-native twist: the default grad "maker" emits a single ``<type>_grad``
op whose kernel is derived from the forward kernel via ``jax.vjp``
(core/registry.py make_vjp_grad_kernel) — per-op hand-written grad kernels
(the reference's *_grad CUDA kernels) are unnecessary because XLA
differentiates and fuses the recomputation.  Custom grad makers can still
be registered per-op for ops whose grads need special structure.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from paddle_tpu import framework
from paddle_tpu.core import registry
from paddle_tpu.core import types as core_types
from paddle_tpu.framework import Operator, Parameter, Variable, grad_var_name

__all__ = ["append_backward", "gradients"]


def _is_float_var(v: Variable) -> bool:
    return core_types.is_float_dtype(v.dtype)


def _requires_grad_vars(block, extra_no_grad: Set[str], extra_leaves: Set[str] = frozenset()) -> Set[str]:
    """Forward sweep: which vars can carry gradient back to a trainable leaf.

    ``extra_leaves``: var names treated as grad-carrying leaves regardless of
    their stop_gradient flag (gradients()' ``inputs``, reference
    backward.py:939 calc_gradient marks them the same way).
    """
    req: Set[str] = set(extra_leaves)
    for v in block.vars.values():
        if v.name in extra_no_grad:
            continue
        if isinstance(v, Parameter) and v.trainable:
            req.add(v.name)
        elif not v.stop_gradient and v.op is None and _is_float_var(v):
            # explicitly created leaf (incl. data vars with stop_gradient=False)
            req.add(v.name)
    for op in block.ops:
        try:
            opdef = registry.get_op(op.type)
        except KeyError:
            continue
        if not opdef.differentiable:
            continue
        feeds_grad = False
        for slot, names in op.inputs.items():
            if slot in opdef.no_grad_set:
                continue
            if any(n in req for n in names):
                feeds_grad = True
                break
        if feeds_grad:
            for names in op.outputs.values():
                for n in names:
                    v = block._find_var_recursive(n)
                    if v is not None and _is_float_var(v) and n not in extra_no_grad:
                        req.add(n)
    return req


def _make_grad_op_descs(op: Operator, opdef, out_grad_names: Dict[str, str], req: Set[str]):
    """Build the generic vjp grad-op desc for ``op``.

    ``out_grad_names``: forward output var name -> its (aggregated) grad var.
    Returns (inputs, outputs, attrs, grad_in_to_fwd_in) for one grad op.
    """
    g_inputs: Dict[str, List[str]] = {}
    for slot, names in op.inputs.items():
        g_inputs[slot] = list(names)
    grad_out_slots = []
    empty_mask = {}
    for slot, names in op.outputs.items():
        gnames = [out_grad_names.get(n) for n in names]
        if any(g is not None for g in gnames):
            g_inputs[slot + "@GRAD"] = [g if g is not None else registry.EMPTY_VAR_NAME for g in gnames]
            grad_out_slots.append(slot)
            if any(g is None for g in gnames):
                empty_mask[slot] = [g is None for g in gnames]
    g_outputs: Dict[str, List[str]] = {}
    want_slots = []
    for slot, names in op.inputs.items():
        if slot in opdef.no_grad_set:
            continue
        outs = []
        any_real = False
        for n in names:
            v = op.block._find_var_recursive(n)
            if v is not None and n in req and _is_float_var(v):
                outs.append(n)  # placeholder; caller renames to grad var
                any_real = True
            else:
                outs.append(None)
        if any_real:
            g_outputs[slot + "@GRAD"] = outs
            want_slots.append(slot)
    attrs = dict(op.attrs)
    attrs["__fwd_output_slots__"] = tuple(op.outputs.keys())
    attrs["__grad_input_slots__"] = tuple(want_slots)
    if empty_mask:
        # positions whose upstream grad is absent (EMPTY_VAR_NAME inputs
        # are dropped at trace time; the vjp kernel re-inserts zeros here)
        attrs["__empty_out_grad_mask__"] = empty_mask
    attrs["op_role"] = "backward"
    return g_inputs, g_outputs, attrs


def append_backward(
    loss: Variable,
    parameter_list: Optional[Sequence] = None,
    no_grad_set: Optional[Set[str]] = None,
    callbacks=None,
):
    """Append grad ops for ``loss`` to its program; return [(param, grad)].

    Matches the reference contract (backward.py:558): loss must be a scalar
    (or shape-[1]) var in the main program's global block.
    """
    result, _ = _append_backward_impl([loss], [None], parameter_list, no_grad_set)
    return result


def _append_backward_impl(
    targets: Sequence[Variable],
    target_gradients: Sequence[Optional[Variable]],
    parameter_list: Optional[Sequence],
    no_grad_set: Optional[Set[str]],
    extra_leaves: Set[str] = frozenset(),
):
    """Shared core of append_backward (single scalar loss) and gradients()
    (multi-target calc_gradient, reference backward.py:821,939): seed each
    target's output-grad (ones, or the caller's target_gradients var), walk
    the block's ops once in reverse accumulating contributions, and return
    [(param, grad)] for the trainable parameters.
    """
    block = targets[0].block
    program = block.program
    for t in targets[1:]:
        if t.block is not block:
            raise ValueError("all gradient targets must live in one block")
    target_names = {t.name for t in targets}
    extra_no_grad = set(no_grad_set or ())
    for v in program.list_vars():
        if v.stop_gradient and not isinstance(v, Parameter):
            extra_no_grad.add(v.name)
        if isinstance(v, Parameter) and not v.trainable:
            extra_no_grad.add(v.name)
    extra_no_grad -= target_names
    extra_no_grad -= set(extra_leaves)

    req = _requires_grad_vars(block, extra_no_grad - target_names, extra_leaves)
    for t in targets:
        if t.name not in req:
            raise ValueError(
                "target %r does not depend on any trainable parameter or "
                "requested input" % t.name
            )

    # locate the last op producing any target
    loss_op_idx = None
    for i in reversed(range(len(block.ops))):
        if target_names & set(block.ops[i].output_arg_names):
            loss_op_idx = i
            break
    if loss_op_idx is None:
        raise ValueError("no gradient target is produced by any op")

    # seed d(target)/d(target): ones, or the caller-provided grad var
    contributions: Dict[str, List[str]] = {}
    for t, tg in zip(targets, target_gradients):
        if tg is not None:
            if tuple(tg.shape or ()) != tuple(t.shape or ()):
                raise ValueError(
                    "target_gradient %r shape %s != target %r shape %s"
                    % (tg.name, tg.shape, t.name, t.shape)
                )
            contributions.setdefault(t.name, []).append(tg.name)
            continue
        loss_grad = grad_var_name(t.name)
        block.create_var(
            name=loss_grad, shape=t.shape or (1,), dtype=t.dtype, stop_gradient=True
        )
        block.append_op(
            type="fill_constant",
            outputs={"Out": [loss_grad]},
            attrs={
                "shape": list(t.shape or (1,)),
                "value": 1.0,
                "dtype": t.dtype,
                "op_role": "backward",
            },
        )
        contributions.setdefault(t.name, []).append(loss_grad)

    # reverse walk, accumulating grad contributions per forward var
    finalized: Dict[str, str] = {}

    def aggregate(name: str) -> Optional[str]:
        """Sum multiple grad contributions (reference backward.py:135)."""
        if name in finalized:
            return finalized[name]
        contribs = contributions.get(name)
        if not contribs:
            return None
        if len(contribs) == 1:
            finalized[name] = contribs[0]
            return contribs[0]
        gname = grad_var_name(name)
        if gname in (c for c in contribs):
            gname = gname + "@SUM"
        fv = block._find_var_recursive(name)
        block.create_var(name=gname, shape=fv.shape if fv else None, dtype=fv.dtype if fv else "float32", stop_gradient=True)
        block.append_op(
            type="sum",
            inputs={"X": contribs},
            outputs={"Out": [gname]},
            attrs={"op_role": "backward"},
        )
        finalized[name] = gname
        return gname

    def add_contribution(fwd_name: str, grad_name: str):
        contributions.setdefault(fwd_name, []).append(grad_name)

    fwd_ops = list(block.ops[: loss_op_idx + 1])
    for op in reversed(fwd_ops):
        try:
            opdef = registry.get_op(op.type)
        except KeyError:
            continue
        if not opdef.differentiable:
            continue
        # does any output carry grad?
        out_has_grad = any(n in contributions for n in op.output_arg_names)
        if not out_has_grad:
            continue
        in_needs_grad = any(
            n in req and n not in extra_no_grad
            for slot, names in op.inputs.items()
            if slot not in opdef.no_grad_set
            for n in names
        )
        if not in_needs_grad:
            continue

        out_grad_names = {}
        for n in op.output_arg_names:
            g = aggregate(n)
            if g is not None:
                out_grad_names[n] = g

        if opdef.grad_maker is not None:
            descs = opdef.grad_maker(op, block, out_grad_names, req - extra_no_grad)
            for d in descs:
                block.append_op(**d)
                for slot, names in d.get("outputs", {}).items():
                    if not slot.endswith("@GRAD"):
                        continue
            # custom makers register contributions themselves via convention:
            # each output named grad_var_name(x)+suffix maps back by stripping
            for d in descs:
                for slot, names in d.get("outputs", {}).items():
                    if not slot.endswith("@GRAD"):
                        continue
                    for gn in names:
                        if gn and gn != registry.EMPTY_VAR_NAME:
                            base = gn.split("@GRAD")[0]
                            add_contribution(base, gn)
            continue

        g_inputs, g_outputs, g_attrs = _make_grad_op_descs(op, opdef, out_grad_names, req - extra_no_grad)
        if not g_outputs:
            continue
        # name grad outputs uniquely, register contributions
        final_outputs: Dict[str, List[str]] = {}
        for slot, names in g_outputs.items():
            outs = []
            for fwd_name in names:
                if fwd_name is None:
                    outs.append(registry.EMPTY_VAR_NAME)
                    continue
                base = grad_var_name(fwd_name)
                k = len(contributions.get(fwd_name, []))
                gname = base if k == 0 else "%s@RENAME@%d" % (base, k)
                fv = block._find_var_recursive(fwd_name)
                block.create_var(
                    name=gname,
                    shape=fv.shape if fv else None,
                    dtype=fv.dtype if fv else "float32",
                    stop_gradient=True,
                )
                add_contribution(fwd_name, gname)
                outs.append(gname)
            final_outputs[slot] = outs
        block.append_op(type=op.type + "_grad", inputs=g_inputs, outputs=final_outputs, attrs=g_attrs)

    # collect (param, grad) pairs
    if parameter_list is not None:
        params = [
            block._find_var_recursive(p) if isinstance(p, str) else p
            for p in parameter_list
        ]
    else:
        params = [p for p in program.global_block().all_parameters() if p.trainable]
    result = []
    for p in params:
        if p is None or p.name in extra_no_grad:
            continue
        g = aggregate(p.name)
        if g is None:
            continue
        gvar = block._find_var_recursive(g)
        result.append((p, gvar))
    # aggregate the requested input leaves (gradients()' inputs): multiple
    # targets contribute separately-named grads; the summed var is what the
    # caller must read, so hand its name back explicitly
    leaf_grads: Dict[str, Optional[str]] = {
        name: aggregate(name) for name in extra_leaves
    }
    program.version += 1
    return result, leaf_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """reference: backward.py:939 calc_gradient — d(sum of targets)/d(inputs).

    Multiple targets are supported: each target's output-grad is seeded
    (ones, or the matching ``target_gradients`` entry) and contributions
    from all targets are summed into each input's grad, matching the
    reference's multi-target semantics (backward.py:821).
    """
    targets = list(targets) if isinstance(targets, (list, tuple)) else [targets]
    inputs = list(inputs) if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    else:
        target_gradients = (
            list(target_gradients)
            if isinstance(target_gradients, (list, tuple))
            else [target_gradients]
        )
    if len(target_gradients) != len(targets):
        raise ValueError(
            "target_gradients length %d != targets length %d"
            % (len(target_gradients), len(targets))
        )
    _, leaf_grads = _append_backward_impl(
        targets,
        target_gradients,
        parameter_list=None,
        no_grad_set=no_grad_set,
        extra_leaves={iv.name for iv in inputs},
    )
    block = targets[0].block
    out = []
    for iv in inputs:
        g = leaf_grads.get(iv.name)
        out.append(block._find_var_recursive(g) if g else None)
    return out
