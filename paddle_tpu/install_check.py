"""Install sanity check (reference: python/paddle/fluid/install_check.py
run_check — trains a tiny model single- and multi-device)."""
from __future__ import annotations

import numpy as np

__all__ = ["run_check"]


def run_check():
    import paddle_tpu as fluid
    from paddle_tpu import framework
    from paddle_tpu.parallel.mesh import local_devices

    prog, startup = framework.Program(), framework.Program()
    prog.random_seed = startup.random_seed = 1
    with framework.program_guard(prog, startup):
        x = fluid.layers.data("x", [2])
        y = fluid.layers.data("y", [1])
        loss = fluid.layers.mean(
            fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y)
        )
        fluid.optimizer.SGDOptimizer(0.01).minimize(loss)

    xb = np.array([[1.0, 2.0], [3.0, 4.0]], dtype="float32")
    yb = np.array([[3.0], [7.0]], dtype="float32")
    scope = fluid.Scope()
    exe = fluid.Executor(fluid.CPUPlace())
    with fluid.scope_guard(scope):
        exe.run(startup)
        exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])
    print("Your paddle_tpu works well on SINGLE device.")

    devs = local_devices()
    if len(devs) > 1:
        prog2, startup2 = framework.Program(), framework.Program()
        prog2.random_seed = startup2.random_seed = 1
        with framework.program_guard(prog2, startup2):
            x = fluid.layers.data("x", [2])
            y = fluid.layers.data("y", [1])
            loss2 = fluid.layers.mean(
                fluid.layers.square_error_cost(fluid.layers.fc(x, 1), y)
            )
            fluid.optimizer.SGDOptimizer(0.01).minimize(loss2)
        compiled = fluid.CompiledProgram(prog2).with_data_parallel(loss_name=loss2.name)
        reps = -(-len(devs) // len(xb))  # batch must divide across the mesh
        xb2, yb2 = np.tile(xb, (reps, 1)), np.tile(yb, (reps, 1))
        scope2 = fluid.Scope()
        with fluid.scope_guard(scope2):
            exe.run(startup2)
            exe.run(compiled, feed={"x": xb2, "y": yb2}, fetch_list=[loss2])
        print("Your paddle_tpu works well on MUTIPLE devices.")
    print("Your paddle_tpu is installed successfully!")
