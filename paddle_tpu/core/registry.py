"""Op registry: each op type maps to a pure JAX kernel + metadata.

TPU-native analog of the reference's OpRegistry/OpInfoMap
(paddle/fluid/framework/op_registry.h:66, op_info.cc).  Differences by
design:

* A kernel is a *pure function* ``kernel(inputs, attrs) -> outputs`` over
  jax arrays — there is no Place/dtype/layout dispatch key
  (operator.cc:898 ChooseKernel); XLA owns code generation for every
  backend, so one kernel body serves CPU and TPU.
* Shape inference defaults to ``jax.eval_shape`` over the kernel itself —
  the kernel *is* the InferShape function (reference keeps separate
  compile/runtime InferShape, shape_inference.h).
* Grad op makers (grad_op_desc_maker.h) default to a generic ``jax.vjp``
  maker: the grad op re-runs the forward kernel under vjp.  Inside one
  jitted module XLA CSE dedups the recomputation, and where it doesn't,
  the recompute acts as rematerialisation — an HBM win on TPU.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

import numpy as np

__all__ = ["OpDef", "register_op", "get_op", "has_op", "infer_shape", "get_kernel"]

# inputs: Dict[slot, List[jax.Array]]; returns Dict[slot, List[jax.Array]] or
# Dict[slot, jax.Array] (normalized to lists by the lowering).
KernelFn = Callable[[Dict[str, List[Any]], Dict[str, Any]], Dict[str, Any]]

GRAD_SLOT_SUFFIX = "@GRAD"
# output name used by grad makers for inputs that need no gradient
EMPTY_VAR_NAME = "@EMPTY@"

_REGISTRY: Dict[str, "OpDef"] = {}


class OpDef:
    def __init__(
        self,
        type: str,
        kernel: Optional[KernelFn],
        infer_shape: Optional[Callable] = None,
        grad_maker: Optional[Callable] = None,
        no_grad_set: Optional[Set[str]] = None,
        differentiable: bool = True,
        stateful_outputs: Sequence[str] = (),
    ):
        self.type = type
        self.kernel = kernel
        self.custom_infer_shape = infer_shape
        self.grad_maker = grad_maker
        # input slots that never receive a gradient (e.g. integer Ids)
        self.no_grad_set = set(no_grad_set or ())
        self.differentiable = differentiable
        # output slots that alias an input (in-place optimizer updates)
        self.stateful_outputs = tuple(stateful_outputs)


def register_op(
    type: str,
    infer_shape: Optional[Callable] = None,
    grad_maker: Optional[Callable] = None,
    no_grad_set: Optional[Set[str]] = None,
    differentiable: bool = True,
    stateful_outputs: Sequence[str] = (),
):
    """Decorator: ``@register_op("relu")`` over the kernel function."""

    def deco(kernel: KernelFn):
        _REGISTRY[type] = OpDef(
            type,
            kernel,
            infer_shape=infer_shape,
            grad_maker=grad_maker,
            no_grad_set=no_grad_set,
            differentiable=differentiable,
            stateful_outputs=stateful_outputs,
        )
        return kernel

    return deco


def has_op(type: str) -> bool:
    _ensure_ops_loaded()
    return type in _REGISTRY or (type.endswith("_grad") and type[: -len("_grad")] in _REGISTRY)


def get_op(type: str) -> OpDef:
    _ensure_ops_loaded()
    if type in _REGISTRY:
        return _REGISTRY[type]
    if type.endswith("_grad"):
        base = _REGISTRY.get(type[: -len("_grad")])
        if base is not None and base.kernel is not None:
            opdef = OpDef(type, make_vjp_grad_kernel(base))
            _REGISTRY[type] = opdef
            return opdef
    raise KeyError("op %r is not registered" % type)


def get_kernel(type: str) -> KernelFn:
    k = get_op(type).kernel
    if k is None:
        raise KeyError("op %r has no kernel (structural op?)" % type)
    return k


_ops_loaded = False


def _ensure_ops_loaded():
    global _ops_loaded
    if not _ops_loaded:
        _ops_loaded = True
        import paddle_tpu.ops  # noqa: F401  (registers all builtin ops)


# ---------------------------------------------------------------------------
# Generic vjp-based grad kernel (the DefaultGradOpDescMaker analog,
# reference: paddle/fluid/framework/grad_op_desc_maker.h)
# ---------------------------------------------------------------------------
def _is_float(x) -> bool:
    return np.issubdtype(np.asarray(x).dtype if not hasattr(x, "dtype") else x.dtype, np.floating) or str(
        getattr(x, "dtype", "")
    ) == "bfloat16"


def make_vjp_grad_kernel(fwd: OpDef) -> KernelFn:
    """Build the kernel for ``<type>_grad``.

    Grad-op slot convention (mirrors the reference's grad op descs):
      inputs  = forward inputs (same slots) + forward outputs (same slots)
                + ``<out_slot>@GRAD`` for each forward output
      outputs = ``<in_slot>@GRAD`` for each differentiable forward input
    """
    import jax
    import jax.numpy as jnp

    def kernel(inputs: Dict[str, List[Any]], attrs: Dict[str, Any]) -> Dict[str, Any]:
        fwd_inputs = {
            slot: vals
            for slot, vals in inputs.items()
            if not slot.endswith(GRAD_SLOT_SUFFIX) and slot not in attrs.get("__fwd_output_slots__", ())
        }
        out_grads = {
            slot[: -len(GRAD_SLOT_SUFFIX)]: vals
            for slot, vals in inputs.items()
            if slot.endswith(GRAD_SLOT_SUFFIX)
        }
        want_slots = [s for s in attrs.get("__grad_input_slots__", fwd_inputs.keys())]
        # split differentiable vs static inputs PER POSITION — a slot may
        # mix float state with bool/int values (e.g. bounded_while's X
        # carries the loop condition alongside float loop state)
        diff = {}
        diff_pos = {}
        for slot in want_slots:
            if slot in fwd.no_grad_set or slot not in fwd_inputs:
                continue
            vals = fwd_inputs[slot]
            idxs = [i for i, v in enumerate(vals) if _is_float(v)]
            if idxs:
                diff[slot] = [vals[i] for i in idxs]
                diff_pos[slot] = idxs
        static = {}
        for s, vals in fwd_inputs.items():
            if s in diff:
                skip = set(diff_pos[s])
                static[s] = [None if i in skip else v for i, v in enumerate(vals)]
            else:
                static[s] = vals
        fwd_attrs = {k: v for k, v in attrs.items() if not k.startswith("__")}

        def f(diff_vals):
            all_in = {}
            for s, vals in static.items():
                if s in diff_vals:
                    merged = list(vals)
                    for i, dv in zip(diff_pos[s], diff_vals[s]):
                        merged[i] = dv
                    all_in[s] = merged
                else:
                    all_in[s] = list(vals)
            outs = fwd.kernel(all_in, fwd_attrs)
            outs = {k: v if isinstance(v, (list, tuple)) else [v] for k, v in outs.items()}
            return {k: list(v) for k, v in outs.items() if k in out_grads}

        primals, vjp_fn = jax.vjp(f, diff)
        def conform(g, v):
            if g is None:
                return jnp.zeros(v.shape, v.dtype)
            g = jnp.asarray(g)
            if g.shape != v.shape:
                g = g.reshape(v.shape)
            return g.astype(v.dtype)

        empty_mask = attrs.get("__empty_out_grad_mask__", {})
        cots = {}
        for slot, vals in primals.items():
            gs = out_grads.get(slot)
            mask = empty_mask.get(slot)
            if gs is not None and mask is not None:
                it = iter(gs)
                gs = [None if empty else next(it) for empty in mask]
            cots[slot] = [conform(g, v) for v, g in zip(vals, (gs or [None] * len(vals)))]
        (in_grads,) = vjp_fn(cots)
        result = {}
        for slot, gvals in in_grads.items():
            # re-expand to full slot length: None at non-diff positions
            # (the lowering drops them against EMPTY output names)
            full = [None] * len(fwd_inputs[slot])
            for i, g in zip(diff_pos[slot], gvals):
                full[i] = g
            result[slot + GRAD_SLOT_SUFFIX] = full
        return result

    return kernel


# ---------------------------------------------------------------------------
# Compile-time shape inference via abstract evaluation
# ---------------------------------------------------------------------------
_DUMMY_BATCH = 117  # stand-in for -1 dims during eval_shape; mapped back after


def infer_shape(op, block) -> None:
    """Set output var shapes/dtypes by abstractly evaluating the kernel.

    The reference maintains hand-written InferShape per op
    (shape_inference.h); here ``jax.eval_shape`` over the kernel gives the
    same answer for free.  Ops may override via ``infer_shape=`` at
    registration (e.g. ops whose output shape depends on attr-only info).
    """
    import jax
    import jax.numpy as jnp

    try:
        opdef = get_op(op.type)
    except KeyError:
        return
    if opdef.custom_infer_shape is not None:
        opdef.custom_infer_shape(op, block)
        return
    if opdef.kernel is None:
        return
    specs: Dict[str, List[Any]] = {}
    all_static = True
    for slot, names in op.inputs.items():
        lst = []
        for n in names:
            if n == EMPTY_VAR_NAME:
                continue
            v = block.var(n)
            if v.shape is None:
                return  # cannot infer
            if any(s == -1 for s in v.shape):
                all_static = False
            shape = tuple(_DUMMY_BATCH if s == -1 else s for s in v.shape)
            lst.append(jax.ShapeDtypeStruct(shape, jnp.dtype(v.dtype) if v.dtype != "bfloat16" else jnp.bfloat16))
        specs[slot] = lst
    try:
        out = jax.eval_shape(lambda ins: opdef.kernel(ins, op.attrs), specs)
    except (
        jax.errors.ConcretizationTypeError,
        jax.errors.TracerArrayConversionError,
        jax.errors.TracerIntegerConversionError,
        jax.errors.TracerBoolConversionError,
    ):
        return  # kernel needs concrete values; leave shapes unset
    except NotImplementedError:
        return
    except Exception as e:
        if not all_static:
            # -1 dims were stand-ins (_DUMMY_BATCH); independent dynamic
            # dims can fabricate mismatches — stay silent, jit will check
            return
        # fully static inputs => a REAL shape/dtype incompatibility:
        # surface it at append_op like the reference's compile-time
        # InferShape (framework.py:992 validates eagerly; round-1
        # weakness #6 buried these in jit)
        raise ValueError(
            "shape inference failed for op %r (inputs %s): %s"
            % (
                op.type,
                {s: [(n, tuple(block.var(n).shape or ())) for n in ns if n != EMPTY_VAR_NAME]
                 for s, ns in op.inputs.items()},
                e,
            )
        ) from e
    for slot, names in op.outputs.items():
        vals = out.get(slot)
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        for n, sd in zip(names, vals):
            if n == EMPTY_VAR_NAME:
                continue
            v = block._find_var_recursive(n)
            if v is None:
                continue
            v.shape = tuple(-1 if s == _DUMMY_BATCH else int(s) for s in sd.shape)
            v.dtype = str(sd.dtype) if str(sd.dtype) != "bfloat16" else "bfloat16"
