"""Var/data type enums (reference: paddle/fluid/framework/framework.proto:105-160)."""
from __future__ import annotations

import numpy as np


class VarType:
    LOD_TENSOR = 7
    SELECTED_ROWS = 8
    FEED_MINIBATCH = 9
    FETCH_LIST = 10
    STEP_SCOPES = 11
    LOD_RANK_TABLE = 12
    LOD_TENSOR_ARRAY = 13
    READER = 15
    RAW = 17


_DTYPE_ALIASES = {
    "float32": "float32",
    "fp32": "float32",
    "float": "float32",
    "float64": "float64",
    "fp64": "float64",
    "double": "float64",
    "float16": "float16",
    "fp16": "float16",
    "bfloat16": "bfloat16",
    "bf16": "bfloat16",
    "int8": "int8",
    "uint8": "uint8",
    "int16": "int16",
    "int32": "int32",
    "int": "int32",
    "int64": "int64",
    "bool": "bool",
}


# serving precision-variant labels (the bf16/int8 compiled variants plus
# the fp32 base program) — ONE alias map shared by AnalysisPredictor's
# dispatch, InferenceServer.submit's validation, and the mixed-precision
# export, so the accepted request-facing spelling set can never drift
# between the layers (a dtype submit admits must be one the predictor
# serves).  Distinct from _DTYPE_ALIASES above: these canonicalize to
# the short variant labels ("bf16"), not numpy dtype names.
PRECISION_ALIASES = {
    "bf16": "bf16", "bfloat16": "bf16",
    "int8": "int8",
    "fp32": "fp32", "float32": "fp32",
}


def canonical_dtype(dtype) -> str:
    if isinstance(dtype, str):
        key = dtype.lower()
        if key in _DTYPE_ALIASES:
            return _DTYPE_ALIASES[key]
    return str(np.dtype(dtype))


def np_dtype(dtype) -> np.dtype:
    d = canonical_dtype(dtype)
    if d == "bfloat16":
        import jax.numpy as jnp

        return np.dtype(jnp.bfloat16)
    return np.dtype(d)


def is_float_dtype(dtype) -> bool:
    return canonical_dtype(dtype) in ("float16", "bfloat16", "float32", "float64")
