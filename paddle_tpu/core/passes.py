"""Program pass framework (reference: paddle/fluid/framework/ir/pass.h:38
PassRegistry + ir/graph_pattern_detector.h — 72 REGISTER_PASS sites).

On TPU most of the reference's passes (kernel fusions, memory reuse,
all-reduce fusion) are XLA compiler decisions, so the pass tier here is
thinner but REAL: program-level rewrites share one registry, one
``apply_pass`` entry point, and a pattern matcher for op-chain rewrites.
Existing rewriters (AMP bf16, slim QAT, feed/fetch pruning) are
registered below so tools can discover and compose them like the
reference's ``pass_builder``.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

__all__ = [
    "ProgramPass", "register_pass", "get_pass", "apply_pass", "list_passes",
    "PassManager", "match_chain",
]

_PASS_REGISTRY: Dict[str, "ProgramPass"] = {}


class ProgramPass:
    """A named program rewrite: ``apply(program, **kwargs) -> program``
    (in-place mutation, program returned for chaining)."""

    def __init__(self, name: str, fn: Callable):
        self.name = name
        self._fn = fn

    def apply(self, program, **kwargs):
        out = self._fn(program, **kwargs)
        if out is None or out is program:
            # in-place rewrite: invalidate compiled-executable caches.
            # Passes returning a NEW program (e.g. a pruned clone) leave
            # the original untouched — no spurious recompiles.
            program.version += 1
            return program
        return out


def register_pass(name: str):
    """Decorator: ``@register_pass("amp_bf16")`` over
    ``fn(program, **kwargs)`` (REGISTER_PASS analog)."""

    def deco(fn):
        _PASS_REGISTRY[name] = ProgramPass(name, fn)
        return fn

    return deco


def get_pass(name: str) -> ProgramPass:
    if name not in _PASS_REGISTRY:
        raise KeyError(
            "pass %r is not registered (have: %s)" % (name, sorted(_PASS_REGISTRY))
        )
    return _PASS_REGISTRY[name]


def list_passes() -> List[str]:
    return sorted(_PASS_REGISTRY)


def apply_pass(name: str, program, **kwargs):
    return get_pass(name).apply(program, **kwargs)


class PassManager:
    """Ordered pipeline of passes (BuildStrategy pass-pipeline analog,
    details/build_strategy.cc:52-186)."""

    def __init__(self, names: Sequence[str] = ()):
        self._names = list(names)

    def add(self, name: str):
        get_pass(name)  # validate eagerly
        self._names.append(name)
        return self

    def apply(self, program, **kwargs):
        for n in self._names:
            apply_pass(n, program, **kwargs.get(n, {}) if isinstance(kwargs.get(n), dict) else {})
        return program


# ---------------------------------------------------------------------------
# pattern matcher (GraphPatternDetector-lite): find op chains linked
# through their tensors
# ---------------------------------------------------------------------------
def match_chain(block, op_types: Sequence[str], link_slots: Optional[Sequence[tuple]] = None):
    """Find occurrences of ``op_types`` where each op's output feeds the
    next op's input.  ``link_slots``: optional [(out_slot, in_slot), ...]
    per link; defaults to any-output -> any-input.  Returns a list of op
    lists (one per match)."""
    def feeds(prev, nxt, link):
        if link is None:
            outs = set(prev.output_arg_names)
            ins = set(nxt.input_arg_names)
            return bool(outs & ins)
        out_slot, in_slot = link
        outs = set(prev.outputs.get(out_slot, ()))
        ins = set(nxt.inputs.get(in_slot, ()))
        return bool(outs & ins)

    def extend(chain, depth):
        """Backtracking search: a mid-chain op may have several
        consumers of the right type — try each."""
        if depth == len(op_types):
            return chain
        link = link_slots[depth - 1] if link_slots else None
        for cand in block.ops:
            if cand.type != op_types[depth] or cand in chain:
                continue
            if feeds(chain[-1], cand, link):
                full = extend(chain + [cand], depth + 1)
                if full is not None:
                    return full
        return None

    matches = []
    for op in block.ops:
        if op.type != op_types[0]:
            continue
        full = extend([op], 1)
        if full is not None:
            matches.append(full)
    return matches


# ---------------------------------------------------------------------------
# built-in passes: the framework's existing rewriters, discoverable
# ---------------------------------------------------------------------------
@register_pass("amp_bf16")
def _amp_pass(program, amp_lists=None):
    """bf16 mixed-precision rewrite (contrib/mixed_precision)."""
    from paddle_tpu.contrib.mixed_precision import decorator as amp

    # rewrite_program works on the default main program's block structure
    amp.rewrite_program(program, amp_lists)
    return program


@register_pass("qat_quantize")
def _qat_pass(program, **kwargs):
    """Quantization-aware-training fake-quant insertion (slim)."""
    from paddle_tpu.contrib.slim import quantization as q

    q.quantize_program(program, **kwargs)
    return program


@register_pass("prune_to_targets")
def _prune_pass(program, feeds=(), targets=()):
    """Backward-slice the program to the target vars (prune.cc analog —
    io.py's inference-model pruning as a reusable pass).  Returns the
    PRUNED CLONE (the original is untouched)."""
    from paddle_tpu import io as _io

    return _io._prune_program(program, list(feeds), list(targets))
