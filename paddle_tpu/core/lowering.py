"""Block -> single jitted XLA module.

This replaces the reference's interpreted hot loop
(paddle/fluid/framework/executor.cc:433-437 ``for op in ops: op->Run``)
with whole-block tracing: every op kernel is a pure JAX function, so the
entire block — forward, backward, and optimizer update ops — traces into
ONE XLA computation.  XLA then fuses elementwise chains into the matmuls
(MXU), assigns buffers (subsuming the reference's memory-reuse passes,
ir/memory_optimize_pass/), and schedules collectives.  State (persistable
vars) is threaded functionally and donated, giving in-place param updates.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from paddle_tpu.core import registry
from paddle_tpu.core.registry import EMPTY_VAR_NAME
from paddle_tpu.monitor import spans as _mon_spans

__all__ = ["lower_block", "trace_ops"]


def trace_ops(ops, env: Dict[str, Any], block=None) -> Dict[str, Any]:
    """Run (or trace) a sequence of Operators over an env of name->array.

    When an activation-sharding context is installed on this thread
    (``sharding.activations.tracing`` — the executor wraps a compiled
    program's block trace in one), every op output written to the env
    passes through the constrainer: matched intermediates get
    ``with_sharding_constraint`` applied in-trace, unmatched ones are
    left for GSPMD propagation."""
    from paddle_tpu.sharding import activations as _sh_act

    act = _sh_act.current()
    for op in ops:
        kernel = registry.get_kernel(op.type)
        ins: Dict[str, List[Any]] = {}
        for slot, names in op.inputs.items():
            vals = []
            for n in names:
                if n == EMPTY_VAR_NAME:
                    continue
                if n not in env:
                    raise KeyError(
                        "op %s input %s=%r not produced/fed (block %s)"
                        % (op.type, slot, n, getattr(block, "idx", "?"))
                    )
                vals.append(env[n])
            if vals:
                ins[slot] = vals
        outs = kernel(ins, op.attrs)
        if outs is None:
            continue
        for slot, names in op.outputs.items():
            vals = outs.get(slot)
            if vals is None:
                continue
            if not isinstance(vals, (list, tuple)):
                vals = [vals]
            for n, v in zip(names, vals):
                if n != EMPTY_VAR_NAME and v is not None:
                    env[n] = v if act is None else act.constrain(n, v)
    return env


def lower_block(
    block,
    feed_names: Sequence[str],
    fetch_names: Sequence[str],
    state_names: Sequence[str],
):
    """Build ``fn(state_dict, feed_dict) -> (fetch_list, new_state_dict)``.

    * ``state_names``: persistable vars read/written by the block (params,
      optimizer moments, LR...).  Returned updated so the caller can donate
      the old buffers.
    * Non-persistable intermediates never materialize outside XLA.
    """
    feed_names = tuple(feed_names)
    fetch_names = tuple(fetch_names)
    state_names = tuple(state_names)
    ops = list(block.ops)

    def fn(state: Dict[str, Any], feed: Dict[str, Any]):
        # the host-side cost of tracing the whole block through the op
        # kernels — this runs under jax.jit tracing on the first dispatch
        # of a cache key, so the span lands nested inside the executor's
        # jit_compile span (run-phase observability, paddle_tpu/monitor)
        _t0 = time.perf_counter() if _mon_spans.recording() else None
        env = dict(state)
        env.update(feed)
        trace_ops(ops, env, block)
        fetches = [env[n] for n in fetch_names]
        new_state = {n: env[n] for n in state_names if n in env}
        if _t0 is not None:
            _mon_spans.record_span(
                "lowering/trace_block", _t0, time.perf_counter() - _t0,
                cat="lower", n_ops=len(ops))
        return fetches, new_state

    return fn
