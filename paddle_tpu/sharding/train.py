"""Sharded FSDP/TP *training* through the partition-rules surface.

PR 10's :class:`~paddle_tpu.sharding.rules.PartitionRules` made
model-parallel serving declarative; this module points the SAME rules
at a TRAIN program — forward + backward + optimizer ops in one compiled
module — so params, grads, and optimizer state all live sharded on the
mesh with zero new user concepts:

* :class:`TrainPartitionRules` — a rule set that also carries the
  optimizer's accumulator↔param mapping
  (``Optimizer.accumulator_map()``).  Each accumulator derives its spec
  from **its param's matched rule** (the ``match_partition_rules``
  shape fmengine uses for Optax state): an Adam moment inherits the
  param's placement, a scalar beta-pow auto-replicates, and there is NO
  ``default=`` escape hatch in the derivation — an accumulator whose
  param no rule covers fails typed, naming the param.
* :func:`train_rules` — build one from a base layout (e.g.
  ``canonical_rules("transformer_lm", "fsdp")``) plus a live optimizer
  or an explicit accumulator map.
* :func:`sharded_train_program` — the one-call surface:
  ``CompiledProgram(prog).with_sharding_rules(train_rules(...))`` with
  the mesh bound, ready for ``Executor.run``/``train_from_dataset``.
  Output layouts are pinned by the executor exactly like serving
  (``out_shardings``), so sharded optimizer state stays sharded across
  steps and the steady state pays zero placement work and zero
  recompiles — the same jit-cache ground truth as the serving path.

Export closes the loop: ``save_inference_model(sharding_rules=`` a
``TrainPartitionRules``)`` unwraps to the base serving rules (the
pruned inference program has no accumulators), so the training layout
rides the manifest into ``AnalysisPredictor`` / the fleet unchanged.

Observability: during each full placement pass the compiled program
publishes ``sharding_train_state_bytes{kind=param|grad|moment}`` — the
per-device bytes the capacity math reads (grad bytes are accounted at
the param's placement: one grad per trainable param, its layout pinned
to the param's by the update's out sharding).  The series retire when
the layout is torn down (:func:`retire_state_bytes`, also called on a
mesh/rules rebind).
"""
from __future__ import annotations

from typing import Dict, Mapping, Optional

from paddle_tpu.sharding.rules import (
    PartitionRules,
    ShardingRuleError,
    _n_elements,
    _shape_of,
)

__all__ = [
    "TrainPartitionRules",
    "train_rules",
    "sharded_train_program",
    "per_device_bytes",
    "state_bytes",
    "publish_state_bytes",
    "retire_state_bytes",
    "box_overlap",
    "box_volume",
    "boxes_cover",
    "shard_boxes",
]

_KINDS = ("param", "grad", "moment")


class TrainPartitionRules(PartitionRules):
    """A rule set over a TRAIN program's persistables.

    ``accumulators``: ``{accumulator var name: param name}`` (values may
    also be ``(param name, kind)`` tuples, the
    ``Optimizer.accumulator_map()`` shape).  Resolution:

    * an accumulator resolves to **its param's** spec — the param's
      first matching rule (or the rule set's default, when the param
      itself falls back to it); scalar/single-element accumulators
      (Adam beta pows) auto-replicate like any scalar,
    * every other name resolves with plain :class:`PartitionRules`
      semantics,
    * rank checks run against the accumulator's OWN shape, so a spec a
      param carries but an accumulator cannot (rank mismatch) is a
      typed error naming both.

    The base layout is kept as :attr:`serving_rules`:
    ``save_inference_model`` unwraps to it, so the exported manifest is
    exactly the serving layout (accumulators do not exist in the pruned
    inference program).
    """

    def __init__(self, rules, accumulators: Mapping[str, object],
                 default=None, name: Optional[str] = None):
        if isinstance(rules, PartitionRules):
            base = rules
            if default is not None:
                base = PartitionRules(base.rules, default=default,
                                      name=base.name)
        else:
            base = PartitionRules(rules, default=default)
        super().__init__(base.rules, default=base.default,
                         name=name or "%s+train" % base.name)
        self.serving_rules = base
        self._acc_param: Dict[str, str] = {}
        self._acc_kind: Dict[str, str] = {}
        for acc, ent in dict(accumulators).items():
            if isinstance(ent, (tuple, list)):
                pname, kind = str(ent[0]), str(ent[1])
            else:
                pname, kind = str(ent), "moment"
            self._acc_param[str(acc)] = pname
            self._acc_kind[str(acc)] = kind
        if not self._acc_param:
            raise ShardingRuleError(
                "TrainPartitionRules %r got an empty accumulator map — "
                "build it from Optimizer.accumulator_map() AFTER "
                "minimize() has run" % self.name)

    @property
    def accumulators(self) -> Dict[str, str]:
        """{accumulator name: param name} (copy)."""
        return dict(self._acc_param)

    def with_default(self, default) -> "TrainPartitionRules":
        # keep the accumulator map through a default rebind — dropping
        # to a plain PartitionRules here would silently replicate any
        # accumulator whose param only the default covers
        return TrainPartitionRules(
            self.serving_rules,
            {a: (p, self._acc_kind[a]) for a, p in self._acc_param.items()},
            default=default, name=self.name)

    def state_kind(self, name: str) -> Optional[str]:
        """``"moment"`` for an optimizer accumulator, ``"param"`` for a
        name some accumulator points at, None otherwise (LR vars, EMA
        state, ...).  The classification behind the
        ``sharding_train_state_bytes`` gauge."""
        if name in self._acc_param:
            return "moment"
        if name in self._param_names:
            return "param"
        return None

    @property
    def _param_names(self):
        names = getattr(self, "_param_name_set", None)
        if names is None:
            names = self._param_name_set = set(self._acc_param.values())
        return names

    # hot-path: begin train_spec_resolve (rule resolution for train
    # state — runs once per name on the compiled program's memo MISS
    # path, inside the dispatch region; pure dict/regex work only)
    def spec_for(self, name: str, shape=None):
        param = self._acc_param.get(name)
        if param is None:
            return super().spec_for(name, shape=shape)
        shp = _shape_of(shape) if shape is not None else None
        if shp is not None and (len(shp) == 0 or _n_elements(shp) == 1):
            # scalar accumulators (beta pows, step counters) never
            # partition — same shortcut as params
            from jax.sharding import PartitionSpec as P

            return P()
        try:
            # the param's FULL resolution (its matched rule, or the rule
            # set's default when the param itself uses it) — the
            # accumulator adds no fallback of its own
            spec = super().spec_for(param)
        except ShardingRuleError as e:
            raise ShardingRuleError(
                "accumulator %r inherits its spec from param %r, which "
                "no rule covers: %s" % (name, param, e)) from None
        if shp is not None:
            self._check_rank(
                name, spec, shp, "inherited from param %r" % param)
        return spec
    # hot-path: end train_spec_resolve


def train_rules(rules, optimizer=None, accumulators=None,
                default=None, name: Optional[str] = None
                ) -> TrainPartitionRules:
    """Build :class:`TrainPartitionRules` from a base layout plus the
    optimizer that owns the accumulators.

    ``rules``: a :class:`PartitionRules` (e.g. ``canonical_rules(...)``)
    or a ``(regex, spec)`` list.  ``optimizer``: a live
    ``paddle_tpu.optimizer.Optimizer`` AFTER ``minimize()`` — its
    ``accumulator_map()`` supplies the name↔param ground truth;
    ``accumulators`` passes the map explicitly instead."""
    if accumulators is None:
        if optimizer is None:
            raise ShardingRuleError(
                "train_rules needs the optimizer (or an explicit "
                "accumulators= map) to know which accumulator belongs "
                "to which param")
        accumulators = optimizer.accumulator_map()
    return TrainPartitionRules(rules, accumulators, default=default,
                               name=name)


def sharded_train_program(program, rules, optimizer=None,
                          accumulators=None, mesh=None, mesh_axes=None,
                          default=None):
    """One call from a built train program to a mesh-sharded
    ``CompiledProgram``: wraps ``rules`` into train rules (accumulators
    inherit their param's placement) and binds the mesh.  ``program``
    may be a ``Program`` or an existing ``CompiledProgram``."""
    from paddle_tpu.parallel.compiled_program import CompiledProgram

    if not isinstance(rules, TrainPartitionRules):
        rules = train_rules(rules, optimizer=optimizer,
                            accumulators=accumulators, default=default)
    compiled = (program if getattr(program, "_is_compiled_program", False)
                else CompiledProgram(program))
    return compiled.with_sharding_rules(rules, mesh=mesh,
                                        mesh_axes=mesh_axes)


# ---------------------------------------------------------------------------
# shard-box algebra: index regions as ((start, stop), ...) per dim.
#
# The shard-exchange checkpoint restore (faults/checkpoint.py) and the
# offline verifier (tools/check_checkpoint.py) both reason about which
# saved shard regions tile which target device regions — one definition
# of the interval math, so the runtime and the tool cannot drift.
# ---------------------------------------------------------------------------
def box_overlap(a, b):
    """Intersection of two boxes (same rank), or None when disjoint on
    any dim.  A box is ``((start, stop), ...)`` over the global shape."""
    out = []
    for (a0, a1), (b0, b1) in zip(a, b):
        lo, hi = max(int(a0), int(b0)), min(int(a1), int(b1))
        if lo >= hi:
            return None
        out.append((lo, hi))
    return tuple(out)


def box_volume(box) -> int:
    n = 1
    for lo, hi in box:
        n *= max(0, int(hi) - int(lo))
    return n


def boxes_cover(boxes, target) -> bool:
    """True iff ``boxes`` (pairwise-disjoint regions — a PartitionSpec
    sharding's shard grid is) fully tile ``target``: the disjointness
    makes overlap-volume summation an exact coverage test."""
    vol = 0
    for b in boxes:
        ov = box_overlap(b, target)
        if ov is not None:
            vol += box_volume(ov)
    return vol == box_volume(target)


def shard_boxes(sharding, shape):
    """``{box: [devices]}`` — each DISTINCT addressable shard region of
    ``sharding`` over global ``shape`` and the local devices holding a
    replica of it.  The shard-exchange restore assembles each box once
    and ``device_put``s it per device."""
    out: Dict = {}
    for dev, idx in sharding.addressable_devices_indices_map(
            tuple(int(d) for d in shape)).items():
        box = []
        for sl, dim in zip(idx, shape):
            start = 0 if sl.start is None else int(sl.start)
            stop = int(dim) if sl.stop is None else int(sl.stop)
            box.append((start, stop))
        out.setdefault(tuple(box), []).append(dev)
    return out


# ---------------------------------------------------------------------------
# per-device state-bytes accounting (sharding_train_state_bytes gauge)
# ---------------------------------------------------------------------------
def per_device_bytes(arr) -> int:
    """ONE device's bytes for ``arr``: a mesh-committed array counts
    its (first) addressable shard, a host/single-device value its full
    size — the measurement behind the gauge, the bench, and the tests
    (one definition, so they cannot drift)."""
    shards = getattr(arr, "addressable_shards", None)
    if shards:
        return int(shards[0].data.nbytes)
    return int(getattr(arr, "nbytes", 0))


# hot-path: begin train_state_bytes (runs on the compiled program's
# full placement pass — a cold/warmup event; reads shard METADATA only,
# never a device buffer, so no d2h sync can hide here)
def state_bytes(kind_of, *state_dicts) -> Dict[str, int]:
    """Per-device bytes by kind over state dicts of {name: array}.
    ``kind_of``: name -> "param"|"moment"|None (TrainPartitionRules
    .state_kind).  Grad bytes are the param total: one grad per
    trainable param, placed like its param by the pinned update
    layout."""
    totals = {"param": 0, "moment": 0}
    for d in state_dicts:
        for n, a in d.items():
            kind = kind_of(n)
            if kind in totals:
                totals[kind] += per_device_bytes(a)
    totals["grad"] = totals["param"]
    return totals
# hot-path: end train_state_bytes


def publish_state_bytes(kind_of, *state_dicts) -> Dict[str, int]:
    """Set the ``sharding_train_state_bytes{kind=...}`` gauges from the
    current state placement (called by the compiled program on each
    full placement pass; steady-state dispatches skip it entirely)."""
    from paddle_tpu.sharding import metrics as _metrics

    totals = state_bytes(kind_of, *state_dicts)
    for kind in _KINDS:
        _metrics.TRAIN_STATE_BYTES.labels(kind=kind).set(totals[kind])
    return totals


def retire_state_bytes() -> None:
    """Drop the ``sharding_train_state_bytes`` series from the
    exposition — called when the sharded-training layout is torn down
    (the compiled program's mesh/rules rebind does this; tests and the
    bench call it explicitly after teardown).  Like the publish path,
    this is process-global: the gauge carries only a ``kind`` label, so
    a process is assumed to host ONE sharded trainer at a time."""
    from paddle_tpu.sharding import metrics as _metrics

    for kind in _KINDS:
        _metrics.TRAIN_STATE_BYTES.remove_labels(kind=kind)
