"""Canonical axis layouts for the in-tree model families.

One :class:`~paddle_tpu.sharding.rules.PartitionRules` builder per
(family, mode), in the ``SpecLayout`` tradition: the mesh axes are
named once (``tp`` for tensor/model parallel, ``fsdp`` for
fully-sharded params) and every rule speaks in those names, so the same
layout runs on any mesh that carries the axes.

Modes
-----
* ``tp`` — Megatron-style tensor parallelism: attention q/k/v and the
  FFN up-projection are COLUMN-parallel (output dim sharded over
  ``tp``, their biases ride along), the attention output and FFN
  down-projections are ROW-parallel (input dim sharded, biases
  replicated — GSPMD inserts the reduce the row-parallel matmul
  needs), embeddings and the LM head shard the vocab dim.  LayerNorm
  params replicate.
* ``fsdp`` — every parameter's leading dim shards over ``fsdp``
  (ZeRO-3-style parameter sharding; GSPMD all-gathers at use).
* ``fsdp_tp`` — the 2D combination: the ``tp`` layout with every
  replicated weight dim sharded over ``fsdp`` instead.
* ``sp`` (transformer family only, outside ``MODES``) — sequence
  parallelism for long-context serving: parameters replicate and the
  layout's ACTIVATION rules shard the sequence axis over ``sp``; the
  fused attention op dispatches to ``parallel/ring_attention.py`` when
  traced under an sp activation context.

Coverage is a tested invariant, not an intention:
``tools/check_partition_rules.py`` builds each family's real in-tree
model and fails the build if any parameter is unmatched or any rule is
dead (matches nothing).
"""
from __future__ import annotations

from typing import Dict

from paddle_tpu.sharding.rules import PartitionRules, ShardingRuleError

__all__ = [
    "AXIS_TP",
    "AXIS_FSDP",
    "AXIS_SP",
    "MODES",
    "FAMILIES",
    "canonical_rules",
]

AXIS_TP = "tp"
AXIS_FSDP = "fsdp"
AXIS_SP = "sp"

# the modes every family must support (tools/check_partition_rules.py
# loops these over serve + train + bf16-variant builds).  ``sp`` is NOT
# a member: sequence parallelism is a transformer-family activation
# layout (DeepFM has no sequence axis, and sp has no train story), so
# it is reachable via canonical_rules(family, "sp") for the transformer
# builders only and guarded by the tool's dedicated check_sp pass.
MODES = ("tp", "fsdp", "fsdp_tp")


def _P(*entries):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*entries)


def _transformer_rules(mode: str, name: str) -> PartitionRules:
    """Shared layout for the transformer LM and the NMT seq2seq — both
    are built from the same blocks (models/transformer.py), so their
    parameter grammar is identical up to the attention-name alternation
    (``_att_`` encoder-style vs ``_self_``/``_cross_`` decoder-style)."""
    attn = r"_(att|self|cross)_"
    act_rules = ()
    act_default = None
    if mode == "tp":
        col_w, col_b = _P(None, AXIS_TP), _P(AXIS_TP)
        row_w, row_b = _P(AXIS_TP, None), _P()
        emb = _P(AXIS_TP, None)
        ln = _P()
    elif mode == "sp":
        # sequence parallel: every PARAM replicates (the 13 patterns are
        # kept so coverage + no-dead-rules hold for the family grammar);
        # the sharding lives in ACTIVATION rules over the auto-generated
        # intermediate names.  The seq axis sits at dim 2 of the fused
        # attention context ([N, H, S, D]) and dim 1 of everything the
        # fc / layer_norm / residual / embedding chain produces
        # ([N, S, ...]).  reshape/transpose tmps are deliberately
        # unconstrained (the seq axis moves around in them; GSPMD
        # propagation places them from their producers/consumers).
        # Divisibility contract: serve with seq_len % sp == 0 — the
        # constrainer skips a non-divisible dim rather than erroring,
        # and the fused_attention op falls back to its gathered path.
        col_w = col_b = row_w = row_b = emb = ln = _P()
        act_rules = (
            (attn + r"fused_\d+\.tmp", _P(None, None, AXIS_SP)),
            (r"^(fc|layer_norm|elementwise_add|embedding)_\d+\.tmp",
             _P(None, AXIS_SP)),
        )
    elif mode == "fsdp":
        return PartitionRules(
            [(r".", _P(AXIS_FSDP))], name=name)  # dim-0 shard everything
    elif mode == "fsdp_tp":
        col_w, col_b = _P(AXIS_FSDP, AXIS_TP), _P(AXIS_TP)
        row_w, row_b = _P(AXIS_TP, AXIS_FSDP), _P(AXIS_FSDP)
        emb = _P((AXIS_FSDP, AXIS_TP), None)
        ln = _P()
    else:
        raise ShardingRuleError(
            "unknown layout mode %r (have %s; the transformer family "
            "additionally has 'sp')" % (mode, MODES))
    return PartitionRules([
        # attention: q/k/v column-parallel, the output projection
        # row-parallel (Megatron-LM, Shoeybi et al.)
        (attn + r"(q|k|v)_w$", col_w),
        (attn + r"(q|k|v)_b$", col_b),
        (attn + r"out_w$", row_w),
        (attn + r"out_b$", row_b),
        # FFN: up column-parallel, down row-parallel
        (r"_ffn_fc0_w$", col_w),
        (r"_ffn_fc0_b$", col_b),
        (r"_ffn_fc1_w$", row_w),
        (r"_ffn_fc1_b$", row_b),
        # embeddings / head: vocab-dim sharded; positions replicated
        # (small, and the gather index is the position itself)
        (r"_word_emb$", emb),
        (r"_pos_emb$", ln),
        (r"_head_w$", col_w),
        (r"_head_b$", col_b),
        # norms replicate (tiny, and every rank needs them whole)
        (r"_ln\d_(scale|bias)$", ln),
    ], name=name, activations=act_rules, activation_default=act_default)


def transformer_lm_rules(mode: str = "tp") -> PartitionRules:
    return _transformer_rules(mode, "transformer_lm/%s" % mode)


def transformer_nmt_rules(mode: str = "tp") -> PartitionRules:
    return _transformer_rules(mode, "transformer_nmt/%s" % mode)


def deepfm_rules(mode: str = "tp") -> PartitionRules:
    """DeepFM CTR: the wide/FM embedding tables row-shard (the id dim is
    the big one), the dense-tower FCs column-shard over ``tp``, and the
    scalar output projection + auto-named tower biases replicate."""
    name = "deepfm/%s" % mode
    if mode == "fsdp":
        return PartitionRules([(r".", _P(AXIS_FSDP))], name=name)
    if mode == "tp":
        table = _P(AXIS_TP, None)
        tower_w = _P(None, AXIS_TP)
    elif mode == "fsdp_tp":
        table = _P((AXIS_FSDP, AXIS_TP), None)
        tower_w = _P(AXIS_FSDP, AXIS_TP)
    else:
        raise ShardingRuleError("unknown layout mode %r (have %s)"
                                % (mode, MODES))
    return PartitionRules([
        (r"_(w1|fm|deep)_emb$", table),
        (r"_deep_fc\d+_w$", tower_w),
        # the 1-wide output head and LayerHelper's auto-named tower
        # biases (``fc_<n>.b_0``) replicate; the head bias is a scalar
        # and self-replicates, but the rule keeps the name covered
        (r"_deep_out_w$", _P()),
        (r"^fc_\d+\.b_\d+$", _P()),
    ], name=name)


FAMILIES: Dict[str, object] = {
    "transformer_lm": transformer_lm_rules,
    "transformer_nmt": transformer_nmt_rules,
    "deepfm": deepfm_rules,
}


# hot-path: begin layout_lookup (layout builders run at endpoint
# setup/load time; they sit upstream of warmup, and must stay pure
# construction — no device work, no sleeps)
def canonical_rules(family: str, mode: str = "tp") -> PartitionRules:
    """The canonical layout for ``family`` in ``mode`` (see MODES)."""
    try:
        builder = FAMILIES[family]
    except KeyError:
        raise ShardingRuleError(
            "unknown model family %r (have %s)"
            % (family, sorted(FAMILIES))) from None
    return builder(mode)
# hot-path: end layout_lookup
