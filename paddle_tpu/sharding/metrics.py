"""Sharding metrics (process-global registry, always on).

Registered at import like every subsystem's metrics;
``tools/check_metrics_docs.py`` holds the README table to this set.

``sharding_params_sharded_total`` counts parameters the compiled
program placed SHARD-wise (a non-replicated PartitionSpec) at restage
time — placement is a warmup-time event, so the counter moving after
warmup means state is being re-staged per step (a bug the steady-token
machinery exists to prevent).  ``sharding_group_hbm_bytes`` is the
per-device footprint of one model-parallel group's persistable state:
the number the "does this model fit one chip's share" capacity math
reads.
"""
from __future__ import annotations

from paddle_tpu.monitor import registry as _registry

__all__ = ["PARAMS_SHARDED", "GROUP_HBM_BYTES", "ACTIVATION_BYTES",
           "TRAIN_STATE_BYTES", "SPARSE_TABLE_BYTES", "SPARSE_ROW_DTYPE",
           "SPARSE_LOOKUPS"]

PARAMS_SHARDED = _registry.REGISTRY.counter(
    "sharding_params_sharded_total",
    "params placed shard-wise (non-replicated PartitionSpec) onto a "
    "mesh at restage time")
GROUP_HBM_BYTES = _registry.REGISTRY.gauge(
    "sharding_group_hbm_bytes",
    "per-device HBM bytes of one model-parallel group's persistable "
    "state (sharded params count their shard, replicated params their "
    "full size)", ("group",))
ACTIVATION_BYTES = _registry.REGISTRY.gauge(
    "sharding_activation_bytes",
    "per-device bytes of one group's constrained intermediate "
    "activations, summed over the last traced program (sequence-"
    "parallel serving's capacity number: ~1/n_sp of the unsharded "
    "activation footprint)", ("group",))
TRAIN_STATE_BYTES = _registry.REGISTRY.gauge(
    "sharding_train_state_bytes",
    "per-device bytes of sharded-training state by kind (param | grad "
    "| moment); published on each full placement pass (restage — a "
    "warmup-time event) and retired when the layout is torn down.  "
    "Grad bytes are accounted at the param's placement: one grad per "
    "trainable param, layout pinned to the param's by the update's "
    "out sharding.  Scope: ONE sharded-training layout per process — "
    "publish is last-writer-wins and retire is global (kind is the "
    "only label; a training process hosts one trainer)", ("kind",))
SPARSE_TABLE_BYTES = _registry.REGISTRY.gauge(
    "sharding_sparse_table_bytes",
    "per-device bytes of one mesh-resident row-sharded lookup table "
    "(the addressable shard — ~1/n_shards of the replicated table); "
    "set at bind, retired by MeshTableRuntime.close()", ("table",))
SPARSE_ROW_DTYPE = _registry.REGISTRY.gauge(
    "sharding_sparse_row_dtype",
    "info gauge (value always 1) naming one mesh-resident table's row "
    "STORAGE dtype (fp32 | int8 per-row-scaled codes); set at bind, "
    "retired by MeshTableRuntime.close()", ("table", "dtype"))
SPARSE_LOOKUPS = _registry.REGISTRY.counter(
    "sharding_sparse_lookups_total",
    "device-side gathers served by mesh-resident tables (each one a "
    "host PS round-trip the mesh path did NOT pay)")
