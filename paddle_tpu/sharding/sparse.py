"""Mesh-resident sparse tables: row-sharded distributed lookup ON the mesh.

The PS path (``distributed/ps.py``) keeps huge embedding tables on
host-CPU servers and round-trips every batch's rows over TCP — the
right tool when a table exceeds the whole mesh's HBM, and the only tool
the runtime had until this module.  But the ``deepfm`` canonical layout
(``sharding/layouts.py``) already *declares* the better placement for
tables that fit the MESH (just not one chip): row-shard the id dim
across devices.  This module is the runtime for that declaration:

* the table lives as ONE jax array sharded ``P(axis, None)`` over the
  bound mesh — each device holds ``height / n_shards`` contiguous rows,
  so per-device table bytes are ~``1/n_shards`` of replicated and a
  table larger than one chip's HBM share becomes usable;
* lookup is a device-side gather under ``shard_map``: every shard
  gathers the rows it owns (ids outside its range contribute zeros)
  and a ``psum`` over the shard axis assembles the full row set on
  every device — the id→shard routing rides the mesh collectives
  (the all-to-all/psum pattern of ``parallel/hybrid.py``), replacing
  the host PS round-trip entirely;
* grads push back shard-wise: the same masked routing feeds a
  scatter-add update applied per shard with the SERVER-side optimizer
  semantics (``sgd`` / ``adagrad`` — numerically the ``ps._Table.push``
  kernels), so a mesh-resident table trains with loss parity against
  the PS path for deterministic initializers;
* ``row_dtype="int8"`` stores rows as int8 codes with per-row fp32
  absmax scales (``paddle_tpu.quant``) riding the SAME shard layout —
  ~4x fewer table bytes per device at the same shard count.  Lookup
  dequantizes after the local gather, BEFORE the psum (collectives
  move fp32 rows, tables store int8); push dequant-accumulates: the
  per-target-row aggregated grad is applied to the dequantized row and
  the result requantized, and the quantizer's fixed-point identity
  (``requantize(dequantize(q, s)) == (q, s)`` exactly) makes the
  row-set write collision-safe — every lane targeting a row writes the
  identical bytes, and untouched rows round-trip unchanged.  Adagrad
  moments stay fp32 (they are optimizer state, not capacity-bound
  serving state).

Unique-id counts are bucketed by the caller (the executor's prefetch
pads to a power-of-two ladder, or the autotuned
``propose_id_bucket_ladder`` rungs), and lookup/push executables are
built once per (table, bucket) — ``warmup()`` pre-compiles the ladder,
after which mixed batch sizes cost ZERO recompiles (``compiles`` is
the ground truth, same contract as ``Executor.jit_cache_stats``).

Bind with :func:`bind_mesh_tables` on a ``CompiledProgram`` whose mesh
carries the shard axis; the executor's
``_prefetch_distributed_tables`` then routes lookups/pushes here for
every bound table and never touches a ``PSClient`` for them.
"""
from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.sharding import metrics as _sh_metrics

__all__ = ["MeshTable", "MeshTableRuntime", "bind_mesh_tables",
           "ROW_DTYPES", "normalize_row_dtype"]

ROW_DTYPES = ("fp32", "int8")


def normalize_row_dtype(row_dtype) -> str:
    """Canonicalize a table row storage dtype (``None`` -> ``fp32``;
    ``"float32"`` is accepted as an alias)."""
    d = str(row_dtype or "fp32").lower()
    if d == "float32":
        d = "fp32"
    if d not in ROW_DTYPES:
        raise ValueError(
            "mesh-table row_dtype %r not in %s" % (row_dtype, ROW_DTYPES))
    return d


class MeshTable:
    """One mesh-resident table: the sharded row array plus the
    server-optimizer state that rides with it (adagrad moments shard
    exactly like their rows).  ``row_dtype="int8"`` tables carry a
    per-row fp32 ``scales`` array sharded like the rows' id dim."""

    __slots__ = ("name", "dim", "height", "padded_height",
                 "rows_per_shard", "array", "moments", "row_dtype",
                 "scales")

    def __init__(self, name: str, dim: int, height: int,
                 padded_height: int, rows_per_shard: int,
                 array, moments=None, row_dtype: str = "fp32",
                 scales=None):
        self.name = name
        self.dim = int(dim)
        self.height = int(height)
        self.padded_height = int(padded_height)
        self.rows_per_shard = int(rows_per_shard)
        self.array = array
        self.moments = moments
        self.row_dtype = row_dtype
        self.scales = scales

    def bytes_per_device(self) -> int:
        """Addressable shard bytes of the row array (plus the int8
        scales, when present) on one device — the capacity number, from
        the STORED dtype: ~``1/n_shards`` of replicated, and ~4x less
        again for int8 rows."""
        shards = self.array.addressable_shards
        total = int(shards[0].data.nbytes) if shards else 0
        if self.scales is not None:
            sshards = self.scales.addressable_shards
            total += int(sshards[0].data.nbytes) if sshards else 0
        return total

    def replicated_bytes(self) -> int:
        total = int(self.array.nbytes)
        if self.scales is not None:
            total += int(self.scales.nbytes)
        return total


class MeshTableRuntime:
    """The lookup/push engine for a set of mesh-resident tables.

    Construction materializes every table of ``program`` (the
    ``_distributed_tables`` metadata the ``embedding(is_distributed=
    True)`` layer records) onto ``mesh``, row-sharded over ``axis``.
    ``optimizer``/``lr`` select the push-side update kernel — the same
    server-side semantics the PS applies (``sgd`` | ``adagrad``), so a
    program can move between the two backends without retuning.

    ``initializer="zeros"`` is bit-exact with a zero-initialized PS
    table (the parity configuration); ``"uniform"`` draws one seeded
    uniform(-0.05, 0.05) table up front — deterministic, but NOT
    row-parity with the PS's lazy per-id init order.
    """

    _OPTIMIZERS = ("sgd", "adagrad")

    def __init__(self, program, mesh, axis: str,
                 optimizer: str = "sgd", lr: float = 0.1,
                 initializer: str = "zeros", seed: int = 0,
                 row_dtype: str = "fp32"):
        if optimizer not in self._OPTIMIZERS:
            raise ValueError(
                "mesh-table optimizer %r not in %s"
                % (optimizer, self._OPTIMIZERS))
        if axis not in mesh.axis_names:
            raise ValueError(
                "mesh has no axis %r (axes: %s)"
                % (axis, list(mesh.axis_names)))
        metas = getattr(program, "_distributed_tables", None)
        if not metas:
            raise ValueError("program has no distributed lookup tables")
        self.mesh = mesh
        self.axis = axis
        self.optimizer = optimizer
        self.row_dtype = normalize_row_dtype(row_dtype)
        self.lr = float(lr)
        self.n_shards = int(dict(
            zip(mesh.axis_names, mesh.devices.shape))[axis])
        self.tables: Dict[str, MeshTable] = {}
        self.compiles = 0  # lookup/push executables built (recompile truth)
        self.lookups = 0
        self.pushes = 0
        self._fns: Dict[Any, Any] = {}  # (kind, table, bucket) -> jitted
        self._lock = threading.Lock()
        rng = np.random.RandomState(seed)
        seen = set()
        for meta in metas.values():
            name = meta["table"]
            if name in seen:  # tied embeddings share one table
                continue
            seen.add(name)
            self._materialize(name, int(meta["height"]), int(meta["dim"]),
                              initializer, rng)

    # ------------------------------------------------------------------
    def __contains__(self, table: str) -> bool:
        return table in self.tables

    def _materialize(self, name: str, height: int, dim: int,
                     initializer: str, rng) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        padded = -(-height // self.n_shards) * self.n_shards
        if padded >= 1 << 31:
            # lookup/push route ids as int32 on-device; a larger table
            # would silently wrap ids to the wrong shard row
            raise ValueError(
                "mesh table %r height %d exceeds the int32 id-routing "
                "range (2^31-1); shard across more meshes or keep it "
                "on the PS" % (name, height))
        if initializer == "zeros":
            host = np.zeros((padded, dim), np.float32)
        elif initializer == "uniform":
            host = rng.uniform(-0.05, 0.05, (padded, dim)).astype(np.float32)
        else:
            raise ValueError(
                "mesh-table initializer %r not in ('zeros', 'uniform')"
                % initializer)
        sh = NamedSharding(self.mesh, P(self.axis, None))
        scales = None
        if self.row_dtype == "int8":
            from paddle_tpu.quant import INT8_SCALE_FLOOR

            # host-side mirror of quant.quantize_rows (np.rint rounds
            # half-to-even like jnp.round, so the device push kernels
            # round-trip these exact codes)
            hs = np.maximum(
                np.max(np.abs(host), axis=1) / 127.0,
                INT8_SCALE_FLOOR).astype(np.float32)
            host = np.clip(np.rint(host / hs[:, None]),
                           -127, 127).astype(np.int8)
            scales = jax.device_put(
                hs, NamedSharding(self.mesh, P(self.axis)))
        arr = jax.device_put(host, sh)
        moments = None
        if self.optimizer == "adagrad":
            moments = jax.device_put(np.zeros((padded, dim), np.float32), sh)
        tbl = MeshTable(name, dim, height, padded, padded // self.n_shards,
                        arr, moments, row_dtype=self.row_dtype,
                        scales=scales)
        self.tables[name] = tbl
        _sh_metrics.SPARSE_TABLE_BYTES.labels(table=name).set(
            tbl.bytes_per_device())
        _sh_metrics.SPARSE_ROW_DTYPE.labels(
            table=name, dtype=self.row_dtype).set(1)

    # ------------------------------------------------------------------
    # Executable builders: one per (table, bucket) — warmup() walks the
    # ladder so steady-state traffic never compiles.
    # ------------------------------------------------------------------
    def _fn(self, kind: str, table: str, bucket: int):
        key = (kind, table, int(bucket))
        fn = self._fns.get(key)
        if fn is None:
            with self._lock:
                fn = self._fns.get(key)
                if fn is None:
                    build = (self._build_lookup if kind == "lookup"
                             else self._build_push)
                    fn = self._fns[key] = build(self.tables[table])
                    self.compiles += 1
        return fn

    def _build_lookup(self, tbl: MeshTable):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        rps = tbl.rows_per_shard

        if tbl.scales is not None:
            from paddle_tpu.quant import dequantize_rows

            def local_lookup(shard, scales, ids):
                # int8 rung: dequantize AFTER the local gather, BEFORE
                # the psum — the table stores int8, the collective
                # moves (and the step consumes) fp32 rows
                lo = jax.lax.axis_index(axis) * rps
                local = ids - lo
                ok = (local >= 0) & (local < rps)
                safe = jnp.clip(local, 0, rps - 1)
                rows = jnp.where(
                    ok[:, None],
                    dequantize_rows(shard[safe], scales[safe]), 0.0)
                return jax.lax.psum(rows, axis)

            smapped = mesh_lib.shard_map(
                local_lookup, mesh=self.mesh,
                in_specs=(P(axis, None), P(axis), P()), out_specs=P())
            return jax.jit(smapped)

        def local_lookup(shard, ids):
            # id→shard routing: each shard gathers the rows it owns and
            # zeros the rest; the psum assembles full rows everywhere
            # (the all-to-all/psum pattern of parallel/hybrid.py)
            lo = jax.lax.axis_index(axis) * rps
            local = ids - lo
            ok = (local >= 0) & (local < rps)
            safe = jnp.clip(local, 0, rps - 1)
            rows = jnp.where(ok[:, None], shard[safe], 0.0)
            return jax.lax.psum(rows, axis)

        smapped = mesh_lib.shard_map(
            local_lookup, mesh=self.mesh,
            in_specs=(P(axis, None), P()), out_specs=P())
        return jax.jit(smapped)

    def _build_push(self, tbl: MeshTable):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        axis = self.axis
        rps = tbl.rows_per_shard
        lr = self.lr
        adagrad = self.optimizer == "adagrad"
        int8_rows = tbl.scales is not None

        def route(ids):
            # shard-wise routing, shared by both kernels: ids the shard
            # doesn't own scatter a zero (clip + mask), so each row
            # updates exactly once mesh-wide.  Padding dups (the
            # bucketed-unique trick repeats ids[0]) carry zero grads —
            # their scatter-add is a no-op, same as the PS.
            lo = jax.lax.axis_index(axis) * rps
            local = ids - lo
            ok = (local >= 0) & (local < rps)
            return ok, jnp.clip(local, 0, rps - 1)

        if int8_rows:
            from paddle_tpu.quant import dequantize_rows, quantize_rows

            # The int8 push is a row-SET, not a scatter-add: the update
            # must re-quantize whole rows (codes AND scale change
            # together).  An ``at[].set`` with duplicate indexes —
            # bucket-padding dups, clipped foreign ids — is only
            # deterministic when every colliding lane writes identical
            # bytes, so grads are first aggregated per TARGET row
            # (``same @ g``: lanes routed to one row all see the row's
            # total grad).  Lanes whose row took no grad write
            # ``requantize(dequantize(row))``, exact-identity by the
            # quantizer's fixed-point property — untouched rows keep
            # their bytes.
            if adagrad:
                def local_push(shard, scales, mom, ids, grads):
                    ok, safe = route(ids)
                    g = jnp.where(ok[:, None], grads, 0.0)
                    same = (safe[:, None] == safe[None, :]).astype(g.dtype)
                    m_row = mom[safe] + same @ (g * g)
                    mom = mom.at[safe].set(m_row)
                    g_row = same @ g
                    base = dequantize_rows(shard[safe], scales[safe])
                    nq, ns = quantize_rows(
                        base - lr * g_row / (jnp.sqrt(m_row) + 1e-6))
                    return (shard.at[safe].set(nq),
                            scales.at[safe].set(ns), mom)

                in_specs = (P(axis, None), P(axis), P(axis, None),
                            P(), P())
                out_specs = (P(axis, None), P(axis), P(axis, None))
                donate_args = (0, 1, 2)
            else:
                def local_push(shard, scales, ids, grads):
                    ok, safe = route(ids)
                    g = jnp.where(ok[:, None], grads, 0.0)
                    same = (safe[:, None] == safe[None, :]).astype(g.dtype)
                    g_row = same @ g
                    base = dequantize_rows(shard[safe], scales[safe])
                    nq, ns = quantize_rows(base - lr * g_row)
                    return shard.at[safe].set(nq), scales.at[safe].set(ns)

                in_specs = (P(axis, None), P(axis), P(), P())
                out_specs = (P(axis, None), P(axis))
                donate_args = (0, 1)
        elif adagrad:
            def local_push(shard, mom, ids, grads):
                # numerically ps._Table.push adagrad: m += g*g;
                # row -= lr*g/(sqrt(m)+1e-6), per unique id
                ok, safe = route(ids)
                g = jnp.where(ok[:, None], grads, 0.0)
                mom = mom.at[safe].add(g * g)
                denom = jnp.sqrt(mom[safe]) + 1e-6
                shard = shard.at[safe].add(
                    jnp.where(ok[:, None], -lr * g / denom, 0.0))
                return shard, mom

            in_specs = (P(axis, None), P(axis, None), P(), P())
            out_specs = (P(axis, None), P(axis, None))
            donate_args = (0, 1)
        else:
            def local_push(shard, ids, grads):
                # numerically ps._Table.push sgd: row -= lr*g
                ok, safe = route(ids)
                g = jnp.where(ok[:, None], grads, 0.0)
                return shard.at[safe].add(-lr * g)

            in_specs = (P(axis, None), P(), P())
            out_specs = P(axis, None)
            donate_args = (0,)

        smapped = mesh_lib.shard_map(
            local_push, mesh=self.mesh,
            in_specs=in_specs, out_specs=out_specs)
        from paddle_tpu.executor import _donate_kwargs

        # donate the table/moment buffers so the update is in-place in
        # HBM (skipped on CPU — the persistent-cache aliasing hazard,
        # see executor._donate_kwargs)
        donate = _donate_kwargs(self.mesh.devices.flat[0])
        kwargs = ({"donate_argnums": donate_args} if donate else {})
        return jax.jit(smapped, **kwargs)

    # ------------------------------------------------------------------
    # hot-path: begin sparse_lookup (bucketed device gather + shard-wise
    # push dispatch; fn lookup is a dict hit after warmup and the jitted
    # calls are async — no blocking device sync in this region)
    def lookup(self, table: str, uniq_ids) -> Any:
        """Rows for the (bucketed) unique ids: [len(ids), dim] device
        array, replicated over the mesh — feed it straight into the
        compiled step (zero host round-trip)."""
        import jax.numpy as jnp

        tbl = self.tables[table]
        ids = jnp.asarray(uniq_ids, jnp.int32).reshape(-1)  # hot-ok: device-side cast, not a host sync
        fn = self._fn("lookup", table, ids.shape[0])
        self.lookups += 1
        _sh_metrics.SPARSE_LOOKUPS.inc()
        if tbl.scales is not None:
            return fn(tbl.array, tbl.scales, ids)
        return fn(tbl.array, ids)

    def push(self, table: str, uniq_ids, grads) -> None:
        """Apply the (bucketed) unique-id grads shard-wise with the
        bound optimizer.  ``grads`` may be a device array (the fetched
        rows-grad tail) — it never touches the host."""
        import jax.numpy as jnp

        tbl = self.tables[table]
        ids = jnp.asarray(uniq_ids, jnp.int32).reshape(-1)  # hot-ok: device-side cast, not a host sync
        fn = self._fn("push", table, ids.shape[0])
        if tbl.scales is not None:
            if tbl.moments is not None:
                tbl.array, tbl.scales, tbl.moments = fn(
                    tbl.array, tbl.scales, tbl.moments, ids, grads)
            else:
                tbl.array, tbl.scales = fn(
                    tbl.array, tbl.scales, ids, grads)
        elif tbl.moments is not None:
            tbl.array, tbl.moments = fn(tbl.array, tbl.moments, ids, grads)
        else:
            tbl.array = fn(tbl.array, ids, grads)
        self.pushes += 1
    # hot-path: end sparse_lookup

    # ------------------------------------------------------------------
    def warmup(self, buckets: Sequence[int], train: bool = True) -> int:
        """Pre-build lookup (and push, for training) executables for
        every table x bucket rung.  Returns the number of executables
        compiled; after this, traffic whose unique counts bucket into
        the ladder pays ZERO compiles (assert on ``compiles``)."""
        import jax

        before = self.compiles
        for name, tbl in self.tables.items():
            for b in sorted({int(b) for b in buckets}):
                rows = self.lookup(name, np.zeros(b, np.int64))
                jax.block_until_ready(rows)
                if train:
                    self.push(name, np.zeros(b, np.int64),
                              np.zeros((b, tbl.dim), np.float32))
        return self.compiles - before

    # ------------------------------------------------------------------
    def rows(self, table: str, ids) -> np.ndarray:
        """Host copy of specific rows (tests/checkpoint tooling; NOT the
        serving path — this one syncs)."""
        return np.asarray(self.lookup(table, np.asarray(ids)))

    # ------------------------------------------------------------------
    # checkpoint surface: the sharded row/moment arrays ride
    # TrainCheckpoint's shards/ path like any mesh-committed persistable
    # (paddle_tpu.faults.checkpoint gathers/restores through these two)
    # ------------------------------------------------------------------
    def checkpoint_state(self) -> Dict[str, Dict[str, Any]]:
        """``{entry name: {table, kind, array, height}}`` — every device
        array the runtime owns, named for a checkpoint manifest: the row
        array under the table's own name (kind ``mesh_table``) and the
        optimizer moments under ``<table>#moments`` (kind
        ``mesh_table_moments``).  Arrays are PADDED to the shard grid;
        ``height`` is the real row count — rows past it are never read
        by a lookup, so a restore may zero-fill them.  int8 tables add
        their per-row scales under ``<table>#scales`` (kind
        ``mesh_table_scales``): codes without scales decode to garbage,
        so the pair checkpoints and restores together."""
        out: Dict[str, Dict[str, Any]] = {}
        for name, tbl in sorted(self.tables.items()):
            out[name] = {"table": name, "kind": "mesh_table",
                         "array": tbl.array, "height": tbl.height}
            if tbl.scales is not None:
                out[name + "#scales"] = {
                    "table": name, "kind": "mesh_table_scales",
                    "array": tbl.scales, "height": tbl.height}
            if tbl.moments is not None:
                out[name + "#moments"] = {
                    "table": name, "kind": "mesh_table_moments",
                    "array": tbl.moments, "height": tbl.height}
        return out

    def install_state(self, table: str, kind: str, array) -> None:
        """Swap in a restored device array for ``table``'s rows or
        moments.  The array must already be placed with the table's own
        sharding/shape (the checkpoint restore re-places shard-wise onto
        this runtime's mesh before calling)."""
        tbl = self.tables[table]
        if kind == "mesh_table":
            target = tbl.array
        elif kind == "mesh_table_moments":
            target = tbl.moments
        elif kind == "mesh_table_scales":
            target = tbl.scales
        else:
            raise ValueError("unknown mesh-table state kind %r" % kind)
        if target is None:
            raise ValueError(
                "restored %s for table %r but the runtime holds no such "
                "state (row_dtype=%r, optimizer=%r)"
                % (kind, table, tbl.row_dtype, self.optimizer))
        if tuple(array.shape) != tuple(target.shape):
            raise ValueError(
                "restored %s for table %r has shape %s but the runtime "
                "holds %s" % (kind, table, tuple(array.shape),
                              tuple(target.shape)))
        if np.dtype(array.dtype) != np.dtype(target.dtype):
            raise ValueError(
                "restored %s for table %r has dtype %s but the runtime "
                "holds %s — the checkpoint was written under a "
                "different row_dtype; rebind with the matching one"
                % (kind, table, np.dtype(array.dtype),
                   np.dtype(target.dtype)))
        if kind == "mesh_table":
            tbl.array = array
        elif kind == "mesh_table_moments":
            tbl.moments = array
        else:
            tbl.scales = array

    def stats(self) -> Dict[str, Any]:
        return {
            "n_shards": self.n_shards,
            "axis": self.axis,
            "optimizer": self.optimizer,
            "row_dtype": self.row_dtype,
            "compiles": self.compiles,
            "lookups": self.lookups,
            "pushes": self.pushes,
            "tables": {
                name: {
                    "height": t.height,
                    "dim": t.dim,
                    "row_dtype": t.row_dtype,
                    "bytes_per_device": t.bytes_per_device(),
                    "replicated_bytes": t.replicated_bytes(),
                }
                for name, t in self.tables.items()
            },
        }

    def close(self) -> None:
        """Retire the per-table gauge series and drop the device state."""
        for name, tbl in self.tables.items():
            _sh_metrics.SPARSE_TABLE_BYTES.remove_labels(table=name)
            _sh_metrics.SPARSE_ROW_DTYPE.remove_labels(
                table=name, dtype=tbl.row_dtype)
        self.tables.clear()
        self._fns.clear()


def bind_mesh_tables(compiled, axis: Optional[str] = None,
                     optimizer: str = "sgd", lr: float = 0.1,
                     initializer: str = "zeros",
                     seed: int = 0,
                     row_dtype: str = "fp32") -> MeshTableRuntime:
    """Materialize ``compiled``'s distributed lookup tables ON its mesh,
    row-sharded over ``axis`` (default: the mesh's first axis), and
    attach the runtime so the executor's prefetch path routes every
    bound table through device-side gathers instead of host PS pulls.

    Requires a ``CompiledProgram``: the lookup results are
    mesh-replicated device arrays, which only a jit bound to the SAME
    mesh can consume — running the program uncompiled afterwards is a
    typed error at prefetch time, not a jax device mismatch.  The rows
    feed is registered mesh-REPLICATED (its leading dim is unique ids,
    not batch), while the id/label feeds keep the normal batch
    sharding.  Returns the runtime (also at ``program._mesh_tables``).

    ``row_dtype="int8"`` stores rows quantized (per-row absmax scales)
    for ~4x fewer table bytes per device — lookups still hand the step
    fp32 rows, so the consuming program is unchanged.
    """
    if not getattr(compiled, "_is_compiled_program", False):
        raise ValueError(
            "bind_mesh_tables needs a CompiledProgram (the mesh the "
            "tables shard over is the one the step runs on); wrap the "
            "program with CompiledProgram(prog).with_mesh(...) first")
    program = compiled._program
    mesh = compiled.mesh  # the tables MUST live where the step runs
    axis = axis or mesh.axis_names[0]
    runtime = MeshTableRuntime(
        program, mesh, axis, optimizer=optimizer, lr=lr,
        initializer=initializer, seed=seed, row_dtype=row_dtype)
    program._mesh_tables = runtime
    # the prefetched-rows feeds replicate (leading dim = unique ids);
    # everything else keeps the compiled program's batch sharding
    replicated = getattr(compiled, "_replicated_feeds", None)
    if replicated is None:
        replicated = compiled._replicated_feeds = set()
    for meta in program._distributed_tables.values():
        replicated.add(meta["rows_name"])
    return runtime
