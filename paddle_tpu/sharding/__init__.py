"""paddle_tpu.sharding — declarative partition rules for model-parallel
serving and training.

The GSPMD-tradition surface (regex rules over parameter names →
``PartitionSpec``s) that lets ONE predictor span a tensor/FSDP-sharded
mesh instead of replicating every parameter per chip:

* :mod:`paddle_tpu.sharding.rules` — :class:`PartitionRules` (ordered
  first-match rule sets, typed errors, JSON manifest round-trip),
* :mod:`paddle_tpu.sharding.layouts` — canonical ``tp`` / ``fsdp`` /
  ``fsdp_tp`` layouts for the in-tree model families (transformer LM,
  NMT seq2seq, DeepFM), coverage-checked against the real models by
  ``tools/check_partition_rules.py``,
* :mod:`paddle_tpu.sharding.train` — the same rules pointed at a TRAIN
  program: :class:`TrainPartitionRules` derives every optimizer
  accumulator's spec from its param's matched rule, so params, grads,
  and optimizer state all live sharded (FSDP/tp training with zero new
  concepts),
* :mod:`paddle_tpu.sharding.sparse` — mesh-RESIDENT sparse tables:
  a distributed lookup table living row-sharded on the mesh, with
  device-side gather lookups (shard-routed psum) and shard-wise grad
  pushes replacing the host PS round-trip
  (:func:`bind_mesh_tables` on a ``CompiledProgram``),
* :mod:`paddle_tpu.sharding.metrics` — placement observability
  (imported lazily by the placement path; import it explicitly for the
  registry series).

Entry points: ``CompiledProgram.with_sharding_rules(rules, ...)``
(paddle_tpu/parallel/compiled_program.py),
``save_inference_model(..., sharding_rules=..., sharding_mesh=...)``
(paddle_tpu/io.py), and ``AnalysisPredictor`` which reconstructs the
saved layout automatically on load (paddle_tpu/inference.py).
"""
from paddle_tpu.sharding.layouts import (
    AXIS_FSDP,
    AXIS_TP,
    FAMILIES,
    MODES,
    canonical_rules,
    deepfm_rules,
    transformer_lm_rules,
    transformer_nmt_rules,
)
from paddle_tpu.sharding.rules import (
    MeshCommittedStateError,
    PartitionRules,
    ShardingRuleError,
)
from paddle_tpu.sharding.sparse import (
    MeshTableRuntime,
    bind_mesh_tables,
)
from paddle_tpu.sharding.train import (
    TrainPartitionRules,
    sharded_train_program,
    train_rules,
)

__all__ = [
    "PartitionRules",
    "ShardingRuleError",
    "MeshCommittedStateError",
    "TrainPartitionRules",
    "train_rules",
    "sharded_train_program",
    "canonical_rules",
    "transformer_lm_rules",
    "transformer_nmt_rules",
    "deepfm_rules",
    "AXIS_TP",
    "AXIS_FSDP",
    "MODES",
    "FAMILIES",
    "MeshTableRuntime",
    "bind_mesh_tables",
]
