"""Activation sharding: trace-time ``with_sharding_constraint`` placement.

Param rules place persistable state at restage time (device_put with a
NamedSharding); activation rules have no array to place — they bind
INSIDE the traced computation.  This module is that binding: a
:class:`ActivationConstrainer` built by the CompiledProgram from its
rule set + mesh, installed as a thread-local context around the block
trace (executor wraps the lowered fn), and consulted by
``core.lowering.trace_ops`` for every op output it writes.  A matched
intermediate gets ``jax.lax.with_sharding_constraint`` applied; an
unmatched one is left for GSPMD propagation.

The constrainer also keeps the books: per-name full vs per-device
nbytes of every constrained intermediate, accumulated into a report the
predictor's ``sharding_stats()`` reads — the "activation bytes/device"
number long-context capacity math needs (a 1/sp fraction of the
unsharded footprint when the seq axis shards over sp).

Ops that want to SPECIALIZE under an activation layout (the fused
attention op dispatching to ring attention over the sp axis) read the
installed context via :func:`current` — trace-time only, never on the
steady dispatch path.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Optional

__all__ = ["ActivationConstrainer", "tracing", "current"]

_TLS = threading.local()


def current() -> Optional["ActivationConstrainer"]:
    """The ActivationConstrainer installed on this thread (trace time
    only), or None."""
    return getattr(_TLS, "ctx", None)


@contextmanager
def tracing(ctx: Optional["ActivationConstrainer"]):
    """Install ``ctx`` for the duration of a block trace."""
    prev = getattr(_TLS, "ctx", None)
    _TLS.ctx = ctx
    try:
        yield ctx
    finally:
        _TLS.ctx = prev


class ActivationConstrainer:
    """Applies a rule set's activation specs during tracing.

    ``rules``: a PartitionRules carrying activation rules; ``mesh``: the
    jax Mesh the specs bind to; ``axis_sizes``: {axis: size} for the
    divisibility guard.  Resolution is memoized per (name, shape tuple)
    — auto-generated intermediate names repeat across jit keys, and the
    regex scan must not re-run per trace.
    """

    def __init__(self, rules, mesh, axis_sizes: Dict[str, int]):
        self.rules = rules
        self.mesh = mesh
        self.axis_sizes = {str(a): int(n) for a, n in dict(axis_sizes).items()}
        # largest axis group any activation rule shards the seq dim over
        # — the divisor serving lengths must honor (len-ladder rounding)
        self._memo: Dict[Any, Any] = {}
        # name -> (full_nbytes, per_device_nbytes) for every constrained
        # intermediate of the LAST trace (one serve program traces the
        # same set per jit key; last-trace-wins keeps the report sized
        # to one executable, not the sum over warmup rungs)
        self.report: Dict[str, tuple] = {}
        self._trace_report: Dict[str, tuple] = {}

    # the sp axis name, if any activation rule shards over exactly one
    # axis named "sp" (the canonical layout) — what the fused attention
    # op asks for to pick the ring path
    @property
    def sp_axis(self) -> Optional[str]:
        from paddle_tpu.sharding.layouts import AXIS_SP

        if AXIS_SP in self.axis_sizes and self.axis_sizes[AXIS_SP] > 1:
            return AXIS_SP
        return None

    def begin_trace(self) -> None:
        self._trace_report = {}

    def end_trace(self) -> None:
        if self._trace_report:
            self.report = dict(self._trace_report)

    def _shard_factor(self, spec, shape) -> int:
        """Total device count the spec splits ``shape`` over, or 0 when
        a sharded dim is not divisible (→ skip the constraint)."""
        k = 1
        for dim, entry in zip(shape, tuple(spec)):
            if entry is None:
                continue
            axes = (entry,) if isinstance(entry, str) else tuple(entry)
            f = 1
            for a in axes:
                f *= self.axis_sizes.get(a, 1)
            if f > 1:
                if int(dim) % f:
                    return 0
                k *= f
        return k

    # hot-path: begin activation_constrain (runs under jit TRACING — the
    # first dispatch of a cache key, inside the executor's dispatch
    # region.  Pure spec resolution + with_sharding_constraint emission:
    # a blocking sync here would stall every novel-shape warmup)
    def constrain(self, name: str, value):
        """Apply the rule set's constraint for ``name`` to ``value`` (a
        traced array), or return it untouched."""
        shape = getattr(value, "shape", None)
        if shape is None:
            return value
        key = (name, tuple(shape))
        hit = self._memo.get(key, _MISS)
        if hit is _MISS:
            hit = None
            spec = self.rules.activation_spec_for(name, shape=shape)
            if spec is not None:
                k = self._shard_factor(spec, shape)
                if k > 1:
                    from jax.sharding import NamedSharding

                    hit = (NamedSharding(self.mesh, spec), k)
            self._memo[key] = hit
        if hit is None:
            return value
        sharding, k = hit
        import jax
        import numpy as np

        full = int(np.prod(shape)) * value.dtype.itemsize
        self._trace_report[name] = (full, full // k)
        return jax.lax.with_sharding_constraint(value, sharding)
    # hot-path: end activation_constrain

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        """Aggregate bytes of the last traced program's constrained
        intermediates: {'activation_bytes_unsharded', 'activation_bytes
        _per_device', 'n_constrained'}."""
        full = sum(f for f, _ in self.report.values())
        per_dev = sum(p for _, p in self.report.values())
        return {
            "activation_bytes_unsharded": int(full),
            "activation_bytes_per_device": int(per_dev),
            "n_constrained": len(self.report),
        }


_MISS = object()
