"""Declarative partition rules: ordered ``(regex, PartitionSpec)`` pairs.

The GSPMD-tradition sharding surface (Xu et al., "GSPMD: General and
Scalable Parallelization for ML Computation Graphs"): instead of
annotating every parameter by hand, a model family declares an ORDERED
list of rules matched against parameter *names* — the pjit
partition-rule pattern (``match_partition_rules`` over regexes; the
reference idiom behind every large JAX LM trainer).  Semantics:

* rules are tried in order; the FIRST pattern whose ``re.search``
  matches the name wins (an unanchored pattern is substring semantics;
  anchor with ``^``/``$`` for exact-name rules),
* scalar / single-element parameters are never partitioned (they get
  ``PartitionSpec()`` without consuming a rule),
* a parameter no rule matches is a typed ``ShardingRuleError`` naming
  it — unless the rule set carries a ``default=`` spec,
* a spec whose rank exceeds the parameter's rank is rejected HERE, at
  rule-resolve time, as a typed error — not three layers down as an
  XLA shape error.

Rule sets serialize to a JSON-safe manifest (``to_manifest`` /
``from_manifest``) so ``save_inference_model`` can carry the layout
with the weights and a serving child reconstructs the same placement
(paddle_tpu/io.py, paddle_tpu/inference.py).

ACTIVATION rules (``activations=``) are a second ordered rule list over
*intermediate* var names — the ``with_sharding_constraint`` placement
surface (sequence-parallel serving shards activations, not params).
Their semantics differ from param rules in one load-bearing way: an
unmatched activation resolves to ``activation_default``, which is
``None`` by default and means **no constraint at all** (GSPMD
propagation decides) — never silent replication.  A ``PartitionSpec()``
default would pin every intermediate replicated and defeat the sharding
the matched rules ask for.
"""
from __future__ import annotations

import re
from typing import Any, Dict, Iterable, Mapping, Optional, Sequence, Tuple

__all__ = [
    "PartitionRules",
    "ShardingRuleError",
    "MeshCommittedStateError",
    "spec_to_manifest",
    "spec_from_manifest",
]


class ShardingRuleError(ValueError):
    """A partition-rule problem caught at rule-resolve time: an
    unmatched parameter, a spec whose rank exceeds the parameter's,
    a mesh missing a rule's axis, or a malformed manifest."""


class MeshCommittedStateError(RuntimeError):
    """Scope state is committed to a device mesh by a previous
    *compiled* run, and an *uncompiled* ``Executor.run`` would feed it
    into a single-device jit — the failure would otherwise surface as
    an inscrutable device-mismatch deep inside jax.  Re-run with the
    CompiledProgram, or opt into reshard-on-gather
    (``Executor(reshard_on_gather=True)`` /
    ``PADDLE_TPU_RESHARD_ON_GATHER=1``) to pull the state back to host
    once."""


def _partition_spec_cls():
    from jax.sharding import PartitionSpec

    return PartitionSpec


def _as_spec(spec):
    """Coerce ``spec`` (PartitionSpec | sequence of entries | None) to a
    PartitionSpec.  Entries are ``None`` (replicated dim), an axis name,
    or a tuple of axis names (a dim sharded over several axes)."""
    P = _partition_spec_cls()
    if spec is None:
        return P()
    if isinstance(spec, P):
        return spec
    if isinstance(spec, str):
        raise ShardingRuleError(
            "partition spec %r is a bare string — pass PartitionSpec(%r) "
            "or a sequence of dim entries" % (spec, spec))
    entries = []
    for e in spec:
        if e is None or isinstance(e, str):
            entries.append(e)
        elif isinstance(e, (list, tuple)):
            entries.append(tuple(str(a) for a in e))
        else:
            raise ShardingRuleError(
                "partition spec entry %r: expected None, an axis name, "
                "or a tuple of axis names" % (e,))
    return P(*entries)


def spec_to_manifest(spec) -> list:
    """JSON-safe form of a PartitionSpec: a list whose entries are
    ``None``, an axis-name string, or a list of axis names."""
    out = []
    for e in tuple(spec):
        if e is None or isinstance(e, str):
            out.append(e)
        else:
            out.append([str(a) for a in e])
    return out


def spec_from_manifest(doc) -> Any:
    return _as_spec(doc)


def _shape_of(leaf) -> Optional[Tuple[int, ...]]:
    shape = getattr(leaf, "shape", None)
    if shape is None and isinstance(leaf, (tuple, list)):
        shape = leaf
    if shape is None:
        return None
    return tuple(int(d) for d in shape)


def _n_elements(shape: Sequence[int]) -> int:
    n = 1
    for d in shape:
        n *= int(d)
    return n


class PartitionRules:
    """An ordered first-match-wins rule set over parameter names.

    ``rules``: sequence of ``(pattern, spec)`` where ``pattern`` is a
    regex matched with ``re.search`` and ``spec`` is a PartitionSpec
    (or a sequence of dim entries).  ``default``: the spec unmatched
    parameters fall back to; with no default an unmatched parameter is
    a typed :class:`ShardingRuleError`.
    """

    def __init__(self, rules: Iterable[Tuple[str, Any]], default=None,
                 name: str = "rules", activations: Iterable[Tuple[str, Any]] = (),
                 activation_default=None):
        self.name = str(name)
        self.rules: Tuple[Tuple[str, Any], ...] = tuple(
            (str(pat), _as_spec(spec)) for pat, spec in rules)
        self._compiled = tuple(
            (re.compile(pat), spec) for pat, spec in self.rules)
        self.default = _as_spec(default) if default is not None else None
        # activation (intermediate-var) rules: same first-match-wins
        # grammar, but the fallback is None = NO constraint (see module
        # docstring) — P() here would force replication
        self.activations: Tuple[Tuple[str, Any], ...] = tuple(
            (str(pat), _as_spec(spec)) for pat, spec in activations)
        self._act_compiled = tuple(
            (re.compile(pat), spec) for pat, spec in self.activations)
        self.activation_default = (_as_spec(activation_default)
                                   if activation_default is not None else None)
        if not self.rules and self.default is None:
            raise ShardingRuleError(
                "empty rule set %r with no default spec" % self.name)

    # ------------------------------------------------------------------
    def with_default(self, default) -> "PartitionRules":
        """A copy of this rule set with ``default`` as the unmatched-name
        fallback spec.  Subclasses override so a rebuild keeps their
        extra state (TrainPartitionRules' accumulator map)."""
        return PartitionRules(self.rules, default=default, name=self.name,
                              activations=self.activations,
                              activation_default=self.activation_default)

    def axes(self) -> set:
        """Every mesh axis name any rule (or the default) refers to —
        activation rules included, so ``validate_mesh`` catches a
        missing ``sp`` axis at bind time, not as an XLA unbound-axis
        failure inside the first traced constraint."""
        out: set = set()
        specs = [spec for _, spec in self.rules]
        specs.extend(spec for _, spec in self.activations)
        if self.default is not None:
            specs.append(self.default)
        if self.activation_default is not None:
            specs.append(self.activation_default)
        for spec in specs:
            for e in tuple(spec):
                if e is None:
                    continue
                if isinstance(e, str):
                    out.add(e)
                else:
                    out.update(e)
        return out

    # hot-path: begin rule_resolve (called from the compiled program's
    # state-sharding memo MISS path — once per name, but that miss
    # happens inside the dispatch region, so resolution itself must
    # never grow a blocking sync or a sleep)
    def _first_match(self, name: str):
        """(pattern, spec) of the first matching rule, or None."""
        for rx, spec in self._compiled:
            if rx.search(name) is not None:
                return rx.pattern, spec
        return None
    # hot-path: end rule_resolve

    @staticmethod
    def _check_rank(name: str, spec, shape: Sequence[int],
                    pattern: Optional[str]) -> None:
        if len(tuple(spec)) > len(shape):
            via = " (rule %r)" % pattern if pattern else " (default spec)"
            raise ShardingRuleError(
                "partition spec %s has rank %d but param %r has shape %s"
                "%s — spec rank must not exceed the param rank"
                % (tuple(spec), len(tuple(spec)), name, tuple(shape), via))

    # ------------------------------------------------------------------
    def spec_for(self, name: str, shape=None):
        """Resolve one parameter name to its PartitionSpec.

        ``shape`` (a shape sequence or an object with ``.shape``):
        enables the scalar short-circuit and the rank check; without it
        only name matching happens.  Raises :class:`ShardingRuleError`
        for an unmatched name (no ``default``) or a spec/param rank
        mismatch."""
        P = _partition_spec_cls()
        shp = _shape_of(shape) if shape is not None else None
        if shp is not None and (len(shp) == 0 or _n_elements(shp) == 1):
            return P()  # never partition scalars / single elements
        hit = self._first_match(name)
        if hit is not None:
            pattern, spec = hit
            if shp is not None:
                self._check_rank(name, spec, shp, pattern)
            return spec
        if self.default is not None:
            if shp is not None:
                self._check_rank(name, self.default, shp, None)
            return self.default
        raise ShardingRuleError(
            "no partition rule in %r matches param %r (tried %d rules, "
            "no default= spec given)"
            % (self.name, name, len(self.rules)))

    def match(self, params: Mapping[str, Any]) -> Dict[str, Any]:
        """Resolve every entry of ``{name: array-or-shape}`` to a spec
        pytree ``{name: PartitionSpec}``; first unmatched name (or rank
        mismatch) raises typed."""
        return {
            name: self.spec_for(name, shape=leaf)
            for name, leaf in params.items()
        }

    # hot-path: begin activation_resolve (resolution happens at jit
    # TRACE time — once per cache key, but tracing sits inside the first
    # dispatch of the executor's hot region, so it must stay pure regex
    # + dict work: no device sync, no sleeps)
    def activation_spec_for(self, name: str, shape=None):
        """Resolve one INTERMEDIATE var name to its PartitionSpec, or
        ``None`` for "no constraint" (unmatched and no
        ``activation_default``).  A spec whose rank exceeds the value's
        is resolved to None rather than raised: intermediates are
        auto-named and rule authors match families of them, so a
        low-rank straggler (a scalar scale, a [S] position vector)
        simply goes unconstrained."""
        hit = None
        for rx, spec in self._act_compiled:
            if rx.search(name) is not None:
                hit = spec
                break
        if hit is None:
            hit = self.activation_default
        if hit is None:
            return None
        shp = _shape_of(shape) if shape is not None else None
        if shp is not None and len(tuple(hit)) > len(shp):
            return None
        return hit
    # hot-path: end activation_resolve

    def dead_activation_rules(self, names: Iterable[str]) -> list:
        """Activation patterns matching NONE of ``names`` — same
        stale-cruft contract as :meth:`dead_rules`, checked by
        tools/check_partition_rules.py against the real program's
        intermediate var set."""
        names = list(names)
        out = []
        for rx, _ in self._act_compiled:
            if not any(rx.search(n) is not None for n in names):
                out.append(rx.pattern)
        return out

    def dead_rules(self, names: Iterable[str]) -> list:
        """Patterns that match NONE of ``names`` — a dead rule in a
        canonical layout is stale cruft that will rot (the
        check_partition_rules tool fails them)."""
        names = list(names)
        out = []
        for rx, _ in self._compiled:
            if not any(rx.search(n) is not None for n in names):
                out.append(rx.pattern)
        return out

    def validate_mesh(self, mesh) -> None:
        """Every axis the rules name must exist on ``mesh`` — caught
        here as a typed error instead of an XLA unbound-axis failure."""
        missing = sorted(self.axes() - set(mesh.axis_names))
        if missing:
            raise ShardingRuleError(
                "rule set %r shards over mesh axes %s which are not on "
                "the mesh (axes: %s)"
                % (self.name, missing, list(mesh.axis_names)))

    @staticmethod
    def check_divisible(name: str, spec, shape: Sequence[int],
                        axis_sizes: Mapping[str, int]) -> None:
        """Every sharded dim must divide by its axes' total size —
        jax.device_put rejects uneven shards with a raw ValueError deep
        in the loader; this names the param and rule-level cause."""
        for dim, entry in zip(shape, tuple(spec)):
            if entry is None:
                continue
            axes = (entry,) if isinstance(entry, str) else tuple(entry)
            k = 1
            for a in axes:
                k *= int(axis_sizes.get(a, 1))
            if k > 1 and int(dim) % k:
                raise ShardingRuleError(
                    "param %r dim of size %d is sharded over %s (total "
                    "%d devices) but is not divisible by it (shape %s, "
                    "spec %s)" % (name, int(dim), list(axes), k,
                                  tuple(shape), tuple(spec)))

    def validate_shapes(self, named_shapes: Mapping[str, Any],
                        axis_sizes: Mapping[str, int]) -> None:
        """Resolve every entry and check shard divisibility against the
        mesh axis sizes — the full fail-at-export bundle (coverage +
        rank + divisibility), all typed."""
        for name, leaf in named_shapes.items():
            shape = _shape_of(leaf)
            spec = self.spec_for(name, shape=leaf)
            if shape:
                self.check_divisible(name, spec, shape, axis_sizes)

    # ------------------------------------------------------------------
    def to_manifest(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "name": self.name,
            "rules": [[pat, spec_to_manifest(spec)]
                      for pat, spec in self.rules],
        }
        if self.default is not None:
            doc["default"] = spec_to_manifest(self.default)
        if self.activations:
            doc["activations"] = [[pat, spec_to_manifest(spec)]
                                  for pat, spec in self.activations]
        if self.activation_default is not None:
            doc["activation_default"] = spec_to_manifest(
                self.activation_default)
        return doc

    @classmethod
    def from_manifest(cls, doc: Mapping[str, Any]) -> "PartitionRules":
        try:
            rules = [(pat, spec_from_manifest(spec))
                     for pat, spec in doc["rules"]]
            acts = [(pat, spec_from_manifest(spec))
                    for pat, spec in doc.get("activations", [])]
        except (KeyError, TypeError, ValueError) as e:
            raise ShardingRuleError(
                "malformed partition-rules manifest: %r" % (doc,)) from e
        default = doc.get("default")
        act_default = doc.get("activation_default")
        return cls(rules,
                   default=spec_from_manifest(default)
                   if default is not None else None,
                   name=doc.get("name", "rules"),
                   activations=acts,
                   activation_default=spec_from_manifest(act_default)
                   if act_default is not None else None)

    # ------------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.rules)

    def __repr__(self) -> str:
        return "PartitionRules(%r, %d rules%s%s)" % (
            self.name, len(self.rules),
            ", default=%s" % (tuple(self.default),)
            if self.default is not None else "",
            ", %d activation rules" % len(self.activations)
            if self.activations else "")
