"""Device meshes and sharding helpers.

TPU-native replacement for the reference's device/communicator management:
`NCCLContextMap` / `NCCLCommunicator` flat + hierarchical rings
(reference: paddle/fluid/platform/nccl_helper.h:90,179) become a named
`jax.sharding.Mesh` over the chips; ring ids map to axis names
(parallel/env.py) and XLA GSPMD inserts the collectives that the reference
built manually as op-handles (details/all_reduce_op_handle.cc).

Axis conventions (the scaling-book layout):
  * ``dp``   — data parallel (batch dim). Rides ICI within a slice, DCN
               across slices (hierarchical allreduce analog,
               nccl_helper.h:179 — here just axis ordering in the mesh).
  * ``tp``   — tensor/model parallel (hidden dims of matmuls).
  * ``pp``   — pipeline stages.
  * ``sp``   — sequence/context parallel (ring attention).
  * ``ep``   — expert parallel (MoE / sharded embedding tables).
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

__all__ = ["make_mesh", "default_mesh", "data_parallel_mesh", "MeshGuard",
           "local_devices", "shard_map"]


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across jax versions: new jax exposes it at top
    level with ``check_vma``; older releases only ship
    ``jax.experimental.shard_map`` whose analogous knob is
    ``check_rep``.  On those pre-vma releases the check is forced OFF:
    without ``lax.pvary`` there is no way to annotate intentional
    replication, so ``check_rep=True`` rejects valid programs the new
    checker accepts (it is a static debugging aid, not semantics)."""
    import jax

    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        try:
            return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=check_vma)
        except TypeError:  # top-level alias predating the check_vma rename
            return fn(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)

_current_mesh = None


def local_devices(backend: Optional[str] = None):
    """Devices for mesh building. ``PADDLE_TPU_BACKEND`` overrides the jax
    default (the test suite sets it to ``cpu`` to get the 8-device virtual
    mesh while the process default backend is the real TPU)."""
    import os

    import jax

    backend = backend or os.environ.get("PADDLE_TPU_BACKEND") or None
    return jax.devices(backend) if backend else jax.devices()


def make_mesh(axes: Dict[str, int], devices=None, backend: Optional[str] = None):
    """Build a jax Mesh with named axes; sizes must multiply to #devices
    (or a divisor thereof — extra devices are left out)."""
    from jax.sharding import Mesh

    if devices is None:
        devices = local_devices(backend)
    sizes = list(axes.values())
    n = int(np.prod(sizes)) if sizes else 1
    if n > len(devices):
        raise ValueError(
            "mesh %r needs %d devices, have %d" % (axes, n, len(devices))
        )
    dev_array = np.array(devices[:n]).reshape(sizes)
    return Mesh(dev_array, tuple(axes.keys()))


def data_parallel_mesh(num_devices: Optional[int] = None, backend: Optional[str] = None):
    devs = local_devices(backend)
    if num_devices is not None:
        devs = devs[:num_devices]
    return make_mesh({"dp": len(devs)}, devs)


def default_mesh():
    """The mesh bound by MeshGuard, or a fresh all-devices dp mesh."""
    if _current_mesh is not None:
        return _current_mesh
    return data_parallel_mesh()


class MeshGuard:
    """Bind a mesh as the process-wide default (the reference's
    `ParallelExecutor` holding its NCCLContextMap for the run)."""

    def __init__(self, mesh):
        self.mesh = mesh
        self._prev = None

    def __enter__(self):
        global _current_mesh
        self._prev = _current_mesh
        _current_mesh = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        global _current_mesh
        _current_mesh = self._prev
        return False
