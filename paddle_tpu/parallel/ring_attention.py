"""Ring attention: sequence-parallel exact attention over an ``sp`` axis.

The reference has no sequence parallelism (SURVEY.md §2.10 — LoDTensor
ragged batching is its only long-sequence story); this module is the
TPU-native long-context mechanism the survey calls for: K/V blocks rotate
around the ring via `lax.ppermute` while each rank's queries accumulate
attention with an online (flash-style) running max / denominator — exact
softmax attention with O(seq/sp) memory per chip and comm overlapped with
compute by XLA.

Used by parallel/hybrid.py when ``ring_attention=True`` (default for
sp>1); standalone use:

    out = ring_attention(q, k, v, axis_name="sp", causal=True)

inside shard_map, where q/k/v are [batch, heads, t_local, d] sequence
shards in ring order.
"""
from __future__ import annotations

from typing import Optional

__all__ = ["ring_attention"]


def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = True, scale: Optional[float] = None):
    """Exact attention over ring-sharded sequences.

    q/k/v: [B, H, Tl, D] local shards (rank r holds tokens
    [r*Tl, (r+1)*Tl)).  Returns [B, H, Tl, D].
    """
    import jax
    import jax.numpy as jnp

    B, H, Tl, D = q.shape
    n = jax.lax.psum(1, axis_name)
    rank = jax.lax.axis_index(axis_name)
    if scale is None:
        scale = 1.0 / (D ** 0.5)

    q_pos = rank * Tl + jnp.arange(Tl)  # global positions of my queries

    neg = jnp.full((), -1e30, q.dtype)

    # hot-path: begin ring_step (the blockwise K/V-rotation body — traced
    # into every sp-serving executable; einsum/ppermute only, a host sync
    # or sleep here would land inside every long-context warmup trace)
    def block(carry, step):
        """Process the K/V block that started at rank (rank - step) % n."""
        acc, m, l, kb, vb = carry
        src = (rank - step) % n          # owner of this block
        k_pos = src * Tl + jnp.arange(Tl)
        s = jnp.einsum("bhqd,bhkd->bhqk", q, kb) * scale
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]   # [Tl, Tl]
            s = jnp.where(mask[None, None], s, neg)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # rescale previous accumulator, add this block
        p = jnp.exp(s - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum("bhqk,bhkd->bhqd", p, vb)
        # rotate K/V to the next rank
        perm = [(i, (i + 1) % n) for i in range(n)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        return (acc_new, m_new, l_new, kb, vb), None
    # hot-path: end ring_step

    # derive inits from q so they inherit its device-varying (vma) type —
    # a plain jnp.zeros carry would mismatch the scan body under shard_map
    acc0 = jnp.zeros_like(q)
    l0 = jnp.sum(jnp.zeros_like(q), axis=-1)
    m0 = l0 + neg
    (acc, m, l, _, _), _ = jax.lax.scan(
        block, (acc0, m0, l0, k, v), jnp.arange(n)
    )
    # rows with no valid key (can't happen when causal and diag included)
    l = jnp.maximum(l, 1e-20)
    return acc / l[..., None]
