"""BuildStrategy / ExecutionStrategy / DistributedStrategy parity objects.

Reference: paddle/fluid/framework/details/build_strategy.h and
python/paddle/fluid/incubate/fleet/collective/__init__.py:98.  Most of the
reference's knobs steer its hand-built pass pipeline (fuse allreduce,
hierarchical rings, memory reuse); under XLA those are compiler decisions,
so the fields are accepted for API parity — setting one after
construction WARNS that it is inert here — and the few that still mean
something (gradient sharding, microbatches, mesh shape, local SGD, DGC)
steer jit shardings / the transpilers instead.
"""
from __future__ import annotations

import warnings
from typing import Dict, Optional

__all__ = ["BuildStrategy", "ExecutionStrategy", "DistributedStrategy"]


class _WarnsOnInertKnobs:
    """Warn when a knob that XLA subsumes is explicitly set (round-1
    weakness: accepted-and-ignored silently)."""

    _INERT: frozenset = frozenset()
    _init_done = False

    def __setattr__(self, name, value):
        if self._init_done and name in self._INERT:
            warnings.warn(
                "%s.%s is accepted for fluid API parity but has no effect "
                "on TPU: XLA owns fusion/scheduling/memory decisions"
                % (type(self).__name__, name),
                stacklevel=2,
            )
        object.__setattr__(self, name, value)


class BuildStrategy(_WarnsOnInertKnobs):
    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    _INERT = frozenset({
        "fuse_elewise_add_act_ops", "fuse_all_reduce_ops",
        "fuse_all_optimizer_ops", "fuse_broadcast_ops", "memory_optimize",
        "enable_inplace", "enable_sequential_execution",
        "remove_unnecessary_lock", "use_hierarchical_allreduce",
        "hierarchical_allreduce_inter_nranks", "nccl_comm_num",
    })

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_elewise_add_act_ops = True  # XLA fuses regardless
        self.fuse_all_reduce_ops = True
        self.fuse_all_optimizer_ops = True
        self.fuse_broadcast_ops = True
        self.memory_optimize = True
        self.enable_inplace = True
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.nccl_comm_num = 1
        self._init_done = True


class ExecutionStrategy(_WarnsOnInertKnobs):
    class ExecutorType:
        Default = 0
        Experimental = 1

    _INERT = frozenset({
        "num_threads", "num_iteration_per_drop_scope",
        "use_thread_pool", "allow_op_delay",
    })

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.use_thread_pool = False
        self.allow_op_delay = False
        self._init_done = True


class DistributedStrategy(BuildStrategy):
    """Fleet collective-mode strategy (reference:
    incubate/fleet/collective/__init__.py:98) extended with the TPU mesh
    shape: axis name -> size. ``sharding_specs`` maps var names to
    PartitionSpec tuples for model-parallel params."""

    def __init__(self):
        super().__init__()
        # reopen: BuildStrategy.__init__ closed the init window
        object.__setattr__(self, "_init_done", False)
        self.mode = "collective"
        self.collective_mode = "grad_allreduce"  # or "local_sgd"
        self.local_sgd_steps = 1
        self.use_local_sgd = False
        self.use_dgc = False
        self.mesh_axes: Dict[str, int] = {}
        self.sharding_specs: Dict[str, tuple] = {}
        self.exec_strategy = ExecutionStrategy()
        self.use_amp = False
        self.num_microbatches = 1
        # 5D hybrid-parallel engine config (HybridConfig kwargs: dp/pp/tp/
        # sp/ep + model dims); consumed by
        # fleet.distributed_optimizer(...).build_hybrid_train_step()
        self.hybrid: Optional[Dict] = None
        self._init_done = True
