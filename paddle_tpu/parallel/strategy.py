"""BuildStrategy / ExecutionStrategy / DistributedStrategy parity objects.

Reference: paddle/fluid/framework/details/build_strategy.h and
python/paddle/fluid/incubate/fleet/collective/__init__.py:98.  Most of the
reference's knobs steer its hand-built pass pipeline (fuse allreduce,
hierarchical rings, memory reuse); under XLA those are compiler decisions,
so the fields are accepted for API parity and the few that still mean
something (gradient sharding, microbatches, mesh shape) steer jit
shardings instead.
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["BuildStrategy", "ExecutionStrategy", "DistributedStrategy"]


class BuildStrategy:
    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_elewise_add_act_ops = True  # XLA fuses regardless
        self.fuse_all_reduce_ops = True
        self.fuse_all_optimizer_ops = True
        self.fuse_broadcast_ops = True
        self.memory_optimize = True
        self.enable_inplace = True
        self.enable_sequential_execution = False
        self.remove_unnecessary_lock = True
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.use_hierarchical_allreduce = False
        self.hierarchical_allreduce_inter_nranks = 0
        self.nccl_comm_num = 1


class ExecutionStrategy:
    class ExecutorType:
        Default = 0
        Experimental = 1

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.num_iteration_per_run = 1
        self.use_thread_pool = False
        self.allow_op_delay = False


class DistributedStrategy(BuildStrategy):
    """Fleet collective-mode strategy (reference:
    incubate/fleet/collective/__init__.py:98) extended with the TPU mesh
    shape: axis name -> size. ``sharding_specs`` maps var names to
    PartitionSpec tuples for model-parallel params."""

    def __init__(self):
        super().__init__()
        self.mode = "collective"
        self.collective_mode = "grad_allreduce"  # or "local_sgd"
        self.local_sgd_steps = 1
        self.use_local_sgd = False
        self.use_dgc = False
        self.mesh_axes: Dict[str, int] = {}
        self.sharding_specs: Dict[str, tuple] = {}
        self.exec_strategy = ExecutionStrategy()
        self.use_amp = False
        self.num_microbatches = 1
