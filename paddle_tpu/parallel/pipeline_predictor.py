"""PipelinePredictor: micro-batched GPipe inference over the ``pp`` axis.

The serving analog of ``pipeline_program.build_pipeline_step``: the
pruned INFERENCE program is cut into K stages at single-crossing
activation boundaries (``propose_cut_vars`` picks balanced ones when the
caller doesn't), and one request batch runs as M micro-batches through a
compiled GPipe schedule — ``lax.scan`` over ``M + K - 1`` slots inside
``shard_map`` over a ``{"pp": K}`` mesh, ``lax.switch`` on the device's
stage coordinate, activations streaming stage-to-stage via
``lax.ppermute``.  The ppermute IS the double buffer: each slot's
hand-off is issued against the buffer the previous slot filled, and XLA
overlaps the send with the next slot's compute.

Serving contract (PR 10's sharded-group shape): a PipelinePredictor is
ONE replica behind ``InferenceServer`` — it duck-types the
``AnalysisPredictor`` surface the server consumes (``run_padded``,
``jit_cache_stats``, ``get_input_names``, ``input_specs``) and adds
``pipeline_stats()``: stage counts, the executed schedule's structural
bubble ratio ``(K-1)/(M+K-1)``, and per-stage occupancy ``M/(M+K-1)`` —
what the ``serving_pipeline_bubble_ratio`` / per-stage occupancy gauges
publish.

Micro-batch selection: the configured ``num_microbatches`` is a CAP.
For each padded batch B the schedule uses the largest divisor of B that
is <= the cap (deterministic per bucket rung, so the warmed compiled
shape set stays closed — the zero-recompile contract).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.parallel.pipeline_program import (
    PipelinePlanError,
    _stage_ranges,
    propose_cut_vars,
)

__all__ = ["PipelinePredictor"]


def _largest_divisor_leq(b: int, cap: int) -> int:
    for m in range(min(b, cap), 0, -1):
        if b % m == 0:
            return m
    return 1


class PipelinePredictor:
    """Load a saved inference model and serve it pipelined over ``pp``.

    ``model_dir``: a ``save_inference_model`` export.  ``n_stages``:
    pipeline depth K (devices used).  ``num_microbatches``: micro-batch
    cap M (see module docstring).  ``cut_vars``: explicit stage-boundary
    var names; default picks balanced single-crossing boundaries.
    """

    def __init__(self, model_dir: str, n_stages: int = 2,
                 num_microbatches: int = 4,
                 cut_vars: Optional[Sequence[str]] = None,
                 params_filename: Optional[str] = None):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        import paddle_tpu as fluid
        from paddle_tpu import io
        from paddle_tpu.parallel import mesh as mesh_lib

        self.model_dir = model_dir
        self._scope = fluid.Scope()
        self._exe = fluid.Executor(fluid.CPUPlace()
                                   if jax.default_backend() == "cpu"
                                   else None)
        with fluid.scope_guard(self._scope):
            self._program, self._feed_names, self._fetch_vars = (
                io.load_inference_model(model_dir, self._exe,
                                        params_filename=params_filename))
        self._fetch_names = [v.name for v in self._fetch_vars]
        block = self._program.global_block()
        self._block = block
        self._ops = list(block.ops)
        self._param_names = sorted(
            v.name for v in self._program.list_vars()
            if v.persistable and not v.is_data)
        K = int(n_stages)
        if cut_vars is None:
            cut_vars = propose_cut_vars(
                self._ops, K,
                skip_names=list(self._param_names) + list(self._feed_names))
        self._ranges, self._cut_names = _stage_ranges(self._ops,
                                                      list(cut_vars))
        if len(self._ranges) != K:
            raise PipelinePlanError(
                "op-stage plan has %d stages (%d cut vars) but "
                "n_stages=%d was requested — pass cut_vars matching the "
                "stage count" % (len(self._ranges), len(self._cut_names), K))
        self._K = K
        self._M = int(num_microbatches)
        if self._M < 1:
            raise PipelinePlanError(
                "num_microbatches must be >= 1 (got %d)" % self._M)
        self._mesh = mesh_lib.make_mesh({"pp": K})
        # params replicate across the pp group ONCE at construction —
        # heterogeneous stages under lax.switch need every stage's
        # params resident (pipeline_program.py's documented trade)
        rep = NamedSharding(self._mesh, P())
        self._params = {
            n: jax.device_put(np.asarray(self._scope.get(n)), rep)
            for n in self._param_names
        }
        self._cache: Dict[Any, Any] = {}
        self._stats = {"hits": 0, "misses": 0}
        self._last_schedule: Optional[Tuple[int, int]] = None  # (M_eff, T)

    # ------------------------------------------------------------------
    # predictor surface (duck-types AnalysisPredictor for the server)
    # ------------------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return list(self._feed_names)

    def get_output_names(self) -> List[str]:
        return list(self._fetch_names)

    def input_specs(self) -> Dict[str, Any]:
        from paddle_tpu.core import types as core_types

        specs = {}
        for name in self._feed_names:
            var = self._block.var(name)
            shape = tuple(
                1 if int(d) < 0 else int(d) for d in (var.shape or ())[1:])
            specs[name] = (shape, core_types.np_dtype(var.dtype))
        return specs

    def jit_cache_stats(self) -> Dict[str, int]:
        return dict(self._stats)

    def pipeline_stats(self) -> Dict[str, Any]:
        """The serving-visible pipeline contract: stage count, cut vars,
        per-stage op counts, and the LAST executed schedule's structural
        bubble ratio (``(K-1)/(M+K-1)`` — the fraction of stage-slots
        the GPipe ramp leaves idle) + per-stage occupancy (``M/T``;
        every stage is busy exactly M of the T slots)."""
        K = self._K
        if self._last_schedule is not None:
            M, T = self._last_schedule
        else:
            M, T = self._M, self._M + K - 1
        return {
            "n_stages": K,
            "num_microbatches": self._M,
            "microbatches_last": M,
            "schedule_slots": T,
            "bubble_ratio": (K - 1) / float(T),
            "stage_occupancy": {str(i): M / float(T) for i in range(K)},
            "cut_vars": list(self._cut_names),
            "stage_ops": [r.stop - r.start for r in self._ranges],
        }

    # ------------------------------------------------------------------
    def _build(self, B: int, feed_sig):
        """Compile the GPipe executable for padded batch ``B``."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.core import lowering
        from paddle_tpu.parallel import mesh as mesh_lib

        K = self._K
        M = _largest_divisor_leq(B, self._M)
        mb = B // M
        T = M + K - 1
        ops_ranges = self._ranges
        cut_names = self._cut_names
        feed_names = list(self._feed_names)
        fetch_names = list(self._fetch_names)
        block = self._block

        def stage_trace(i):
            def fn(env):
                lowering.trace_ops(self._ops[ops_ranges[i]], env, block)
                return env
            return fn

        def full_fwd(params, fd):
            env = dict(params)
            env.update(fd)
            for i in range(K):
                stage_trace(i)(env)
            return ({c: env[c] for c in cut_names},
                    [env[n] for n in fetch_names])

        one_mb = {
            n: jax.ShapeDtypeStruct((mb,) + tuple(shp[1:]), np.dtype(dt))
            for n, shp, dt in feed_sig
        }
        cut_abs, fetch_abs = jax.eval_shape(full_fwd, self._params, one_mb)
        cut_shapes = {c: tuple(s.shape) for c, s in cut_abs.items()}
        cut_dtypes = {c: s.dtype for c, s in cut_abs.items()}
        fetch_shapes = [tuple(s.shape) for s in fetch_abs]
        fetch_dtypes = [s.dtype for s in fetch_abs]
        flat_dims = {
            c: int(np.prod(shp[1:])) if len(shp) > 1 else 1
            for c, shp in cut_shapes.items()
        }
        maxd = max(flat_dims.values())
        buf_dtype = jnp.result_type(*cut_dtypes.values())

        def local_run(params, feeds_mb):
            stage = jax.lax.axis_index("pp")

            def make_branch(i):
                def branch(act_in, mb_idx):
                    env = dict(params)
                    env.update({n: feeds_mb[n][mb_idx] for n in feed_names})
                    if i > 0:
                        cin = cut_names[i - 1]
                        env[cin] = (
                            act_in[:, : flat_dims[cin]]
                            .reshape(cut_shapes[cin])
                            .astype(cut_dtypes[cin])
                        )
                    stage_trace(i)(env)
                    if i < K - 1:
                        cout = cut_names[i]
                        flat = env[cout].reshape(cut_shapes[cout][0], -1)
                        pad = maxd - flat.shape[1]
                        if pad:
                            flat = jnp.pad(flat, ((0, 0), (0, pad)))
                        fz = [jnp.zeros(s, d) for s, d in
                              zip(fetch_shapes, fetch_dtypes)]
                        return flat.astype(buf_dtype), fz
                    fs = [env[n].astype(d)
                          for n, d in zip(fetch_names, fetch_dtypes)]
                    return jnp.zeros((mb, maxd), buf_dtype), fs

                return branch

            branches = [make_branch(i) for i in range(K)]

            # hot-path: begin pipeline_handoff (the compiled GPipe slot
            # loop: switch-dispatched stage compute + the ppermute
            # hand-off, traced into every pipelined executable — pure
            # device ops, any host sync here would serialize the stages)
            def body(carry, t):
                buf, fetch_acc = carry
                mb_idx = jnp.clip(t - stage, 0, M - 1)
                act_out, fetches_mb = jax.lax.switch(
                    stage, branches, buf, mb_idx)
                valid = jnp.logical_and(t - stage >= 0, t - stage < M)
                write = jnp.logical_and(valid, stage == K - 1)
                new_acc = []
                for acc, f in zip(fetch_acc, fetches_mb):
                    upd = jnp.where(write, f, acc[mb_idx])
                    new_acc.append(acc.at[mb_idx].set(upd))
                act_out = jnp.where(valid, act_out, 0.0)
                # the double-buffered stage hand-off: this slot's send
                # overlaps the next slot's switch compute under XLA
                sent = jax.lax.ppermute(
                    act_out, "pp", [(i, (i + 1) % K) for i in range(K)])
                return (sent, tuple(new_acc)), None
            # hot-path: end pipeline_handoff

            init = (
                jnp.zeros((mb, maxd), buf_dtype),
                tuple(jnp.zeros((M,) + s, d)
                      for s, d in zip(fetch_shapes, fetch_dtypes)),
            )
            (_, fetch_acc), _ = jax.lax.scan(body, init, jnp.arange(T))
            # only the last stage wrote real values; psum replicates
            # them onto every pp rank (zeros elsewhere contribute 0)
            return [jax.lax.psum(a, "pp") for a in fetch_acc]

        smapped = mesh_lib.shard_map(
            local_run,
            mesh=self._mesh,
            in_specs=(P(), {n: P() for n, _, _ in feed_sig}),
            out_specs=[P() for _ in fetch_names],
            check_vma=False,
        )

        def run(params, feed):
            feeds_mb = {
                n: jnp.reshape(feed[n], (M, mb) + tuple(feed[n].shape[1:]))
                for n in feed_names
            }
            outs = smapped(params, feeds_mb)
            flat = []
            for o, shp in zip(outs, fetch_shapes):
                if len(shp) >= 1 and shp[0] == mb:
                    flat.append(o.reshape((B,) + tuple(shp[1:])))
                else:
                    flat.append(o[-1])  # non-batched fetch: last mb's value
            return flat

        return jax.jit(run), (M, T)

    # ------------------------------------------------------------------
    def run(self, feed, return_numpy: bool = True):
        """One pipelined dispatch over the full batch (micro-batched
        internally; see module docstring for the M_eff rule)."""
        if not isinstance(feed, dict):
            feed = dict(zip(self._feed_names, feed))
        feed = {n: np.asarray(v) for n, v in feed.items()}
        feed_sig = tuple(
            (n, tuple(feed[n].shape), np.dtype(feed[n].dtype).name)
            for n in self._feed_names)
        dims = {np.shape(feed[n])[0] for n in self._feed_names
                if np.ndim(feed[n])}
        if len(dims) != 1:
            raise ValueError(
                "pipelined run needs one consistent batch dim; got %s"
                % sorted(dims))
        (B,) = dims
        entry = self._cache.get(feed_sig)
        if entry is not None:
            self._stats["hits"] += 1
        else:
            self._stats["misses"] += 1
            entry = self._cache[feed_sig] = self._build(int(B), feed_sig)
        fn, schedule = entry
        self._last_schedule = schedule
        outs = fn(self._params, feed)
        if return_numpy:
            outs = [np.asarray(o) for o in outs]
        return outs

    def run_padded(self, feed, n_valid: Optional[int] = None,
                   return_numpy: bool = True):
        """Serving entry for pre-padded bucket feeds (the
        AnalysisPredictor contract: run the padded batch, slice outputs
        back to ``n_valid`` rows)."""
        if not isinstance(feed, dict):
            feed = dict(zip(self._feed_names, feed))
        dims = {np.shape(v)[0] if np.ndim(v) else None
                for v in feed.values()}
        dims.discard(None)
        if len(dims) != 1:
            raise ValueError(
                "run_padded needs one consistent padded leading dim; "
                "got %s" % sorted(dims))
        (padded,) = dims
        if n_valid is None:
            n_valid = padded
        if not 0 < n_valid <= padded:
            raise ValueError(
                "n_valid=%r out of range for padded batch %d"
                % (n_valid, padded))
        outs = self.run(feed, return_numpy=return_numpy)
        if n_valid == padded:
            return outs
        return [
            o[:n_valid] if np.ndim(o) >= 1 and np.shape(o)[0] == padded
            else o
            for o in outs
        ]

