"""Hybrid 5D-parallel transformer engine: dp / pp / tp / sp / ep.

This is the TPU-native replacement for the reference's whole distributed
runtime zoo — ParallelExecutor NCCL data-parallel (parallel_executor.cc),
PipelineTrainer/SectionWorker pipeline stages (framework/section_worker.cc,
optimizer.py:2665 PipelineOptimizer), and the sharded-table model
parallelism (distributed_lookup_table) — expressed as ONE jitted training
step under `jax.shard_map` over a 5-axis mesh:

  * dp — batch sharding; gradient psum over ``dp`` (the NCCL allreduce).
  * pp — GPipe microbatch pipeline: each rank owns ``n_layers/pp`` blocks;
    activations stream stage-to-stage via `lax.ppermute` inside a
    `lax.scan` (the SectionWorker queue loop, but compiled; bubbles and
    all).  Backward flows through the transposed ppermute automatically.
  * tp — Megatron-style tensor parallel: qkv/ffn weights column-sharded,
    out/second-ffn row-sharded, psum at row-parallel outputs.
  * sp — sequence parallel: activations sharded over the sequence dim;
    attention computes local query rows against all-gathered K/V
    (ring attention is the drop-in upgrade — parallel/ring_attention.py).
  * ep — expert parallel: MoE expert weights sharded over ``ep``; each
    rank computes its local experts, combined by psum.

Everything — forward, backward, optimizer update — is one XLA module per
step; collectives ride ICI in mesh-axis order.

Numerics are validated against a single-device reference implementation
(`reference_loss`) in tests/test_hybrid_parallel.py, in the loss-parity
style of the reference's dist tests (test_dist_base.py:432).
"""
from __future__ import annotations

import functools
from typing import Any, Dict, NamedTuple, Optional, Tuple

import numpy as np

from paddle_tpu.parallel import mesh as mesh_lib

__all__ = ["HybridConfig", "init_params", "make_train_step", "reference_loss", "factorize_mesh"]


class HybridConfig(NamedTuple):
    vocab_size: int = 1000
    d_model: int = 64
    n_head: int = 4
    d_ff: int = 128
    n_layers: int = 4
    n_experts: int = 4
    seq_len: int = 32
    batch: int = 8          # global batch
    microbatches: int = 2   # per dp-shard microbatch count (GPipe M)
    dp: int = 1
    pp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    lr: float = 0.1
    ring_attention: bool = True  # sp>1: ring attention vs all-gather KV

    @property
    def layers_per_stage(self) -> int:
        assert self.n_layers % self.pp == 0
        return self.n_layers // self.pp

    def mesh_axes(self) -> Dict[str, int]:
        return {"dp": self.dp, "pp": self.pp, "tp": self.tp, "sp": self.sp, "ep": self.ep}


def factorize_mesh(n_devices: int) -> Dict[str, int]:
    """Deterministically factor a device count onto the 5 axes.

    Order of filling: pp, tp, dp, sp, ep — pipeline+tensor first (the
    common v5e intra-host layout), then data, then sequence/expert.
    """
    sizes = {"dp": 1, "pp": 1, "tp": 1, "sp": 1, "ep": 1}
    order = ["pp", "tp", "dp", "sp", "ep"]
    n = n_devices
    i = 0
    while n > 1:
        for p in (2, 3, 5, 7, 11, 13):
            if n % p == 0:
                sizes[order[i % len(order)]] *= p
                n //= p
                break
        else:  # prime > 13: give it all to dp
            sizes["dp"] *= n
            n = 1
        i += 1
    return sizes


# ---------------------------------------------------------------------------
# Parameters.  Stage-stacked: leading dim pp, second dim layers-per-stage.
# ---------------------------------------------------------------------------
def _param_specs(cfg: HybridConfig):
    """name -> PartitionSpec dims (None = replicated on that dim)."""
    from jax.sharding import PartitionSpec as P

    return {
        "word_emb": P(),
        "pos_emb": P(),
        "head": P(None, "tp"),
        "ln1_scale": P("pp"),
        "ln1_bias": P("pp"),
        "ln2_scale": P("pp"),
        "ln2_bias": P("pp"),
        "wq": P("pp", None, None, "tp"),
        "wk": P("pp", None, None, "tp"),
        "wv": P("pp", None, None, "tp"),
        "wo": P("pp", None, "tp", None),
        "gate_w": P("pp"),
        "moe_w0": P("pp", None, "ep", None, "tp"),
        "moe_w1": P("pp", None, "ep", "tp", None),
    }


def init_params(cfg: HybridConfig, seed: int = 0) -> Dict[str, np.ndarray]:
    rng = np.random.RandomState(seed)
    S, L = cfg.pp, cfg.layers_per_stage
    D, F, E, V = cfg.d_model, cfg.d_ff, cfg.n_experts, cfg.vocab_size

    def rand(*shape, scale=None):
        scale = scale if scale is not None else (1.0 / np.sqrt(shape[-2] if len(shape) >= 2 else D))
        return (rng.standard_normal(shape) * scale).astype(np.float32)

    return {
        "word_emb": rand(V, D, scale=0.02),
        "pos_emb": rand(cfg.seq_len, D, scale=0.02),
        "head": rand(D, V),
        "ln1_scale": np.ones((S, L, D), np.float32),
        "ln1_bias": np.zeros((S, L, D), np.float32),
        "ln2_scale": np.ones((S, L, D), np.float32),
        "ln2_bias": np.zeros((S, L, D), np.float32),
        "wq": rand(S, L, D, D),
        "wk": rand(S, L, D, D),
        "wv": rand(S, L, D, D),
        "wo": rand(S, L, D, D),
        "gate_w": rand(S, L, D, E),
        "moe_w0": rand(S, L, E, D, F),
        "moe_w1": rand(S, L, E, F, D, scale=1.0 / np.sqrt(F)),
    }


# ---------------------------------------------------------------------------
# Model math (shared by the sharded engine and the reference impl).
# ---------------------------------------------------------------------------
def _layer_norm(x, scale, bias, eps=1e-5):
    import jax.numpy as jnp

    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    return (x - mu) / jnp.sqrt(var + eps) * scale + bias


def _attention_math(q, k, v, bias, n_head_local, d_head):
    """q: [b, Tq, Hl*Dh]; k/v: [b, Tk, Hl*Dh]; bias: [Tq, Tk]."""
    import jax.numpy as jnp

    b, tq, _ = q.shape
    tk = k.shape[1]

    def heads(x, t):
        return x.reshape(b, t, n_head_local, d_head).transpose(0, 2, 1, 3)

    qh, kh, vh = heads(q, tq), heads(k, tk), heads(v, tk)
    scores = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) / np.sqrt(d_head)
    scores = scores + bias
    w = _softmax(scores)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
    return ctx.transpose(0, 2, 1, 3).reshape(b, tq, n_head_local * d_head)


def _softmax(x):
    import jax.nn

    return jax.nn.softmax(x, axis=-1)


def _moe_math(x, gate_logits_local, w0_local, w1_local):
    """x: [b, t, D]; gate_logits_local: [b, t, e_loc] (already softmaxed
    slice); w0_local: [e_loc, D, F_loc]; w1_local: [e_loc, F_loc, D]."""
    import jax
    import jax.numpy as jnp

    h = jnp.einsum("btd,edf->btef", x, w0_local)
    h = jax.nn.gelu(h)
    y = jnp.einsum("btef,efd->bted", h, w1_local)
    return jnp.einsum("bted,bte->btd", y, gate_logits_local)


# ---------------------------------------------------------------------------
# Single-device reference (for loss parity tests)
# ---------------------------------------------------------------------------
def reference_loss(params: Dict[str, Any], tokens, labels, cfg: HybridConfig):
    """Pure single-device forward loss, same math as the sharded engine."""
    import jax
    import jax.numpy as jnp

    D, H = cfg.d_model, cfg.n_head
    d_head = D // H
    T = cfg.seq_len
    x = params["word_emb"][tokens] + params["pos_emb"][None, :, :]
    causal = jnp.where(jnp.arange(T)[None, :] <= jnp.arange(T)[:, None], 0.0, -1e9)
    for s in range(cfg.pp):
        for l in range(cfg.layers_per_stage):
            h = _layer_norm(x, params["ln1_scale"][s, l], params["ln1_bias"][s, l])
            q, k, v = h @ params["wq"][s, l], h @ params["wk"][s, l], h @ params["wv"][s, l]
            att = _attention_math(q, k, v, causal, H, d_head)
            x = x + att @ params["wo"][s, l]
            h = _layer_norm(x, params["ln2_scale"][s, l], params["ln2_bias"][s, l])
            gates = jax.nn.softmax(h @ params["gate_w"][s, l], axis=-1)
            x = x + _moe_math(h, gates, params["moe_w0"][s, l], params["moe_w1"][s, l])
    logits = x @ params["head"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    return jnp.mean(nll)


# ---------------------------------------------------------------------------
# The sharded engine
# ---------------------------------------------------------------------------
def _optimizer_plan(optimizer):
    """Map a fluid optimizer object onto its registered op kernel
    (reference: each Optimizer's _append_optimize_op emits the same op).

    Returns (op_type, attrs, moment_slots, pow_slots, lr, l2_decay).
    moment_slots are per-param aux tensors shaped like the param (sharded
    with the param's spec); pow_slots are per-param scalars (replicated).
    """
    if optimizer is None:
        return ("sgd", {}, [], {}, None, 0.0)
    decay = 0.0
    reg = getattr(optimizer, "regularization", None)
    if reg is not None:
        if type(reg).__name__ != "L2DecayRegularizer":
            raise ValueError(
                "hybrid engine: only L2 decay regularization is supported "
                "(got %s)" % type(reg).__name__
            )
        decay = float(reg._coeff)
    lr = optimizer._learning_rate
    if not isinstance(lr, (int, float)):
        raise ValueError(
            "hybrid engine: optimizer must have a float learning rate "
            "(LR schedules run program-side)"
        )
    # exact-class whitelist (ADVICE r4): a wrapper/subclass like
    # DGCMomentumOptimizer or LarsMomentumOptimizer carries extra update
    # semantics a substring match would silently drop — those must raise
    # and route through the Program path instead
    from paddle_tpu import optimizer as opt_mod

    cls = type(optimizer)
    if cls is opt_mod.AdamOptimizer:
        return (
            "adam",
            {"beta1": optimizer._beta1, "beta2": optimizer._beta2,
             "epsilon": optimizer._epsilon},
            ["Moment1", "Moment2"],
            {"Beta1Pow": optimizer._beta1, "Beta2Pow": optimizer._beta2},
            float(lr), decay,
        )
    if cls is opt_mod.MomentumOptimizer:
        return (
            "momentum",
            {"mu": optimizer._momentum,
             "use_nesterov": optimizer._use_nesterov},
            ["Velocity"], {}, float(lr), decay,
        )
    if cls is opt_mod.SGDOptimizer:
        return ("sgd", {}, [], {}, float(lr), decay)
    raise ValueError(
        "hybrid engine supports exactly SGDOptimizer/MomentumOptimizer/"
        "AdamOptimizer (got %s — subclasses and wrappers carry extra "
        "update semantics); route other optimizers through the Program "
        "path" % cls.__name__
    )


def init_opt_state(cfg: HybridConfig, params, optimizer):
    """Optimizer aux state for ``make_train_step(..., optimizer=)``:
    '<param>@<Slot>' -> zeros_like(param) moments and scalar beta pows
    (the reference's per-param accumulators, optimizer.py
    _add_accumulator)."""
    _, _, moment_slots, pow_slots, _, _ = _optimizer_plan(optimizer)
    aux = {}
    for n, p in params.items():
        for slot in moment_slots:
            aux["%s@%s" % (n, slot)] = np.zeros_like(p)
        for slot, v0 in pow_slots.items():
            aux["%s@%s" % (n, slot)] = np.full((1,), v0, np.float32)
    return aux


def make_train_step(cfg: HybridConfig, mesh=None, optimizer=None):
    """Build the single jitted XLA module implementing the full
    5D-parallel training step (fwd + bwd + optimizer update).

    ``optimizer=None``: plain SGD at ``cfg.lr``;
    ``step(params, tokens, labels) -> (loss, new_params)``.

    ``optimizer=`` a fluid SGD/Momentum/Adam optimizer object (with
    optional L2 regularization): the update replays the optimizer's
    REGISTERED op kernel per parameter — the same kernels the Program
    path runs (parallel/pipeline_program.py does the same for pipeline
    sections) — and the step signature becomes
    ``step(params, aux, tokens, labels) -> (loss, new_params, new_aux)``
    with ``aux`` from :func:`init_opt_state`.  Moments shard with their
    parameter's spec; beta pows replicate.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        mesh = mesh_lib.make_mesh(cfg.mesh_axes())
    specs = _param_specs(cfg)
    opt_op, opt_attrs, moment_slots, pow_slots, opt_lr, l2_decay = _optimizer_plan(optimizer)
    aux_spec_of = {}
    for n in specs:
        for slot in moment_slots:
            aux_spec_of["%s@%s" % (n, slot)] = specs[n]
        for slot in pow_slots:
            aux_spec_of["%s@%s" % (n, slot)] = P()

    D, H, T, V, E, F = cfg.d_model, cfg.n_head, cfg.seq_len, cfg.vocab_size, cfg.n_experts, cfg.d_ff
    assert H % cfg.tp == 0 and D % cfg.tp == 0 and F % cfg.tp == 0
    assert T % cfg.sp == 0 and E % cfg.ep == 0 and cfg.batch % cfg.dp == 0
    h_loc, t_loc, e_loc = H // cfg.tp, T // cfg.sp, E // cfg.ep
    d_head = D // H
    b_loc = cfg.batch // cfg.dp
    M = cfg.microbatches
    assert b_loc % M == 0
    mb = b_loc // M
    S = cfg.pp
    n_steps = M + S - 1

    ALL_AXES = ("dp", "pp", "tp", "sp", "ep")

    def replicated_axes(spec):
        used = {a for a in spec if a is not None}
        return tuple(a for a in ALL_AXES if a not in used)

    def lift_all(x):
        """pvary x over every mesh axis it isn't already varying on, so
        downstream vma state is uniform regardless of axis sizes.  On
        jax releases predating the vma tracking (no jax.typeof /
        lax.pvary) there is no varying-axis state to normalize — the
        rep checker there is the coarser check_rep — so this is a
        no-op."""
        typeof = getattr(jax, "typeof", None)
        pvary = getattr(jax.lax, "pvary", None)
        if typeof is None or pvary is None:
            return x
        vma = typeof(x).vma
        missing = tuple(a for a in ALL_AXES if a not in vma)
        return pvary(x, missing) if missing else x

    # ---------------- per-stage block (runs under shard_map) -------------
    def stage_fn(sp_idx, tp_idx, ep_idx, stage_params, x):
        """x: [mb, t_loc, D] local activation; applies this stage's layers."""
        q_off = sp_idx * t_loc
        rows = jnp.arange(t_loc) + q_off
        cols = jnp.arange(T)
        causal = jnp.where(cols[None, :] <= rows[:, None], 0.0, -1e9)

        for l in range(cfg.layers_per_stage):
            p = {k: v[l] for k, v in stage_params.items()}
            h = _layer_norm(x, p["ln1_scale"], p["ln1_bias"])
            # tp column-parallel qkv: local [D, D/tp] slices
            q = h @ p["wq"]
            k = h @ p["wk"]
            v = h @ p["wv"]
            if cfg.sp > 1 and cfg.ring_attention:
                # ring attention: K/V blocks rotate over the sp ring with
                # online-softmax accumulation (parallel/ring_attention.py)
                from paddle_tpu.parallel.ring_attention import ring_attention

                b = q.shape[0]

                def heads(z):
                    return z.reshape(b, t_loc, h_loc, d_head).transpose(0, 2, 1, 3)

                ctx = ring_attention(heads(q), heads(k), heads(v), "sp", causal=True)
                att = ctx.transpose(0, 2, 1, 3).reshape(b, t_loc, h_loc * d_head)
            else:
                # sp: all-gather K/V sequence shards -> full-length keys
                if cfg.sp > 1:
                    k = jax.lax.all_gather(k, "sp", axis=1, tiled=True)
                    v = jax.lax.all_gather(v, "sp", axis=1, tiled=True)
                att = _attention_math(q, k, v, causal, h_loc, d_head)
            # tp row-parallel output projection + psum over tp
            o = att @ p["wo"]
            o = jax.lax.psum(o, "tp")
            x = x + o

            h = _layer_norm(x, p["ln2_scale"], p["ln2_bias"])
            gates = jax.nn.softmax(h @ p["gate_w"], axis=-1)  # full E
            g_loc = jax.lax.dynamic_slice_in_dim(gates, ep_idx * e_loc, e_loc, axis=-1)
            y = _moe_math(h, g_loc, p["moe_w0"], p["moe_w1"])
            y = jax.lax.psum(y, ("ep", "tp"))
            x = x + y
        return x

    STAGE_KEYS = (
        "ln1_scale", "ln1_bias", "ln2_scale", "ln2_bias",
        "wq", "wk", "wv", "wo", "gate_w", "moe_w0", "moe_w1",
    )

    # ---------------- full local step (inside shard_map) ------------------
    def local_loss(params, tokens, labels):
        stage = jax.lax.axis_index("pp")
        sp_idx = jax.lax.axis_index("sp")
        tp_idx = jax.lax.axis_index("tp")
        ep_idx = jax.lax.axis_index("ep")

        # slice my sequence shard of tokens/labels: [b_loc, t_loc]
        tok = jax.lax.dynamic_slice_in_dim(tokens, sp_idx * t_loc, t_loc, axis=1)
        lab = jax.lax.dynamic_slice_in_dim(labels, sp_idx * t_loc, t_loc, axis=1)
        pos = jax.lax.dynamic_slice_in_dim(params["pos_emb"], sp_idx * t_loc, t_loc, axis=0)[None]
        x = params["word_emb"][tok] + pos  # [b_loc, t_loc, D]
        x = lift_all(x)

        # microbatches [M, mb, t_loc, D]
        xs = x.reshape(M, mb, t_loc, D)
        stage_params = {k: params[k][0] for k in STAGE_KEYS}  # local stage (pp-sharded dim0)

        if S == 1:
            final = stage_fn(sp_idx, tp_idx, ep_idx, stage_params, x)
        else:
            def body(carry, t):
                buf = carry
                x_t = xs[jnp.clip(t, 0, M - 1)]
                inp = jnp.where(stage == 0, x_t, buf)
                out = stage_fn(sp_idx, tp_idx, ep_idx, stage_params, inp)
                sent = jax.lax.ppermute(out, "pp", [(i, (i + 1) % S) for i in range(S)])
                y = jnp.where(stage == S - 1, out, 0.0)
                return sent, y

            init = lift_all(jnp.zeros((mb, t_loc, D), x.dtype))
            _, ys = jax.lax.scan(body, init, jnp.arange(n_steps))
            final = ys[S - 1 :].reshape(b_loc, t_loc, D)  # valid on last stage

        # head: tp column-parallel logits -> gather over tp
        logits_loc = final @ params["head"]  # [b_loc, t_loc, V/tp]
        if cfg.tp > 1:
            logits = jax.lax.all_gather(logits_loc, "tp", axis=-1, tiled=True)
        else:
            logits = logits_loc
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, lab[..., None], axis=-1)[..., 0]
        loss_sum = jnp.sum(nll)
        # only the last pipeline stage's loss is real
        loss_sum = jnp.where(stage == S - 1, loss_sum, 0.0)
        total_tokens = cfg.batch * T
        loss = jax.lax.psum(loss_sum, ("dp", "pp", "sp")) / total_tokens
        # value-identity pmean proves tp/ep invariance to the vma checker
        # (the loss is computed redundantly on those ranks)
        return jax.lax.pmean(loss, ("tp", "ep"))

    def apply_optimizer(params, grads, aux):
        """Replay the registered optimizer kernel per parameter (the same
        kernels Executor programs run; pipeline_program.py's pattern)."""
        from paddle_tpu.core.registry import get_kernel

        kern = get_kernel(opt_op)
        lr_arr = jnp.asarray([opt_lr], jnp.float32)
        new_p, new_aux = {}, dict(aux)
        for n in params:
            g = grads[n]
            if l2_decay:
                g = g + l2_decay * params[n]
            ins = {"Param": [params[n]], "Grad": [g.astype(params[n].dtype)],
                   "LearningRate": [lr_arr]}
            for slot in moment_slots + list(pow_slots):
                ins[slot] = [aux["%s@%s" % (n, slot)]]
            outs = kern(ins, opt_attrs)
            new_p[n] = outs["ParamOut"]
            for slot in moment_slots + list(pow_slots):
                out = outs.get(slot + "Out")
                if out is not None:
                    new_aux["%s@%s" % (n, slot)] = out
        return new_p, new_aux

    # pre-vma jax (no lax.pvary / jax.typeof) runs shard_map with
    # check_rep=False (mesh_lib.shard_map), which disables the automatic
    # cotangent psum over each input's replication axes — grads come back
    # as raw per-device partials.  The exact correction: every device
    # seeds its (replicated) loss output with 1, so the SPMD backward
    # computes the adjoint of N_mesh identical losses — psum the grad
    # over the param's replicated axes and divide by the mesh size.
    pre_vma = (getattr(jax, "typeof", None) is None
               or getattr(jax.lax, "pvary", None) is None)
    n_mesh = int(np.prod(list(cfg.mesh_axes().values())))

    def reduce_grads(grads):
        out = {}
        for n, g in grads.items():
            rep = replicated_axes(specs[n])
            if rep:
                g = jax.lax.psum(g, rep)
            out[n] = g / n_mesh
        return out

    def sharded_step(params, aux, tokens, labels):
        # Gradient reduction over each param's replication axes (the
        # reference's NCCL allreduce, details/all_reduce_op_handle.cc) is
        # inserted by shard_map's transpose: under check_vma=True the
        # cotangent of an input that is invariant over an axis is psum'd
        # over that axis automatically.  Under pre-vma check_rep=False
        # the reduction is applied explicitly (reduce_grads above).
        loss, grads = jax.value_and_grad(local_loss)(params, tokens, labels)
        if pre_vma:
            grads = reduce_grads(grads)
        if optimizer is None:
            new_params = {n: params[n] - cfg.lr * grads[n] for n in params}
            return loss, new_params, aux
        new_params, new_aux = apply_optimizer(params, grads, aux)
        return loss, new_params, new_aux

    in_specs = (
        {n: specs[n] for n in specs},
        {n: aux_spec_of[n] for n in aux_spec_of},
        P("dp"),
        P("dp"),
    )
    out_specs = (
        P(),
        {n: specs[n] for n in specs},
        {n: aux_spec_of[n] for n in aux_spec_of},
    )

    smapped = mesh_lib.shard_map(
        sharded_step,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=True,
    )
    jitted = jax.jit(smapped)

    def place_aux(aux):
        return {
            n: jax.device_put(v, NamedSharding(mesh, aux_spec_of[n]))
            for n, v in aux.items()
        }

    def place(params, tokens, labels):
        params = {
            n: jax.device_put(v, NamedSharding(mesh, specs[n])) for n, v in params.items()
        }
        tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp")))
        labels = jax.device_put(labels, NamedSharding(mesh, P("dp")))
        return params, tokens, labels

    if optimizer is None:
        # legacy signature: step(params, tokens, labels) -> (loss, params)
        def step(params, tokens, labels):
            loss, new_params, _ = jitted(params, {}, tokens, labels)
            return loss, new_params

        return step, place, mesh

    def step(params, aux, tokens, labels):
        return jitted(params, aux, tokens, labels)

    step.place_aux = place_aux
    return step, place, mesh
