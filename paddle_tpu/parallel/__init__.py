"""Distributed / parallel execution: meshes, sharding, collectives, fleet.

TPU-native replacement for the reference's ParallelExecutor + NCCL stack
(parallel_executor.cc, operators/collective/, transpiler/) — see
parallel/compiled_program.py and parallel/fleet.py.
"""
from paddle_tpu.parallel import env  # noqa: F401
from paddle_tpu.parallel import mesh  # noqa: F401
from paddle_tpu.parallel.mesh import MeshGuard, data_parallel_mesh, make_mesh  # noqa: F401
from paddle_tpu.parallel.strategy import (  # noqa: F401
    BuildStrategy,
    DistributedStrategy,
    ExecutionStrategy,
)
from paddle_tpu.parallel.compiled_program import CompiledProgram  # noqa: F401
from paddle_tpu.parallel import collective_transpiler  # noqa: F401
from paddle_tpu.parallel import fleet as fleet_mod  # noqa: F401
from paddle_tpu.parallel.fleet import fleet  # noqa: F401
from paddle_tpu.parallel import hybrid  # noqa: F401
from paddle_tpu.parallel import ring_attention  # noqa: F401
