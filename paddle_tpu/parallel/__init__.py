"""Distributed / parallel execution: meshes, sharding, collectives, fleet.

TPU-native replacement for the reference's ParallelExecutor + NCCL stack
(parallel_executor.cc, operators/collective/, transpiler/) — see
parallel/compiled_program.py and parallel/fleet.py.
"""
from paddle_tpu.parallel import env  # noqa: F401
