"""CompiledProgram: sharded/jit execution plan for a Program.

Reference: python/paddle/fluid/compiler.py:57 (`CompiledProgram
.with_data_parallel(...)`) which builds a C++ ParallelExecutor — per-device
graph clones with NCCL all-reduce op-handles (parallel_executor.cc:356,
ir/multi_devices_graph_pass/).  TPU-native design: the single lowered XLA
module is jitted with `jax.sharding` in_shardings over a named Mesh; GSPMD
partitions the computation and inserts ICI collectives (the all-reduce on
gradients falls out of batch-dim sharding + replicated params — no graph
rewriting).  Because the executor feeds the *global* batch and loss means
reduce over it, gradient scaling matches the reference's CoeffNumDevice
strategy automatically.

Model parallelism: `DistributedStrategy.mesh_axes` gives the mesh shape
(dp/tp/pp/sp/ep) and `sharding_specs` maps persistable var names to
PartitionSpec dim tuples, e.g. ``{"fc_w": (None, "tp")}`` for a
column-parallel weight.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel.strategy import BuildStrategy, DistributedStrategy, ExecutionStrategy

__all__ = ["CompiledProgram"]


class CompiledProgram:
    _is_compiled_program = True

    def __init__(self, program):
        # accept either a Program or another CompiledProgram's program
        self._program = getattr(program, "_program", program)
        self._mesh = None
        self._strategy: Optional[DistributedStrategy] = None
        self._batch_axis = "dp"
        self._build_strategy: Optional[BuildStrategy] = None
        self._exec_strategy: Optional[ExecutionStrategy] = None
        self._loss_name: Optional[str] = None

    # ------------------------------------------------------------------
    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from=None,
        places=None,
    ) -> "CompiledProgram":
        """Data-parallel over all local devices (reference: compiler.py:126)."""
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        n = len(places) if places else None
        self._mesh = mesh_lib.data_parallel_mesh(n)
        return self

    def with_strategy(self, strategy: DistributedStrategy, mesh=None) -> "CompiledProgram":
        """Bind an explicit mesh/sharding plan (tp/pp/sp/ep aware)."""
        self._strategy = strategy
        if mesh is not None:
            self._mesh = mesh
        elif strategy.mesh_axes:
            self._mesh = mesh_lib.make_mesh(strategy.mesh_axes)
        else:
            self._mesh = mesh_lib.default_mesh()
        return self

    def with_mesh(self, mesh) -> "CompiledProgram":
        self._mesh = mesh
        return self

    # ------------------------------------------------------------------
    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = mesh_lib.default_mesh()
        return self._mesh

    def _spec_for_state(self, name: str):
        from jax.sharding import PartitionSpec as P

        specs = self._strategy.sharding_specs if self._strategy else {}
        if name in specs:
            return P(*specs[name])
        return P()  # replicated

    def _spec_for_feed(self, name: str, ndim: int):
        from jax.sharding import PartitionSpec as P

        specs = self._strategy.sharding_specs if self._strategy else {}
        if name in specs:
            return P(*specs[name])
        if ndim >= 1 and self._batch_axis in self.mesh.axis_names:
            return P(self._batch_axis)  # shard batch dim, rest replicated
        return P()

    def _sharding(self, spec):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, spec)

    # ------------------------------------------------------------------
    # Executor integration
    # ------------------------------------------------------------------
    def _jit_kwargs(self, block, feed_names, fetch_names, state_mut, state_ro,
                    state_out, per_step_feed=False):
        from jax.sharding import PartitionSpec as P

        mut_sh = {n: self._sharding(self._spec_for_state(n)) for n in state_mut}
        ro_sh = {n: self._sharding(self._spec_for_state(n)) for n in state_ro}

        feed_sh = {}
        for n in feed_names:
            var = block._find_var_recursive(n)
            ndim = len(var.shape) if var is not None and var.shape is not None else 1
            spec = self._spec_for_feed(n, ndim)
            if per_step_feed:
                # Executor.run(steps=N, per_step_feed=True) stacks a
                # leading steps axis on every feed; keep it replicated and
                # shift the batch/seq sharding one axis right
                spec = P(None, *spec)
            feed_sh[n] = self._sharding(spec)
        return {"in_shardings": (mut_sh, ro_sh, feed_sh)}

    def _shard_inputs(self, feed_arrays, mut_state, ro_state, per_step_feed=False):
        import jax
        from jax.sharding import PartitionSpec as P

        def put(arrs, spec_fn):
            out = {}
            for n, a in arrs.items():
                sh = self._sharding(spec_fn(n, np.ndim(a)))
                out[n] = jax.device_put(a, sh)
            return out

        def feed_spec(n, d):
            if per_step_feed:
                return P(None, *self._spec_for_feed(n, d - 1))
            return self._spec_for_feed(n, d)

        feed_arrays = put(feed_arrays, feed_spec)
        mut_state = put(mut_state, lambda n, d: self._spec_for_state(n))
        ro_state = put(ro_state, lambda n, d: self._spec_for_state(n))
        return feed_arrays, mut_state, ro_state

    # parity helpers --------------------------------------------------
    def _compile_data_parallel(self, *a, **k):  # reference: compiler.py:241
        return self

    def __repr__(self):
        ax = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)) if self._mesh else {}
        return "CompiledProgram(mesh=%s)" % (ax,)


class ParallelExecutor:
    """Legacy multi-device executor (reference: parallel_executor.py) —
    thin facade over CompiledProgram.with_data_parallel + Executor; the
    `run` signature matches the reference (fetch_list of names/vars,
    feed dict split across the dp mesh by the compiled program)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        from paddle_tpu import framework
        from paddle_tpu.executor import Executor
        from paddle_tpu.framework import CPUPlace, TPUPlace

        program = main_program or framework.default_main_program()
        self._compiled = CompiledProgram(program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy,
        )
        self._exe = Executor(TPUPlace(0) if use_cuda else CPUPlace())
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        return self._exe.run(
            self._compiled, feed=feed or feed_dict, fetch_list=fetch_list,
            scope=self._scope, return_numpy=return_numpy,
        )
