"""CompiledProgram: sharded/jit execution plan for a Program.

Reference: python/paddle/fluid/compiler.py:57 (`CompiledProgram
.with_data_parallel(...)`) which builds a C++ ParallelExecutor — per-device
graph clones with NCCL all-reduce op-handles (parallel_executor.cc:356,
ir/multi_devices_graph_pass/).  TPU-native design: the single lowered XLA
module is jitted with `jax.sharding` in_shardings over a named Mesh; GSPMD
partitions the computation and inserts ICI collectives (the all-reduce on
gradients falls out of batch-dim sharding + replicated params — no graph
rewriting).  Because the executor feeds the *global* batch and loss means
reduce over it, gradient scaling matches the reference's CoeffNumDevice
strategy automatically.

Model parallelism: `DistributedStrategy.mesh_axes` gives the mesh shape
(dp/tp/pp/sp/ep) and `sharding_specs` maps persistable var names to
PartitionSpec dim tuples, e.g. ``{"fc_w": (None, "tp")}`` for a
column-parallel weight.  `with_sharding_rules(rules)` is the
declarative layer above that: an ordered regex→PartitionSpec rule set
(`paddle_tpu.sharding.PartitionRules`, GSPMD tradition) resolved
against persistable names at restage time — each param is placed
SHARD-wise on the mesh (not replicated), output layouts are pinned so
sharded state stays sharded across steps, and after warmup the steady
state pays zero placement work and zero recompiles.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel.strategy import BuildStrategy, DistributedStrategy, ExecutionStrategy

__all__ = ["CompiledProgram"]


class CompiledProgram:
    _is_compiled_program = True

    def __init__(self, program):
        # accept either a Program or another CompiledProgram's program
        self._program = getattr(program, "_program", program)
        self._mesh = None
        self._strategy: Optional[DistributedStrategy] = None
        self._rules = None  # PartitionRules (with_sharding_rules)
        self._mesh_axes: Optional[Dict[str, int]] = None  # manifest form
        self._batch_axis = "dp"
        # feeds whose leading dim is NOT the batch (mesh-table prefetch
        # rows: leading dim = bucketed unique ids) — placed replicated
        # instead of batch-sharded (sharding.sparse.bind_mesh_tables)
        self._replicated_feeds: set = set()
        self._build_strategy: Optional[BuildStrategy] = None
        self._exec_strategy: Optional[ExecutionStrategy] = None
        self._loss_name: Optional[str] = None
        # resolved-sharding memos: NamedSharding construction walks the
        # mesh, so the per-run _shard_inputs pass must not rebuild one
        # per array per step (O(n_params) rent on the dispatch hot path)
        self._sharding_memo: Dict[Any, Any] = {}
        self._state_sh_memo: Dict[str, Any] = {}
        self._feed_sh_memo: Dict[tuple, Any] = {}
        # jit keys whose state reached the self-feeding steady state (a
        # full placement pass with zero re-stages): state checks are
        # skipped for them — outputs are out_shardings-pinned and flow
        # back through the scope, so per-step state placement work drops
        # to zero (see _shard_inputs)
        self._steady_tokens: set = set()
        # param name -> np.dtype applied at shard-placement time: a
        # value whose dtype differs is cast host-side right before its
        # device_put, so the device only ever holds per-shard bytes in
        # the target dtype (the composed bf16+sharded endpoint's hoisted
        # casts land here — see with_cast_dtypes)
        self._cast_dtypes: Dict[str, Any] = {}
        # activation constrainer (sequence-parallel serving): built once
        # per rules+mesh bind, installed by the executor around block
        # tracing; holds the per-name activation-bytes report
        self._act_constrainer = None

    # ------------------------------------------------------------------
    def with_data_parallel(
        self,
        loss_name: Optional[str] = None,
        build_strategy: Optional[BuildStrategy] = None,
        exec_strategy: Optional[ExecutionStrategy] = None,
        share_vars_from=None,
        places=None,
    ) -> "CompiledProgram":
        """Data-parallel over all local devices (reference: compiler.py:126)."""
        self._loss_name = loss_name
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        n = len(places) if places else None
        self._mesh = mesh_lib.data_parallel_mesh(n)
        self._clear_sharding_memos()
        return self

    def with_strategy(self, strategy: DistributedStrategy, mesh=None) -> "CompiledProgram":
        """Bind an explicit mesh/sharding plan (tp/pp/sp/ep aware)."""
        self._strategy = strategy
        if mesh is not None:
            self._mesh = mesh
        elif strategy.mesh_axes:
            self._mesh = mesh_lib.make_mesh(strategy.mesh_axes)
        else:
            self._mesh = mesh_lib.default_mesh()
        self._clear_sharding_memos()
        return self

    def with_mesh(self, mesh) -> "CompiledProgram":
        self._mesh = mesh
        self._clear_sharding_memos()
        return self

    def with_sharding_rules(self, rules, mesh=None, mesh_axes=None,
                            default=None) -> "CompiledProgram":
        """Bind a declarative partition-rule set (GSPMD tradition).

        ``rules``: a :class:`paddle_tpu.sharding.PartitionRules` or a
        sequence of ``(regex, PartitionSpec)`` pairs (first match
        wins; ``default=`` is the fallback spec for unmatched names —
        without it an unmatched persistable is a typed
        ``ShardingRuleError`` at resolve time, never an XLA error).

        The mesh: ``mesh`` (a jax Mesh) or ``mesh_axes`` (axis→size,
        e.g. ``{"tp": 2}``); with neither, a single-axis rule set
        spans every local device on its one axis.  Explicit
        ``DistributedStrategy.sharding_specs`` entries still win over
        the rules for their names (per-var override)."""
        from paddle_tpu.sharding.rules import PartitionRules, ShardingRuleError

        if not isinstance(rules, PartitionRules):
            rules = PartitionRules(rules, default=default)
        elif default is not None:
            # polymorphic rebuild: a TrainPartitionRules keeps its
            # accumulator map through the default rebind
            rules = rules.with_default(default)
        if mesh is not None:
            self._mesh = mesh
            self._mesh_axes = dict(
                zip(mesh.axis_names, mesh.devices.shape))
        elif mesh_axes:
            self._mesh_axes = {str(a): int(n) for a, n in
                               dict(mesh_axes).items()}
            self._mesh = mesh_lib.make_mesh(self._mesh_axes)
        else:
            axes = sorted(rules.axes())
            if len(axes) != 1:
                raise ShardingRuleError(
                    "rule set %r spans axes %s — pass mesh= or "
                    "mesh_axes= to fix their sizes" % (rules.name, axes))
            n = len(mesh_lib.local_devices())
            self._mesh_axes = {axes[0]: n}
            self._mesh = mesh_lib.make_mesh(self._mesh_axes)
        rules.validate_mesh(self._mesh)
        # clear BEFORE rebinding: the retire check inside must see the
        # OLD rules (a train layout being replaced tears down its
        # state-bytes series; the new layout republishes at placement)
        self._clear_sharding_memos()
        self._rules = rules
        return self

    def with_cast_dtypes(self, dtypes: Dict[str, Any]) -> "CompiledProgram":
        """Bind placement-time dtype casts (precision × sharding).

        ``dtypes``: param name → numpy-compatible dtype (e.g.
        ``ml_dtypes.bfloat16``).  During ``_shard_inputs`` a listed
        state value whose dtype differs is cast host-side immediately
        before its ``device_put``, so the cast happens ONCE per param at
        placement and the device never materializes the source-width
        array — the hoisted param casts of a bf16 variant land exactly
        here when the endpoint is also sharded."""
        self._cast_dtypes = {str(n): np.dtype(d) for n, d in
                             dict(dtypes or {}).items()}
        # a new cast map invalidates steady-state conclusions (a steady
        # token would skip the placement pass that applies the casts)
        self._steady_tokens.clear()
        return self

    @property
    def sharding_rules(self):
        return self._rules

    def activation_constrainer(self):
        """The trace-time activation constrainer for this plan, or None
        when the bound rules carry no activation rules.  Built once per
        rules+mesh bind (cleared with the sharding memos) — the
        constrainer's own (name, shape) memo is what keeps re-traces of
        new bucket rungs from re-scanning the regex list."""
        if self._act_constrainer is not None:
            return self._act_constrainer
        rules = self._rules
        if rules is None or not (getattr(rules, "activations", ())
                                 or getattr(rules, "activation_default", None)
                                 is not None):
            return None
        from paddle_tpu.sharding.activations import ActivationConstrainer

        axes = self._mesh_axes or dict(
            zip(self.mesh.axis_names, self.mesh.devices.shape))
        self._act_constrainer = ActivationConstrainer(
            rules, self.mesh, axes)
        return self._act_constrainer

    def activation_stats(self):
        """Aggregate activation-bytes report of the last traced program
        (see ActivationConstrainer.stats), or None when activations are
        not ruled."""
        c = self.activation_constrainer()
        return c.stats() if c is not None else None

    def _clear_sharding_memos(self) -> None:
        if getattr(self._rules, "state_kind", None) is not None:
            # a mesh/rules rebind tears the old training layout down:
            # its state-bytes series must not keep scraping stale values
            from paddle_tpu.sharding import train as _sh_train

            _sh_train.retire_state_bytes()
        self._sharding_memo.clear()
        self._state_sh_memo.clear()
        self._feed_sh_memo.clear()
        self._act_constrainer = None
        # a re-bound mesh invalidates every steady-state conclusion: a
        # stale token would skip state placement against the OLD layout
        self._steady_tokens.clear()
        # ...and every compiled executable: the executor's plan/jit keys
        # carry this wrapper's uid, so stamping a FRESH uid orphans the
        # entries jitted with the old mesh's in/out shardings (they age
        # out of the LRU) instead of silently serving the old layout
        if getattr(self, "_ptpu_uid", None) is not None:
            from paddle_tpu import framework

            self._ptpu_uid = None
            framework._program_uid(self)

    # ------------------------------------------------------------------
    @property
    def mesh(self):
        if self._mesh is None:
            self._mesh = mesh_lib.default_mesh()
        return self._mesh

    def _spec_for_state(self, name: str):
        from jax.sharding import PartitionSpec as P

        specs = self._strategy.sharding_specs if self._strategy else {}
        if name in specs:
            return P(*specs[name])
        if self._rules is not None:
            # rule resolution (regex scan + rank/divisibility checks)
            # runs once per name — state_sharding memoizes the resolved
            # NamedSharding, so the dispatch region never re-resolves
            # (warmup-time only; tools/check_hot_path.py guards the
            # sharding files)
            var = self._program.global_block()._find_var_recursive(name)
            shape = (tuple(var.shape)
                     if var is not None and var.shape is not None else None)
            spec = self._rules.spec_for(name, shape=shape)
            if shape and self._mesh_axes:
                # typed here, not as a raw device_put ValueError later
                self._rules.check_divisible(
                    name, spec, shape, self._mesh_axes)
            return spec
        return P()  # replicated

    def _spec_for_feed(self, name: str, ndim: int):
        from jax.sharding import PartitionSpec as P

        specs = self._strategy.sharding_specs if self._strategy else {}
        if name in specs:
            return P(*specs[name])
        if name in self._replicated_feeds:
            return P()  # unique-id-keyed prefetch rows, not batch rows
        if ndim >= 1 and self._batch_axis in self.mesh.axis_names:
            return P(self._batch_axis)  # shard batch dim, rest replicated
        return P()

    def _sharding(self, spec):
        from jax.sharding import NamedSharding

        sh = self._sharding_memo.get(spec)
        if sh is None:
            sh = self._sharding_memo[spec] = NamedSharding(self.mesh, spec)
        return sh

    # ------------------------------------------------------------------
    # Sharding resolution (memoized per name — the reader's sharded
    # prefetcher and the executor's per-run _shard_inputs both resolve
    # through here, so a steady-state step pays dict lookups only)
    # ------------------------------------------------------------------
    def state_sharding(self, name: str):
        sh = self._state_sh_memo.get(name)
        if sh is None:
            sh = self._state_sh_memo[name] = self._sharding(
                self._spec_for_state(name))
        return sh

    def feed_sharding(self, name: Optional[str], ndim: int,
                      steps_axis: bool = False):
        """NamedSharding for feed ``name`` with array rank ``ndim``.
        ``steps_axis=True`` treats the leading axis as a replicated
        per_step_feed ``steps`` axis and shifts the batch sharding one
        axis right (reader.device_buffered chunk assembly)."""
        from jax.sharding import PartitionSpec as P

        key = (name, int(ndim), bool(steps_axis))
        sh = self._feed_sh_memo.get(key)
        if sh is None:
            if steps_axis:
                spec = P(None, *self._spec_for_feed(name, ndim - 1))
            else:
                spec = self._spec_for_feed(name, ndim)
            sh = self._feed_sh_memo[key] = self._sharding(spec)
        return sh

    # ------------------------------------------------------------------
    # Executor integration
    # ------------------------------------------------------------------
    def _jit_kwargs(self, block, feed_names, fetch_names, state_mut, state_ro,
                    state_out, per_step_feed=False):
        mut_sh = {n: self.state_sharding(n) for n in state_mut}
        ro_sh = {n: self.state_sharding(n) for n in state_ro}

        feed_sh = {}
        for n in feed_names:
            var = block._find_var_recursive(n)
            ndim = len(var.shape) if var is not None and var.shape is not None else 1
            # Executor.run(steps=N, per_step_feed=True) stacks a leading
            # steps axis on every feed; keep it replicated and shift the
            # batch/seq sharding one axis right (steps_axis)
            feed_sh[n] = self.feed_sharding(
                n, ndim + 1 if per_step_feed else ndim,
                steps_axis=per_step_feed)
        # pin state OUTPUT layouts to the state input shardings (None =
        # compiler-chosen for the fetches subtree): the next step's
        # _shard_inputs then recognizes every fed-back state array by
        # identity and passes it through — without this the compiler may
        # pick a different output layout and force a device_put per
        # state array per step (O(n_params) hot-path rent)
        out_sh = {n: self.state_sharding(n) for n in state_out}
        return {"in_shardings": (mut_sh, ro_sh, feed_sh),
                "out_shardings": (None, out_sh)}

    # hot-path: begin shard_inputs (per-dispatch placement pass)
    def _shard_inputs(self, feed_arrays, mut_state, ro_state,
                      per_step_feed=False, steady_token=None):
        """Place feeds/state for the mesh.  Returns (feeds, mut, ro,
        restaged) where ``restaged`` holds the STATE arrays that had to
        be re-placed — the executor writes those back to the scope so a
        read-only param is resharded once, not per step.

        The placement check is inlined and ordered cheapest-first: a
        prefetcher-staged feed hits ``cur is sh`` (same memoized
        sharding object).  State goes one step further: once a full
        pass re-stages NOTHING under a ``steady_token`` (the executor's
        jit key), that token is marked steady and state checks are
        SKIPPED entirely — outputs are out_shardings-pinned, so the
        state the scope feeds back is correctly placed by construction.
        A scope var replaced behind our back surfaces as a loud pjit
        device-mismatch error, not silent corruption."""
        import jax
        from jax.sharding import NamedSharding

        device_put = jax.device_put
        feed_sharding = self.feed_sharding
        state_sharding = self.state_sharding
        cast_dtypes = self._cast_dtypes
        restaged: Dict[str, Any] = {}

        def put(arrs, sh_of, track=False, cast=False):
            out = {}
            for n, a in arrs.items():
                sh = sh_of(n, a)
                cur = getattr(a, "sharding", None)
                if cur is not None and (
                        cur is sh
                        or (type(cur) is NamedSharding
                            and cur.mesh is sh.mesh and cur.spec == sh.spec)):
                    out[n] = a
                else:
                    if cast and cast_dtypes:
                        # placement-time precision cast (cold: runs only
                        # on the restage pass, never in steady state —
                        # the value is the load-time host-staged array)
                        tgt = cast_dtypes.get(n)
                        if tgt is not None and np.dtype(a.dtype) != tgt:
                            a = np.asarray(a).astype(tgt)  # hot-ok: host-staged param, placement-time only
                    out[n] = device_put(a, sh)
                    if track:
                        restaged[n] = out[n]
            return out

        if per_step_feed:
            feed_sh = lambda n, a: feed_sharding(  # noqa: E731
                n, np.ndim(a), steps_axis=True)
        else:
            feed_sh = lambda n, a: feed_sharding(n, np.ndim(a))  # noqa: E731
        feed_out = put(feed_arrays, feed_sh)
        if steady_token is not None and steady_token in self._steady_tokens:
            return feed_out, mut_state, ro_state, restaged
        state_sh = lambda n, a: state_sharding(n)  # noqa: E731
        mut_out = put(mut_state, state_sh, track=True, cast=True)
        ro_out = put(ro_state, state_sh, track=True, cast=True)
        if steady_token is not None and not restaged:
            self._steady_tokens.add(steady_token)
        kind_of = getattr(self._rules, "state_kind", None)
        if kind_of is not None:
            # sharded TRAINING accounting: per-device param/grad/moment
            # bytes, published on every full placement pass (cold —
            # steady-state dispatches return above before reaching this)
            from paddle_tpu.sharding import train as _sh_train

            _sh_train.publish_state_bytes(kind_of, mut_out, ro_out)
        if restaged and self._rules is not None:
            # placement accounting (cold: restage is a warmup-time
            # event; a counter still moving in steady state means state
            # is re-placed per step — the bug this design prevents)
            n_sharded = sum(
                1 for n in restaged
                if any(e is not None
                       for e in tuple(state_sharding(n).spec)))
            if n_sharded:
                from paddle_tpu.sharding import metrics as _sh_metrics

                _sh_metrics.PARAMS_SHARDED.inc(n_sharded)
        return feed_out, mut_out, ro_out, restaged
    # hot-path: end shard_inputs

    # parity helpers --------------------------------------------------
    def _compile_data_parallel(self, *a, **k):  # reference: compiler.py:241
        return self

    def __repr__(self):
        ax = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)) if self._mesh else {}
        return "CompiledProgram(mesh=%s)" % (ax,)


class ParallelExecutor:
    """Legacy multi-device executor (reference: parallel_executor.py) —
    thin facade over CompiledProgram.with_data_parallel + Executor; the
    `run` signature matches the reference (fetch_list of names/vars,
    feed dict split across the dp mesh by the compiled program)."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None, build_strategy=None,
                 num_trainers=1, trainer_id=0, scope=None):
        from paddle_tpu import framework
        from paddle_tpu.executor import Executor
        from paddle_tpu.framework import CPUPlace, TPUPlace

        program = main_program or framework.default_main_program()
        self._compiled = CompiledProgram(program).with_data_parallel(
            loss_name=loss_name, build_strategy=build_strategy,
            exec_strategy=exec_strategy,
        )
        self._exe = Executor(TPUPlace(0) if use_cuda else CPUPlace())
        self._scope = scope

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        return self._exe.run(
            self._compiled, feed=feed or feed_dict, fetch_list=fetch_list,
            scope=self._scope, return_numpy=return_numpy,
        )
