"""Pipeline parallelism for fluid Programs (reference: PipelineOptimizer
optimizer.py:2665 cuts the program into sections run by SectionWorker
threads over blocking queues, framework/pipeline_trainer.cc,
section_worker.cc:141).

TPU-native design: the program's forward ops are CUT at the ``cut_list``
vars into K stages; the GPipe microbatch schedule is COMPILED — one
``lax.scan`` over M + K - 1 slots inside ``shard_map`` over the ``pp``
mesh axis, activations streaming stage-to-stage via ``lax.ppermute``
(the queue hop, but on ICI, inside the same XLA module as the compute).
Reverse-mode AD through the scan/ppermute yields the reference's 2K-1
backward sections automatically, and the optimizer update applies the
program optimizer's rule functionally.

Heterogeneous stages run under ``lax.switch`` on the device's pp
coordinate with a uniform padded activation buffer, so parameters are
replicated across the pp group (correct schedule + semantics; for
memory-scaling stage-sharded pipelining use the hybrid engine,
parallel/hybrid.py, where stages are homogeneous and stacked).
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["build_pipeline_step", "PipelinePlanError", "propose_cut_vars"]


class PipelinePlanError(ValueError):
    """A pipeline stage plan that cannot run: the cut vars don't yield
    the stage count the mesh's ``pp`` axis expects, a cut leaves a
    stage with zero ops, or no single-crossing cut boundary exists for
    the requested stage count.  Raised at plan time with both counts
    named — never as a raw %-format assert or an XLA shape error."""


def _stage_ranges(ops, cut_names: Sequence[str]):
    """Split the op list at the producers of the cut vars.  Returns
    (ranges, ordered_cut_names) with cuts re-sorted into program order so
    boundary i always binds activation cut i-1."""
    bounds = {}
    for c in cut_names:
        idx = None
        for i, op in enumerate(ops):
            if c in op.output_arg_names:
                idx = i
        if idx is None:
            raise PipelinePlanError(
                "cut var %r is not produced by the program" % c)
        bounds[c] = idx + 1
    ordered = sorted(cut_names, key=lambda c: bounds[c])
    cuts = [bounds[c] for c in ordered]
    if len(set(cuts)) != len(cuts):
        raise PipelinePlanError(
            "cut vars %r share a producer boundary" % (cut_names,))
    starts = [0] + cuts
    ends = cuts + [len(ops)]
    ranges = []
    for i, (s, e) in enumerate(zip(starts, ends)):
        if e <= s:
            at = ("before cut var %r" % ordered[i] if i < len(ordered)
                  else "after cut var %r" % ordered[-1])
            raise PipelinePlanError(
                "stage %d of %d (%s) would contain zero ops — the plan's "
                "%d cut vars do not split the program's %d ops into "
                "non-empty stages"
                % (i, len(cut_names) + 1, at, len(cut_names), len(ops)))
        ranges.append(slice(s, e))
    return ranges, ordered


def propose_cut_vars(ops, n_stages: int, skip_names: Sequence[str] = ()
                     ) -> List[str]:
    """Pick ``n_stages - 1`` cut vars that split ``ops`` into balanced
    stages, each boundary crossed by exactly ONE live intermediate (the
    single activation the GPipe hand-off can carry).

    ``skip_names``: names that don't count as crossing activations —
    params and feeds (replicated onto every stage, available everywhere).
    Raises :class:`PipelinePlanError` when fewer than ``n_stages - 1``
    single-crossing boundaries exist (e.g. a program whose layers share
    a materialized attention bias: every boundary carries two live vars,
    so no single cut var can express it — build with fused attention)."""
    if n_stages < 2:
        raise PipelinePlanError(
            "pipeline needs at least 2 stages (got %d)" % n_stages)
    skip = set(skip_names)
    produced_at: Dict[str, int] = {}
    last_use: Dict[str, int] = {}
    for i, op in enumerate(ops):
        for n in op.input_arg_names:
            if n not in skip:
                last_use[n] = i
        for n in op.output_arg_names:
            if n not in skip:
                produced_at[n] = i
    # boundary b (between op b-1 and op b) is cuttable when exactly one
    # live non-param/non-feed var crosses it AND that var's (last)
    # producer is op b-1 — _stage_ranges cuts at the producer, so any
    # other producer position would induce a different boundary
    candidates: Dict[int, str] = {}
    for b in range(1, len(ops)):
        crossing = [n for n, p in produced_at.items()
                    if p < b and last_use.get(n, -1) >= b]
        if len(crossing) == 1 and produced_at[crossing[0]] == b - 1:
            candidates[b] = crossing[0]
    if len(candidates) < n_stages - 1:
        raise PipelinePlanError(
            "program has %d single-crossing boundaries but %d stages "
            "need %d cut vars — multi-var boundaries (e.g. a shared "
            "materialized attention bias crossing every layer) cannot "
            "be pipelined; rebuild the program so each stage boundary "
            "carries one activation" % (len(candidates), n_stages,
                                        n_stages - 1))
    chosen: List[int] = []
    for j in range(1, n_stages):
        ideal = j * len(ops) / float(n_stages)
        best = min((b for b in candidates if b not in chosen),
                   key=lambda b: abs(b - ideal))
        chosen.append(best)
    return [candidates[b] for b in sorted(chosen)]


def build_pipeline_step(program, loss_name: str, plan: Dict[str, Any], mesh):
    """Compile one pipelined training step.

    Returns (step, state_names): ``step(state, feed) -> (loss, new_state)``
    jitted over ``mesh`` (axis 'pp'); state = params (+ momentum slots).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from paddle_tpu.core import lowering

    block = program.global_block()
    ops = [
        op for op in block.ops
        if op.attrs.get("op_role", "forward") in ("forward", "loss")
    ]
    M = int(plan["num_microbatches"])
    ranges, cut_names = _stage_ranges(ops, list(plan["cut_vars"]))
    K = len(ranges)
    pp_size = mesh.shape["pp"]
    if pp_size != K:
        raise PipelinePlanError(
            "op-stage plan has %d stages (%d cut vars) but the mesh's "
            "pp axis has %d devices — the schedule maps one stage per "
            "pp coordinate, so the counts must agree (add/remove cut "
            "vars or rebuild the mesh)" % (K, len(cut_names), pp_size)
        )

    param_names = sorted(p.name for p in program.all_parameters())
    trainable = {
        p.name for p in program.all_parameters() if getattr(p, "trainable", True)
    }
    feed_names = sorted(plan["feed_names"])

    # per-stage reads/writes to find each stage's params and feeds
    stage_ops = [ops[r] for r in ranges]

    def stage_trace(i):
        def fn(env):
            lowering.trace_ops(stage_ops[i], env, block)
            return env

        return fn

    # the program's own optimizer-update ops, replayed functionally on
    # the (state, grads) pair after AD — any registered optimizer works
    # in sections (reference: optimizer.py:2665 + section_worker.cc)
    update_descs = list(plan["update_descs"])
    grad_of = {d["inputs"]["Param"][0]: d["inputs"]["Grad"][0] for d in update_descs}
    aux_names = set()
    for d in update_descs:
        pname, gname = d["inputs"]["Param"][0], d["inputs"]["Grad"][0]
        for slot, names in d["inputs"].items():
            for nm in names:
                if nm not in (pname, gname):
                    aux_names.add(nm)
        for slot, names in d["outputs"].items():
            for nm in names:
                if nm not in (pname, gname):
                    aux_names.add(nm)
    aux_names -= set(param_names)

    def step(state: Dict[str, Any], feed: Dict[str, Any]):
        # shapes from the actual batch
        some = feed[feed_names[0]]
        B = some.shape[0]
        if B % M:
            raise ValueError("batch %d not divisible by %d microbatches" % (B, M))
        mb = B // M

        # microbatch stacks [M, mb, ...]
        feeds_mb = {
            n: jnp.reshape(feed[n], (M, mb) + tuple(feed[n].shape[1:]))
            for n in feed_names
        }

        params = {n: state[n] for n in param_names}
        # abstract-eval the full forward on one microbatch to size the
        # uniform activation buffer (cut var shapes differ per boundary)
        def full_fwd(params, fd):
            env = dict(params)
            env.update(fd)
            for i in range(K):
                stage_trace(i)(env)
            return {c: env[c] for c in cut_names}

        one_mb = {n: v[0] for n, v in feeds_mb.items()}
        cut_abstract = jax.eval_shape(full_fwd, params, one_mb)
        cut_shapes = {c: tuple(s.shape) for c, s in cut_abstract.items()}
        cut_dtypes = {c: s.dtype for c, s in cut_abstract.items()}
        flat_dims = {
            c: int(np.prod(shp[1:])) if len(shp) > 1 else 1
            for c, shp in cut_shapes.items()
        }
        maxd = max(flat_dims.values())
        # ring buffer dtype: wide enough for every boundary (bf16 cuts
        # travel as-is; mixing promotes)
        buf_dtype = jnp.result_type(*cut_dtypes.values())

        def run_local(params, feeds_mb):
            stage = jax.lax.axis_index("pp")

            def make_branch(i):
                def branch(act_in, mb_idx):
                    env = dict(params)
                    env.update({n: feeds_mb[n][mb_idx] for n in feed_names})
                    if i > 0:
                        cin = cut_names[i - 1]
                        shp = cut_shapes[cin]
                        env[cin] = (
                            act_in[:, : flat_dims[cin]]
                            .reshape(shp)
                            .astype(cut_dtypes[cin])
                        )
                    stage_trace(i)(env)
                    if i < K - 1:
                        cout = cut_names[i]
                        flat = env[cout].reshape(cut_shapes[cout][0], -1)
                        pad = maxd - flat.shape[1]
                        if pad:
                            flat = jnp.pad(flat, ((0, 0), (0, pad)))
                        return flat.astype(buf_dtype), jnp.zeros((), jnp.float32)
                    loss = env[loss_name].reshape(())
                    return jnp.zeros((mb, maxd), buf_dtype), loss.astype(jnp.float32)

                return branch

            branches = [make_branch(i) for i in range(K)]
            T = M + K - 1

            def body(carry, t):
                buf, loss_acc = carry
                mb_idx = jnp.clip(t - stage, 0, M - 1)
                out, loss_mb = jax.lax.switch(stage, branches, buf, mb_idx)
                valid = jnp.logical_and(t - stage >= 0, t - stage < M)
                loss_acc = loss_acc + jnp.where(
                    jnp.logical_and(valid, stage == K - 1), loss_mb, 0.0
                )
                # mask invalid-slot activations so garbage never reaches a
                # valid compute (defensive; the schedule already aligns)
                out = jnp.where(valid, out, 0.0)
                sent = jax.lax.ppermute(
                    out, "pp", [(i, (i + 1) % K) for i in range(K)]
                )
                return (sent, loss_acc), None

            init = (jnp.zeros((mb, maxd), buf_dtype), jnp.zeros((), jnp.float32))
            (_, loss_sum), _ = jax.lax.scan(body, init, jnp.arange(T))
            # PRE-psum local loss (nonzero on the last stage only).
            # Differentiating the replicated post-psum value would scale
            # grads by K: every device seeds cotangent 1 on an identical
            # total, and the joint SPMD reverse pass sums them.
            return loss_sum / M

        def local_step(state, feeds_mb):
            from paddle_tpu.core.registry import get_kernel

            params = {n: state[n] for n in param_names}
            loss_local, grads = jax.value_and_grad(run_local)(params, feeds_mb)
            loss = jax.lax.psum(loss_local, "pp")
            grads = {n: jax.lax.psum(g, "pp") for n, g in grads.items()}
            # weight decay (the program's regularization ops run on the
            # grad side, which AD-replay skips; reference:
            # regularizer.py append_regularization_ops grad += decay)
            for pname, (kind, coeff) in plan.get("decay", {}).items():
                if pname in grads:
                    p = params[pname]
                    extra = coeff * (jnp.sign(p) if kind == "l1" else p)
                    grads[pname] = grads[pname] + extra
            new_state = dict(state)
            for desc in update_descs:
                pname = desc["inputs"]["Param"][0]
                if pname not in trainable:
                    continue  # frozen params stay untouched (backward.py filter)
                gname = desc["inputs"]["Grad"][0]
                ins = {}
                for slot, names in desc["inputs"].items():
                    vals = []
                    for nm in names:
                        if nm == gname and slot == "Grad":
                            vals.append(grads[pname].astype(state[pname].dtype))
                        else:
                            vals.append(new_state[nm])
                    ins[slot] = vals
                outs = get_kernel(desc["type"])(ins, desc["attrs"])
                for slot, names in desc["outputs"].items():
                    val = outs.get(slot)
                    if val is None:
                        continue
                    vals = val if isinstance(val, (list, tuple)) else [val]
                    for nm, v in zip(names, vals):
                        if nm in new_state:
                            new_state[nm] = v.astype(new_state[nm].dtype)
            return loss, new_state

        from paddle_tpu.parallel import mesh as mesh_lib

        smapped = mesh_lib.shard_map(
            local_step,
            mesh=mesh,
            in_specs=(P(), {n: P() for n in feeds_mb}),
            out_specs=(P(), P()),
            check_vma=False,
        )
        return smapped(state, feeds_mb)

    # state = params + every optimizer aux var (moments, beta pows, lr) —
    # all are startup-initialized program vars pulled from the scope
    state_names = list(param_names) + sorted(aux_names)
    return step, state_names
