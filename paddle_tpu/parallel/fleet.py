"""Fleet: unified distributed training API.

Reference: python/paddle/fluid/incubate/fleet/base/fleet_base.py:37
(Fleet + DistributedOptimizer), base/role_maker.py:30-444 (role makers),
collective/__init__.py (Collective fleet + CollectiveOptimizer).

TPU-native: collective mode wraps the optimizer so ``minimize`` returns a
CompiledProgram bound to a mesh built from the role maker's world — the
transpiler NCCL2 rewrite (gen_nccl_id etc.) is unnecessary because the
jax runtime bootstraps the slice; multi-host init maps to
``jax.distributed.initialize``.
"""
from __future__ import annotations

import os
from typing import List, Optional

from paddle_tpu import framework
from paddle_tpu.parallel import mesh as mesh_lib
from paddle_tpu.parallel.compiled_program import CompiledProgram
from paddle_tpu.parallel.strategy import DistributedStrategy

__all__ = [
    "Fleet",
    "fleet",
    "DistributedOptimizer",
    "PaddleCloudRoleMaker",
    "UserDefinedRoleMaker",
    "UserDefinedCollectiveRoleMaker",
    "MPISymetricRoleMaker",
    "Role",
]


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._worker_endpoints: List[str] = []
        self._server_endpoints: List[str] = []
        self._role = Role.WORKER
        self._current_id = 0

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(1, len(self._worker_endpoints))

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return self._worker_endpoints

    def get_pserver_endpoints(self):
        return self._server_endpoints

    def generate_role(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    """Reads the launcher env (reference: role_maker.py:328 — the
    PADDLE_* contract kept verbatim so launch scripts port unchanged)."""

    def __init__(self, is_collective: bool = True):
        super().__init__()
        self._is_collective = is_collective
        self.generate_role()

    def generate_role(self):
        self._worker_endpoints = [
            e for e in os.getenv("PADDLE_TRAINER_ENDPOINTS", "").split(",") if e
        ]
        self._server_endpoints = [
            e for e in os.getenv("PADDLE_PSERVER_ENDPOINTS", "").split(",") if e
        ]
        role = os.getenv("TRAINING_ROLE", "TRAINER").upper()
        self._role = Role.SERVER if role == "PSERVER" else Role.WORKER
        self._current_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))


class UserDefinedRoleMaker(RoleMakerBase):
    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1, server_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = role
        self._worker_endpoints = ["127.0.0.1:%d" % (6170 + i) for i in range(worker_num)]
        self._server_endpoints = server_endpoints or []


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    """reference: role_maker.py UserDefinedCollectiveRoleMaker — all
    ranks are workers (collective mode), endpoints given explicitly."""

    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = current_id
        self._role = Role.WORKER
        self._worker_endpoints = list(worker_endpoints or ["127.0.0.1:6170"])


class MPISymetricRoleMaker(RoleMakerBase):
    """reference: role_maker.py:87 MPISymetricRoleMaker — even MPI ranks
    are workers, odd ranks servers.  Requires mpi4py at generate_role();
    on TPU pods prefer PaddleCloudRoleMaker (env contract) — the jax
    runtime bootstraps the slice without MPI."""

    def __init__(self):
        super().__init__()
        self._generated = False

    def generate_role(self):
        try:
            from mpi4py import MPI  # noqa: PLC0415
        except ImportError as e:
            raise RuntimeError(
                "MPISymetricRoleMaker needs mpi4py (not in this image); "
                "use PaddleCloudRoleMaker (PADDLE_* env contract) instead"
            ) from e
        comm = MPI.COMM_WORLD
        rank = comm.Get_rank()
        self._role = Role.WORKER if rank % 2 == 0 else Role.SERVER
        self._current_id = rank // 2
        # routable per-rank host (reference: get_ip() per node) — NOT
        # 127.0.0.1, which would point every endpoint at localhost on a
        # multi-host job
        import socket

        host = socket.gethostbyname(MPI.Get_processor_name() or
                                    socket.gethostname())
        hosts = comm.allgather("%s:%d" % (host, 6170 + rank))
        self._worker_endpoints = hosts[0::2]
        self._server_endpoints = hosts[1::2]
        self._generated = True


class Fleet:
    """Collective-mode fleet singleton (reference: fleet_base.py:37)."""

    def __init__(self):
        self._role_maker: Optional[RoleMakerBase] = None
        self._strategy: Optional[DistributedStrategy] = None
        self._inited = False

    def init(self, role_maker: Optional[RoleMakerBase] = None):
        self._role_maker = role_maker or PaddleCloudRoleMaker()
        self._role_maker.generate_role()
        self._inited = True
        # multi-host: hand the process set to the jax runtime
        n_hosts = len({e.split(":")[0] for e in self._role_maker.get_trainer_endpoints()})
        if n_hosts > 1 and os.getenv("PADDLE_TPU_DISTRIBUTED_INIT", "0") == "1":
            import jax

            jax.distributed.initialize()
        return self

    # --- introspection (reference API) ---
    def is_worker(self):
        return self._role_maker is None or self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker is not None and self._role_maker.is_server()

    def is_first_worker(self):
        return self._role_maker is None or self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index() if self._role_maker else 0

    def worker_num(self):
        return self._role_maker.worker_num() if self._role_maker else 1

    def worker_endpoints(self, to_string=False):
        eps = self._role_maker.get_trainer_endpoints() if self._role_maker else []
        return ",".join(eps) if to_string else eps

    def server_endpoints(self, to_string=False):
        eps = self._role_maker.get_pserver_endpoints() if self._role_maker else []
        return ",".join(eps) if to_string else eps

    def distributed_optimizer(self, optimizer, strategy: Optional[DistributedStrategy] = None):
        self._strategy = strategy or DistributedStrategy()
        return DistributedOptimizer(optimizer, self._strategy, self)

    # --- program lifecycle ---
    def init_worker(self):
        pass

    def init_server(self, *args, **kwargs):
        pass

    def run_server(self):
        pass

    def stop_worker(self):
        pass

    def save_inference_model(self, executor, dirname, feeded_var_names, target_vars,
                             main_program=None, export_for_deployment=True):
        from paddle_tpu import io

        return io.save_inference_model(dirname, feeded_var_names, target_vars, executor, main_program)

    def save_persistables(self, executor, dirname, main_program=None):
        from paddle_tpu import io

        return io.save_persistables(executor, dirname, main_program)

    @property
    def main_program(self):
        return getattr(self, "_compiled_program", None) or framework.default_main_program()


class DistributedOptimizer:
    """reference: CollectiveOptimizer (incubate/fleet/collective/
    __init__.py:157).  minimize() appends the normal backward+optimize
    ops, then binds a CompiledProgram over the fleet mesh; the gradient
    allreduce is GSPMD's, riding ICI."""

    def __init__(self, optimizer, strategy: DistributedStrategy, fleet_: Fleet):
        self._optimizer = optimizer
        self._strategy = strategy
        self._fleet = fleet_

    def backward(self, *a, **k):
        return self._optimizer.backward(*a, **k)

    def apply_gradients(self, params_grads):
        return self._optimizer.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        ops, pgs = self._optimizer.minimize(loss, startup_program, parameter_list, no_grad_set)
        strat = self._strategy
        if not strat.mesh_axes:
            strat.mesh_axes = {"dp": len(mesh_lib.local_devices())}
        compiled = CompiledProgram(loss.block.program).with_strategy(strat)
        self._fleet._compiled_program = compiled
        return ops, pgs

    def build_hybrid_train_step(self, mesh=None):
        """User-facing route to the 5D hybrid-parallel engine
        (``DistributedStrategy.hybrid`` = HybridConfig kwargs): builds the
        engine step wired to THIS optimizer's registered kernel — the
        reference reaches its parallel modes through the fleet optimizer
        the same way (incubate/fleet/collective/__init__.py:157,
        optimizer.py:2665 pipeline).

        Returns ``(step, helpers)``: ``step(params, aux, tokens, labels)
        -> (loss, new_params, new_aux)`` and ``helpers`` with
        ``init_params()/init_opt_state(params)/place(params, tokens,
        labels)/place_aux(aux)/mesh/config``.
        """
        from paddle_tpu.parallel import hybrid

        if not self._strategy.hybrid:
            raise ValueError(
                "build_hybrid_train_step needs DistributedStrategy.hybrid "
                "= dict of HybridConfig kwargs (dp/pp/tp/sp/ep + dims)"
            )
        cfg = hybrid.HybridConfig(**self._strategy.hybrid)
        step, place, mesh = hybrid.make_train_step(
            cfg, mesh=mesh, optimizer=self._optimizer
        )

        class _Helpers:
            config = cfg

            @staticmethod
            def init_params(seed=0):
                return hybrid.init_params(cfg, seed=seed)

            @staticmethod
            def init_opt_state(params):
                return hybrid.init_opt_state(cfg, params, self._optimizer)

        _Helpers.place = staticmethod(place)
        _Helpers.place_aux = staticmethod(step.place_aux)
        _Helpers.mesh = mesh
        return step, _Helpers


fleet = Fleet()
