"""Active collective-axis registry.

The reference keys NCCL comms by ring_id (platform/collective_helper.h:63).
Here a "ring" is a named mesh axis; the parallel executor binds axes while
tracing under shard_map, and collective ops query them.
"""
from __future__ import annotations

import contextlib
from typing import Dict, List

_active_axes: List[str] = []
_ring_to_axis: Dict[int, str] = {0: "data"}


def axis_for_ring(ring_id: int) -> str:
    return _ring_to_axis.get(int(ring_id), "data")


def set_ring_axis(ring_id: int, axis: str) -> None:
    _ring_to_axis[int(ring_id)] = axis


def axis_active(name: str) -> bool:
    return name in _active_axes


@contextlib.contextmanager
def active_axes(names):
    added = list(names)
    _active_axes.extend(added)
    try:
        yield
    finally:
        for n in added:
            _active_axes.remove(n)
