"""Collective program rewriters: GradAllReduce / LocalSGD.

Reference: python/paddle/fluid/transpiler/collective.py — Collective:36,
GradAllReduce:178 (insert c_allreduce_sum on each grad between backward
and optimize), LocalSGD:269 (periodic parameter averaging instead of
per-step allreduce).

TPU-native: the inserted ``c_allreduce_sum`` ops lower to `lax.psum` over
the mesh axis bound to their ring_id (ops/collective_ops.py, ring 0 ->
"dp") — they are identities outside a mapped axis, so the same rewritten
program runs single-device and under shard_map unchanged.  The GSPMD
CompiledProgram path does NOT need this rewrite (sharding inserts the
all-reduce); this is the explicit-collective path, matching the
reference's program surgery and useful when the user wants manual
control.
"""
from __future__ import annotations

from typing import List, Optional

from paddle_tpu import framework
from paddle_tpu.framework import Program

__all__ = ["Collective", "GradAllReduce", "LocalSGD"]


class Collective:
    """Base rewriter (reference: transpiler/collective.py:36).  The NCCL
    bootstrap ops (c_gen_nccl_id/c_comm_init) are appended to startup for
    parity; on TPU they are no-ops (the runtime owns comm setup)."""

    def __init__(self, nrings: int = 1):
        self.nrings = nrings
        self.nranks = 1
        self.rank = 0

    def transpile(self, startup_program: Program, main_program: Program,
                  rank: int, endpoints: List[str], current_endpoint: str,
                  wait_port: bool = True):
        self.rank = rank
        self.nranks = max(1, len(endpoints))
        self.startup_program = startup_program or framework.default_startup_program()
        self.main_program = main_program or framework.default_main_program()
        self._transpile_startup_program()
        self._transpile_main_program()
        self.main_program.version += 1
        return self

    def _transpile_startup_program(self):
        block = self.startup_program.global_block()
        for ring_id in range(self.nrings):
            block.append_op(type="c_gen_nccl_id", inputs={}, outputs={}, attrs={"ring_id": ring_id})
            block.append_op(
                type="c_comm_init",
                inputs={},
                outputs={},
                attrs={"ring_id": ring_id, "nranks": self.nranks, "rank": self.rank},
            )

    def _transpile_main_program(self):
        raise NotImplementedError

    # --- helpers ---
    def _grad_vars(self, block):
        """(param, grad_name, insert_idx): grads written by backward ops."""
        out = []
        params = {p.name for p in block.all_parameters() if getattr(p, "trainable", True)}
        for idx, op in enumerate(block.ops):
            if op.attrs.get("op_role") != "backward":
                continue
            for n in op.output_arg_names:
                if n.endswith(framework.GRAD_SUFFIX) and n[: -len(framework.GRAD_SUFFIX)] in params:
                    out.append((n[: -len(framework.GRAD_SUFFIX)], n, idx))
        return out

    def _first_optimize_idx(self, block):
        for idx, op in enumerate(block.ops):
            if op.attrs.get("op_role") == "optimize":
                return idx
        return len(block.ops)


class GradAllReduce(Collective):
    """reference: transpiler/collective.py:178."""

    def _transpile_main_program(self):
        block = self.main_program.global_block()
        grads = self._grad_vars(block)
        insert_at = self._first_optimize_idx(block)
        ring = 0
        ops = []
        for _, gname, _ in grads:
            ops.append(("c_allreduce_sum", gname, ring))
            ring = (ring + 1) % self.nrings
        # insert in reverse so indices stay valid
        for op_type, gname, ring_id in reversed(ops):
            block._insert_op(
                insert_at,
                type="scale",
                inputs={"X": [gname]},
                outputs={"Out": [gname]},
                attrs={"scale": 1.0 / self.nranks, "op_role": "backward"},
            )
            block._insert_op(
                insert_at,
                type=op_type,
                inputs={"X": [gname]},
                outputs={"Out": [gname]},
                attrs={"ring_id": ring_id, "op_role": "backward"},
            )


class LocalSGD(Collective):
    """reference: transpiler/collective.py:269 — every ``k_steps`` the
    params are averaged across ranks instead of per-step grad allreduce."""

    def __init__(self, nrings: int = 1, k_steps: int = 4):
        super().__init__(nrings)
        self.k_steps = k_steps

    def _transpile_main_program(self):
        from paddle_tpu import unique_name

        block = self.main_program.global_block()
        # step counter
        counter = block.create_var(
            name=unique_name.generate("@LOCAL_SGD_COUNTER@"),
            shape=[1], dtype="float32", persistable=True, stop_gradient=True,
        )
        sblock = self.startup_program.global_block()
        sblock.create_var(name=counter.name, shape=[1], dtype="float32", persistable=True)
        sblock.append_op(
            type="fill_constant",
            inputs={},
            outputs={"Out": [counter.name]},
            attrs={"shape": [1], "dtype": "float32", "value": 0.0},
        )
        block.append_op(
            type="scale",
            inputs={"X": [counter]},
            outputs={"Out": [counter]},
            attrs={"scale": 1.0, "bias": 1.0, "op_role": "optimize"},
        )
        # every k steps: param <- psum(param)/nranks  (gated in-graph)
        for p in block.all_parameters():
            if not getattr(p, "trainable", True):
                continue
            summed = block.create_var(
                name=unique_name.generate(p.name + "@LOCAL_SGD_AVG@"),
                shape=p.shape, dtype=p.dtype,
            )
            block.append_op(
                type="c_allreduce_sum",
                inputs={"X": [p]},
                outputs={"Out": [summed]},
                attrs={"ring_id": 0, "op_role": "optimize"},
            )
            block.append_op(
                type="local_sgd_select",
                inputs={"Param": [p], "Avg": [summed], "Step": [counter]},
                outputs={"Out": [p]},
                attrs={"k_steps": self.k_steps, "nranks": self.nranks, "op_role": "optimize"},
            )
