"""InferenceServer: a dynamic-batching front end over AnalysisPredictor.

The reference stack ships models to an external serving system
(Paddle Serving); this repo's TPU-native answer is in-process: a
single worker thread owns the predictor (the jitted XLA module is the
"replica"), a bounded queue + DynamicBatcher coalesce concurrent
requests, and a BucketPolicy pads every batch onto a fixed size ladder
so the executor's jit cache sees a CLOSED shape set — after
``warmup()`` pre-compiles each rung, steady-state serving performs
zero XLA compiles (asserted through Executor.jit_cache_stats, not
inferred from timing).

Lifecycle: construct (worker starts) -> warmup() -> submit()/Client
traffic -> stop(drain=True) for a graceful drain.

Observability: metrics live in the process-global registry
(``paddle_tpu.monitor``); ``start_admin()`` binds a localhost HTTP
surface exposing ``/metrics`` (Prometheus text exposition of the whole
registry) and ``/statusz`` (JSON snapshot: this server's metrics incl.
bucket-ladder occupancy and recompile counts, the predictor's jit-cache
stats, and the full registry).
"""
from __future__ import annotations

import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import monitor, profiler
from paddle_tpu.serving.batching import DynamicBatcher, ServingRequest
from paddle_tpu.serving.bucketing import BucketPolicy
from paddle_tpu.serving.errors import DeadlineExceeded, ServerClosed
from paddle_tpu.serving.metrics import ServingMetrics

__all__ = ["InferenceServer"]


class InferenceServer:
    """Wraps a predictor exposing ``run_padded`` / ``jit_cache_stats`` /
    ``get_input_names`` (AnalysisPredictor) behind a batched, bucketed,
    deadline-aware submit() API.

    ``input_specs`` (``{name: (per_row_shape, dtype)}``) defaults to the
    predictor's program-derived specs; pass it explicitly when a feed
    var has dynamic non-batch dims.
    """

    def __init__(
        self,
        predictor,
        max_batch_size: int = 32,
        batch_timeout_ms: float = 5.0,
        queue_capacity: int = 256,
        bucket_ladder: Optional[Sequence[int]] = None,
        input_specs: Optional[Dict[str, Tuple[tuple, Any]]] = None,
        name: str = "server",
    ):
        self.name = name
        self._predictor = predictor
        self._policy = BucketPolicy(max_batch_size, bucket_ladder)
        self._batcher = DynamicBatcher(
            max_batch_size, batch_timeout_ms, queue_capacity)
        self._metrics = ServingMetrics(name)
        self._specs = dict(input_specs) if input_specs else predictor.input_specs()
        self._feed_names = list(predictor.get_input_names())
        # non-blocking fetch (AnalysisPredictor return_numpy=False) lets
        # the worker overlap batch N's d2h with batch N+1's dispatch; a
        # duck-typed predictor without the kwarg just runs synchronously
        import inspect

        try:
            self._nonblocking = "return_numpy" in inspect.signature(
                predictor.run_padded).parameters
        except (TypeError, ValueError):
            self._nonblocking = False
        self._stop = threading.Event()
        self._closed = False           # admission gate (set before _stop on shutdown)
        self._admin = None             # optional HTTP surface (start_admin)
        self._admin_lock = threading.Lock()
        self._warmed = False
        self._baseline_misses: Optional[int] = None
        self._exec_lock = threading.Lock()  # warmup vs worker predictor use
        self._worker = threading.Thread(
            target=self._serve_loop, name="serving-%s" % name, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    @property
    def bucket_ladder(self) -> List[int]:
        return list(self._policy.ladder)

    @property
    def max_batch_size(self) -> int:
        return self._policy.max_batch_size

    def metrics(self) -> Dict[str, object]:
        snap = self._metrics.snapshot()
        snap["queue_depth"] = self._batcher.qsize()
        snap["bucket_ladder"] = self.bucket_ladder
        snap["warmed_up"] = self._warmed
        return snap

    def metrics_text(self) -> str:
        """Prometheus text exposition of the WHOLE process registry
        (this server's series are labeled ``server=<name>``)."""
        return monitor.render_text()

    def statusz(self) -> Dict[str, object]:
        """JSON-serializable status snapshot: this server's metrics
        (incl. bucket-ladder occupancy histogram and recompile counter),
        the predictor's jit-cache stats, and the process registry."""
        return {
            "server": self.name,
            "metrics": self.metrics(),
            "jit_cache": self._predictor.jit_cache_stats(),
            "registry": monitor.snapshot(),
        }

    # ------------------------------------------------------------------
    def start_admin(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        """Serve ``/metrics`` (text exposition) and ``/statusz`` (JSON)
        over HTTP on ``host:port`` (port 0 = ephemeral); returns the
        bound ``(host, port)``.  Stopped by ``stop()``."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _AdminHandler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    body = server.metrics_text().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/statusz":
                    body = json.dumps(
                        server.statusz(), sort_keys=True, default=str
                    ).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(404, "unknown path (try /metrics or /statusz)")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # keep scrapes out of stderr
                pass

        with self._admin_lock:
            if self._admin is not None:  # concurrent/repeat start: reuse
                return self._admin.server_address
            self._admin = ThreadingHTTPServer((host, port), _AdminHandler)
            self._admin_thread = threading.Thread(
                target=self._admin.serve_forever,
                name="serving-admin-%s" % self.name, daemon=True)
            self._admin_thread.start()
            return self._admin.server_address

    @property
    def admin_address(self) -> Optional[Tuple[str, int]]:
        return self._admin.server_address if self._admin is not None else None

    # ------------------------------------------------------------------
    def warmup(self, cache_dir: Optional[str] = None,
               configure_cache: bool = True) -> int:
        """Pre-compile every bucket rung; returns the number of XLA
        compiles the warmup performed.  Routes through jax's persistent
        compilation cache (bench_common.configure_compile_cache) when the
        repo-root helper is importable, so a warm disk cache makes repeat
        server starts cheap; synthetic rows are zeros (always in-range
        for int id feeds).  After warmup the recompile counter arms:
        any further jit-cache miss increments ``metrics()['recompiles']``.

        NOTE ``configure_cache=True`` mutates PROCESS-GLOBAL state (the
        JAX_COMPILATION_CACHE_* env vars + jax.config); pass
        ``configure_cache=False`` when the embedding application owns
        its own jax cache configuration.  Any failure to wire the cache
        (helper missing, or an unrelated ``bench_common`` shadowing it)
        degrades to cold compiles, never a crashed warmup.
        """
        if configure_cache:
            try:
                import bench_common

                bench_common.configure_compile_cache(
                    cache_dir or bench_common.HOME_CACHE_DIR)
            except (ImportError, AttributeError):
                pass  # standalone use / foreign bench_common: compile cold
        misses0 = self._predictor.jit_cache_stats()["misses"]
        for bucket in self._policy.ladder:
            feed = {
                name: np.zeros((bucket,) + tuple(shape), dtype)
                for name, (shape, dtype) in self._specs.items()
            }
            with self._exec_lock:
                with profiler.RecordEvent("serving/%s/warmup" % self.name):
                    self._predictor.run_padded(feed, n_valid=bucket)
        compiles = self._predictor.jit_cache_stats()["misses"] - misses0
        self._metrics.count("warmup_compiles", compiles)
        self._baseline_misses = self._predictor.jit_cache_stats()["misses"]
        self._warmed = True
        return compiles

    # ------------------------------------------------------------------
    def submit(self, feed, timeout_ms: Optional[float] = None) -> ServingRequest:
        """Enqueue one request; returns its future (ServingRequest).

        ``feed``: dict (or positional sequence) of arrays whose shared
        leading dim is the request's row count (1..max_batch_size).
        Raises ServerOverloaded when the queue is full, ServerClosed
        after stop(); the future raises DeadlineExceeded when
        ``timeout_ms`` elapses first.
        """
        if self._closed:
            raise ServerClosed("server %r is stopped" % self.name)
        feed, n_rows = self._normalize_feed(feed)
        deadline = (
            time.monotonic() + float(timeout_ms) / 1e3
            if timeout_ms is not None else None)
        req = ServingRequest(feed, n_rows, deadline)
        try:
            self._batcher.offer(req)
        except Exception:
            self._metrics.count("shed")
            raise
        self._metrics.count("requests")
        # close the submit-vs-stop race: if stop() won between the
        # admission check above and the offer, the worker may already be
        # gone — nothing would ever serve this queue, so fail the
        # stragglers (first completion wins, so a request the worker DID
        # pick up keeps its real result)
        if self._stop.is_set() and not self._worker.is_alive():
            self._fail_stragglers()
            if req.done():
                raise ServerClosed("server %r is stopped" % self.name)
        return req

    def _normalize_feed(self, feed) -> Tuple[Dict[str, np.ndarray], int]:
        if not isinstance(feed, dict):
            feed = dict(zip(self._feed_names, feed))
        if set(feed) != set(self._feed_names):
            raise ValueError(
                "feed names %s != endpoint inputs %s"
                % (sorted(feed), sorted(self._feed_names)))
        out, n_rows = {}, None
        for name, val in feed.items():
            shape, dtype = self._specs[name]
            # coerce to the spec dtype so every request produces the
            # SAME compiled signature the warmup buckets did — a stray
            # float64 feed must not become a novel compile
            arr = np.asarray(val, dtype=dtype)
            if arr.shape[1:] != tuple(shape):
                raise ValueError(
                    "feed %r rows have shape %s, endpoint expects %s"
                    % (name, arr.shape[1:], tuple(shape)))
            if n_rows is None:
                n_rows = arr.shape[0]
            elif arr.shape[0] != n_rows:
                raise ValueError(
                    "inconsistent request row counts: %r has %d rows, "
                    "expected %d" % (name, arr.shape[0], n_rows))
            out[name] = arr
        if not n_rows:
            raise ValueError("empty request (0 rows)")
        if n_rows > self._policy.max_batch_size:
            raise ValueError(
                "request of %d rows exceeds max_batch_size=%d — split it"
                % (n_rows, self._policy.max_batch_size))
        return out, n_rows

    # ------------------------------------------------------------------
    def _fail_stragglers(self) -> None:
        """Fail every request still queued once no worker will ever
        serve it — stuck requests must surface as typed errors, never
        hangs (the subsystem's core contract)."""
        for req in self._batcher.drain_pending():
            req.fail(ServerClosed("server %r stopped" % self.name))

    def _on_expired(self, req: ServingRequest) -> None:
        self._metrics.count("expired")
        req.fail(DeadlineExceeded("deadline passed while queued"))

    def _serve_loop(self) -> None:
        # one batch of d2h kept in flight: dispatch batch N+1 (async jit
        # call, return_numpy=False) BEFORE materializing batch N's
        # outputs, so N's device compute + d2h overlap N+1's host-side
        # merge/pad/dispatch.  With work in flight the batcher is only
        # POLLED (block=False): if no live request is ready the pending
        # batch finalizes immediately — never parked behind an idle (or
        # all-expired) queue.
        pending = None
        while True:
            batch = self._batcher.next_batch(
                self._stop, self._on_expired, block=pending is None)
            if batch is None:
                if pending is not None:
                    self._finalize(*pending)
                    pending = None
                    continue  # re-enter blocking wait
                return  # stopped and drained
            nxt = self._execute(batch)
            if pending is not None:
                self._finalize(*pending)
            if nxt is not None and not self._nonblocking:
                # synchronous predictor: outs are already materialized —
                # deferring would just delay completions by one batch
                self._finalize(*nxt)
                nxt = None
            pending = nxt

    def _execute(self, batch: List[ServingRequest]):
        """Merge + pad + DISPATCH one batch (non-blocking fetch); returns
        the pending tuple for _finalize, or None on failure."""
        valid = sum(r.n_rows for r in batch)
        try:
            merged = {
                name: (
                    np.concatenate([r.feed[name] for r in batch], axis=0)
                    if len(batch) > 1 else batch[0].feed[name])
                for name in self._feed_names
            }
            bucket = self._policy.bucket_for(valid)
            padded = self._policy.pad_feed(merged, bucket)
            misses0 = self._predictor.jit_cache_stats()["misses"]
            t0 = time.perf_counter()
            kw = {"return_numpy": False} if self._nonblocking else {}
            with self._exec_lock:
                with profiler.RecordEvent("serving/%s/batch" % self.name):
                    outs = self._predictor.run_padded(
                        padded, n_valid=valid, **kw)
            recompiled = self._predictor.jit_cache_stats()["misses"] > misses0
        except BaseException as exc:  # noqa: BLE001 — fail the batch, keep serving
            self._metrics.count("failed", len(batch))
            for r in batch:
                r.fail(exc)
            return None
        return (batch, outs, valid, bucket, t0, recompiled)

    def _finalize(self, batch: List[ServingRequest], outs, valid: int,
                  bucket: int, t0: float, recompiled: bool) -> None:
        """Materialize a dispatched batch (the d2h sync) and complete its
        requests.  Deferred XLA runtime errors surface here — fail the
        batch, keep serving.  The batch is observed HERE so ``run_s``
        spans dispatch -> outputs materialized (the real batch duration;
        timing only the async dispatch call would report ~0)."""
        try:
            outs = [np.asarray(o) for o in outs]
        except BaseException as exc:  # noqa: BLE001
            self._metrics.count("failed", len(batch))
            for r in batch:
                r.fail(exc)
            return
        self._metrics.observe_batch(
            valid, bucket, time.perf_counter() - t0,
            recompiled=recompiled and self._warmed)
        off = 0
        now = time.perf_counter()
        for r in batch:
            per_req = [
                o[off:off + r.n_rows]
                if o.ndim >= 1 and o.shape[0] == valid else o
                for o in outs
            ]
            off += r.n_rows
            r.complete(per_req)
            self._metrics.observe_request(now - r.submit_t)

    # ------------------------------------------------------------------
    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Shut down.  ``drain=True`` (graceful): stop admitting, finish
        every queued request, then join the worker.  ``drain=False``:
        queued-but-unstarted requests fail with ServerClosed."""
        self._closed = True
        with self._admin_lock:
            admin, self._admin = self._admin, None
        if admin is not None:
            admin.shutdown()
            admin.server_close()
        if not drain:
            # empty the queue before releasing the worker so it cannot
            # start work we are abandoning
            self._fail_stragglers()
        self._stop.set()
        self._worker.join(timeout)
        # a submit() that raced past the admission check may have
        # enqueued AFTER the worker drained and exited — fail it (and
        # anything else left) rather than leaving its future pending
        if not self._worker.is_alive():
            self._fail_stragglers()
        # retire this instance's series from the registry exposition;
        # metrics()/statusz() keep working off the detached children
        self._metrics.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=exc == (None, None, None))
        return False
