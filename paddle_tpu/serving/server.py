"""InferenceServer: a dynamic-batching front end over AnalysisPredictor.

The reference stack ships models to an external serving system
(Paddle Serving); this repo's TPU-native answer is in-process: N
replica worker threads (one per predictor — typically one per device)
sit behind ONE bounded queue + DynamicBatcher, a dispatcher routes each
coalesced batch to the least-loaded live replica (per-replica in-flight
accounting), and a BucketPolicy pads every batch onto a fixed size
ladder so each replica's jit cache sees a CLOSED shape set — after
``warmup()`` pre-compiles each rung on EVERY replica, steady-state
serving performs zero XLA compiles fleet-wide (asserted through
Executor.jit_cache_stats, not inferred from timing).

Replica fleet semantics: a batch whose replica fails is re-routed to a
live replica (accepted requests never drop with a survivor available);
a replica that fails repeatedly is retired from routing, and
``remove_replica()`` drains one gracefully at runtime.

Lifecycle: construct (workers start) -> warmup() -> submit()/Client
traffic -> stop(drain=True) for a graceful drain.

Observability: metrics live in the process-global registry
(``paddle_tpu.monitor``); ``start_admin()`` binds a localhost HTTP
surface exposing ``/metrics`` (Prometheus text exposition of the whole
registry — or OpenMetrics 1.0 with exemplars when the scraper sends
``Accept: application/openmetrics-text``), ``/statusz`` (JSON snapshot:
this server's metrics incl. bucket-ladder occupancy, per-replica
health, and recompile counts, the predictors' jit-cache stats, and the
full registry), and ``/tracez`` (the flight recorder's tail-sampled
slow/errored request traces).

Request-scoped tracing: each request carries a trace id (minted by the
Client or passed to ``submit(trace_id=...)``); while a batch executes,
the replica worker installs a ``monitor.trace_context`` so every span
in the chain — queue wait, merge/pad/dispatch, executor h2d /
device_execute / d2h, materialize — is attributable to the requests in
the batch, and replica workers register named thread lanes so the
fleet renders as parallel tracks in the merged Chrome trace.  With a
``monitor.flight_recorder()`` installed, batches additionally run under
a span capture and slow/errored/deadline-missed requests retain their
full span trees.
"""
from __future__ import annotations

import contextlib
import json
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import monitor, profiler
from paddle_tpu.faults.metrics import BACKEND_HALFOPEN_PROBES
from paddle_tpu.monitor import flight as _flight
from paddle_tpu.monitor import spans as _mon_spans
from paddle_tpu.serving.admission import (
    ADMISSION_EXPIRED,
    PRIORITY_LOW,
    PRIORITY_NORMAL,
    BrownoutController,
)
from paddle_tpu.serving.batching import DynamicBatcher, ServingRequest
from paddle_tpu.serving.bucketing import BucketPolicy
from paddle_tpu.serving.errors import (
    DeadlineExceeded,
    ServerClosed,
    ServerOverloaded,
    ServingError,
)
from paddle_tpu.serving.metrics import ServingMetrics

__all__ = ["InferenceServer"]

# dispatched-but-not-finalized batches a replica may hold: one executing
# (async dispatch, d2h pending) + one queued behind it — the same
# double-buffer depth the single-worker server ran, now per replica.
# The batcher queue (NOT replica queues) stays the admission buffer, so
# shedding and drain semantics are unchanged.
_MAX_IN_FLIGHT = 2

# consecutive batch failures before a replica is retired from routing
_REPLICA_FAIL_LIMIT = 3

# request-facing dtype aliases: the same shared map AnalysisPredictor
# dispatches by, so submit() can never admit a spelling the predictor
# would then reject (one dict lookup, no contrib import)
from paddle_tpu.core.types import PRECISION_ALIASES as _PRECISION_ALIASES

# safety-net bound for the routing capacity wait (real wakeups are
# notifies from _release/_retire/stop)
_ROUTE_WAIT_S = 0.5


class _Replica:
    """One predictor behind the shared batcher: its own worker thread,
    bounded in-flight accounting, and health state."""

    __slots__ = ("idx", "name", "predictor", "nonblocking", "precision",
                 "lock", "q", "thread", "alive", "in_flight", "executed",
                 "failed", "consec_failures", "retired_at", "removed")

    def __init__(self, idx: int, predictor):
        self.idx = idx
        self.name = "r%d" % idx
        self.predictor = predictor
        # non-blocking fetch (AnalysisPredictor return_numpy=False) lets
        # the replica overlap batch N's d2h with batch N+1's dispatch; a
        # duck-typed predictor without the kwarg runs synchronously.
        # precision-variant dispatch (run_padded precision=) is detected
        # the same way so duck-typed test predictors keep working.
        import inspect

        try:
            params = inspect.signature(predictor.run_padded).parameters
            self.nonblocking = "return_numpy" in params
            self.precision = "precision" in params
        except (TypeError, ValueError):
            self.nonblocking = False
            self.precision = False
        self.lock = threading.Lock()  # warmup vs worker predictor use
        self.q: "queue.Queue" = queue.Queue()  # (batch, retries) | None
        self.thread: Optional[threading.Thread] = None
        self.alive = True
        self.in_flight = 0  # guarded by the server's _route_cv
        self.executed = 0
        self.failed = 0
        self.consec_failures = 0
        self.retired_at = None  # monotonic stamp of failure retirement
        self.removed = False    # remove_replica(): never re-admit


class InferenceServer:
    """Wraps one or more predictors exposing ``run_padded`` /
    ``jit_cache_stats`` / ``get_input_names`` (AnalysisPredictor) behind
    a batched, bucketed, deadline-aware submit() API.

    ``predictor``: a single predictor, or a SEQUENCE of predictors —
    one replica each (e.g. one AnalysisPredictor per device) — behind
    the same queue with least-loaded routing.

    ``input_specs`` (``{name: (per_row_shape, dtype)}``) defaults to the
    first predictor's program-derived specs; pass it explicitly when a
    feed var has dynamic non-batch dims.
    """

    def __init__(
        self,
        predictor,
        max_batch_size: int = 32,
        batch_timeout_ms: float = 5.0,
        queue_capacity: int = 256,
        bucket_ladder: Optional[Sequence[int]] = None,
        input_specs: Optional[Dict[str, Tuple[tuple, Any]]] = None,
        name: str = "server",
        readmit_cooldown_s: Optional[float] = None,
        target_queue_wait_ms: float = 50.0,
        brownout_hold_s: float = 0.25,
        class_weights="default",
        embedding_cache=None,
    ):
        self.name = name
        # circuit-breaker re-admission for failure-retired replicas: a
        # retired replica goes half-open after this cooldown and takes
        # ONE probe batch (it rejoins routing with a single remaining
        # strike — the probe's success resets the streak, a failure
        # re-retires immediately).  None (default) keeps retirement
        # terminal, the pre-existing behavior.
        self._readmit_cooldown = (
            float(readmit_cooldown_s) if readmit_cooldown_s is not None
            else None)
        predictors = (
            list(predictor) if isinstance(predictor, (list, tuple))
            else [predictor])
        if not predictors:
            raise ValueError("InferenceServer needs at least one predictor")
        self._replicas = [_Replica(i, p) for i, p in enumerate(predictors)]
        self._predictor = predictors[0]  # single-replica compat surface
        self._nonblocking = self._replicas[0].nonblocking
        self._policy = BucketPolicy(max_batch_size, bucket_ladder)
        self._batcher = DynamicBatcher(
            max_batch_size, batch_timeout_ms, queue_capacity, name=name,
            target_wait_ms=target_queue_wait_ms,
            class_weights=class_weights)
        self._metrics = ServingMetrics(name)
        # queue-level drops (priority eviction / offer-time sweep) route
        # through the server's accounting, not the batcher's defaults
        self._batcher.on_shed = self._on_queue_shed
        self._batcher.on_expired = self._on_expired
        # hot-id embedding cache (serving/embedding_cache.py): bound to
        # every replica's program so sparse lookups read through it, and
        # to the brownout ladder — a 4th rung serves CACHE-ONLY under
        # sustained saturation (misses get the fallback row instead of
        # queuing on PS pulls), so Zipf-skewed traffic degrades
        # gracefully through a PS outage
        self._embedding_cache = embedding_cache
        if embedding_cache is not None:
            for p in predictors:
                embedding_cache.bind(p)
        # deterministic degradation ladder, driven by queue pressure
        # from the dispatcher loop (L1 drops flight capture, L2 forces
        # eager batching, L3 sheds the lowest priority class, and — on
        # embedding-cache endpoints — L4 serves lookups cache-only)
        thresholds = (
            BrownoutController.THRESHOLDS
            + (BrownoutController.CACHE_ONLY_THRESHOLD,)
            if embedding_cache is not None else None)
        self._brownout = BrownoutController(
            name, hold_s=brownout_hold_s, thresholds=thresholds)
        self._admission_expired = ADMISSION_EXPIRED.labels(server=name)
        self._specs = (
            dict(input_specs) if input_specs else predictors[0].input_specs())
        self._feed_names = list(predictors[0].get_input_names())
        # mixed-precision endpoints: the serving dtypes, default first
        # (AnalysisPredictor.precision_dtypes); warmup compiles every
        # bucket rung for EVERY entry so the per-request choice (policy
        # default vs fp32 opt-out) never compiles
        dts = getattr(predictors[0], "precision_dtypes", None)
        if callable(dts) and self._replicas[0].precision:
            self._precision_dtypes = [str(d) for d in dts()]
        else:
            self._precision_dtypes = ["fp32"]
        self._default_dtype = self._precision_dtypes[0]
        # rungs already compiled on every replica (warmup + replan
        # barriers); replan_ladder only warms the DELTA
        self._warmed_rungs: set = set()
        self._autotune_thread: Optional[threading.Thread] = None
        self._autotune_stop: Optional[threading.Event] = None
        self._replan_lock = threading.Lock()
        self._stop = threading.Event()
        self._closed = False           # admission gate (set before _stop on shutdown)
        self._abort = False            # stop(drain=False): fail instead of route
        self._admin = None             # optional HTTP surface (start_admin)
        self._admin_lock = threading.Lock()
        self._warmed = False
        self._route_cv = threading.Condition()  # replica in_flight/alive state
        for rep in self._replicas:
            rep.thread = threading.Thread(
                target=self._replica_loop, args=(rep,),
                name="serving-%s-%s" % (name, rep.name), daemon=True)
            rep.thread.start()
        self._worker = threading.Thread(
            target=self._dispatch_loop, name="serving-%s" % name, daemon=True)
        self._worker.start()

    # ------------------------------------------------------------------
    @property
    def bucket_ladder(self) -> List[int]:
        return list(self._policy.ladder)

    @property
    def max_batch_size(self) -> int:
        return self._policy.max_batch_size

    @property
    def num_replicas(self) -> int:
        """Live (routable) replica count."""
        with self._route_cv:
            return sum(1 for r in self._replicas if r.alive)

    def replica_stats(self) -> Dict[str, Dict[str, object]]:
        """Per-replica health/throughput snapshot (the in-flight
        accounting behind least-loaded routing)."""
        with self._route_cv:
            return {
                r.name: {
                    "alive": r.alive,
                    "in_flight": r.in_flight,
                    "executed": r.executed,
                    "failed": r.failed,
                    "nonblocking": r.nonblocking,
                }
                for r in self._replicas
            }

    def metrics(self) -> Dict[str, object]:
        snap = self._metrics.snapshot()
        snap["queue_depth"] = self._batcher.qsize()
        snap["admit_limit"] = self._batcher.queue.limit
        snap["brownout_level"] = self._brownout.level
        snap["bucket_ladder"] = self.bucket_ladder
        snap["batch_timeout_ms"] = self._batcher.batch_timeout_s * 1e3
        # exported so a recorded /statusz snapshot is a complete input
        # for tools/autotune_ladder.py (ladder + histogram + wait EWMA)
        snap["queue_wait_ewma_ms"] = round(
            self._batcher.queue.wait_ewma_ms, 3)
        snap["precision_dtypes"] = list(self._precision_dtypes)
        snap["warmed_up"] = self._warmed
        snap["replicas"] = self.replica_stats()
        if self._embedding_cache is not None:
            snap["embedding_cache"] = self._embedding_cache.stats()
        return snap

    def load(self) -> Dict[str, object]:
        """The overload-control load report: queue depth, the adaptive
        admit limit, and the brownout level.  Rides in every wire
        response meta so the fleet balancer folds REPORTED load (the
        server's actual backlog) into least-loaded routing, not just its
        own in-flight counts."""
        return {
            "queue_depth": self._batcher.qsize(),
            "admit_limit": self._batcher.queue.limit,
            "brownout_level": self._brownout.level,
        }

    def metrics_text(self) -> str:
        """Prometheus text exposition of the WHOLE process registry
        (this server's series are labeled ``server=<name>``)."""
        return monitor.render_text()

    def tracez(self) -> Dict[str, object]:
        """The ``/tracez`` document: the process flight recorder's
        tail-sampled slow/errored/deadline-missed request traces (empty
        shell when no recorder is installed)."""
        rec = _flight.get()
        if rec is None:
            return {"recorder": False, "retained": 0, "requests": []}
        doc = rec.statusz()
        doc["recorder"] = True
        return doc

    def statusz(self) -> Dict[str, object]:
        """JSON-serializable status snapshot: this server's metrics
        (incl. bucket-ladder occupancy histogram, per-replica health,
        and recompile counter), the predictors' jit-cache stats, and the
        process registry."""
        doc = {
            "server": self.name,
            "metrics": self.metrics(),
            "jit_cache": self._predictor.jit_cache_stats(),
            "replica_jit_cache": {
                r.name: r.predictor.jit_cache_stats() for r in self._replicas
            },
            "registry": monitor.snapshot(),
        }
        sharding = {}
        for r in self._replicas:
            stats_fn = getattr(r.predictor, "sharding_stats", None)
            if callable(stats_fn) and getattr(r.predictor, "sharded", False):
                sharding[r.name] = stats_fn()
        if sharding:
            # each replica here is a model-parallel GROUP of devices;
            # the capacity math ("does the model fit one chip's
            # share?") reads hbm_bytes_per_device vs replicated_bytes
            doc["sharding"] = sharding
        pipeline = {}
        for r in self._replicas:
            pstats_fn = getattr(r.predictor, "pipeline_stats", None)
            if callable(pstats_fn):
                pipeline[r.name] = pstats_fn()
        if pipeline:
            # a pipelined replica is a pp-GROUP of devices behind one
            # name; the schedule math ("is the bubble amortized?") reads
            # bubble_ratio vs microbatches_last
            doc["pipeline"] = pipeline
        return doc

    # ------------------------------------------------------------------
    def start_admin(self, host: str = "127.0.0.1", port: int = 0) -> Tuple[str, int]:
        """Serve ``/metrics`` (Prometheus text exposition; OpenMetrics
        1.0 with exemplars when the scraper sends ``Accept:
        application/openmetrics-text``), ``/statusz`` (JSON), and
        ``/tracez`` (flight-recorder tail-sampled request traces) over
        HTTP on ``host:port`` (port 0 = ephemeral); returns the bound
        ``(host, port)``.  Stopped by ``stop()``."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        server = self

        class _AdminHandler(BaseHTTPRequestHandler):
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                if path == "/metrics":
                    om = "application/openmetrics-text" in (
                        self.headers.get("Accept") or "")
                    text, ctype = monitor.expose(openmetrics=om)
                    body = text.encode("utf-8")
                elif path == "/statusz":
                    body = json.dumps(
                        server.statusz(), sort_keys=True, default=str
                    ).encode("utf-8")
                    ctype = "application/json"
                elif path == "/tracez":
                    body = json.dumps(
                        server.tracez(), sort_keys=True, default=str
                    ).encode("utf-8")
                    ctype = "application/json"
                else:
                    self.send_error(
                        404,
                        "unknown path (try /metrics, /statusz or /tracez)")
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # keep scrapes out of stderr
                pass

        with self._admin_lock:
            if self._admin is not None:  # concurrent/repeat start: reuse
                return self._admin.server_address
            self._admin = ThreadingHTTPServer((host, port), _AdminHandler)
            self._admin_thread = threading.Thread(
                target=self._admin.serve_forever,
                name="serving-admin-%s" % self.name, daemon=True)
            self._admin_thread.start()
            return self._admin.server_address

    @property
    def admin_address(self) -> Optional[Tuple[str, int]]:
        return self._admin.server_address if self._admin is not None else None

    # ------------------------------------------------------------------
    def warmup(self, cache_dir: Optional[str] = None,
               configure_cache: bool = True) -> int:
        """Pre-compile every bucket rung on EVERY replica (the
        zero-recompile guarantee must hold fleet-wide — a cold replica
        would compile on its first routed batch); returns the total
        number of XLA compiles the warmup performed.  Routes through
        jax's persistent compilation cache
        (bench_common.configure_compile_cache) when the repo-root helper
        is importable — replica 2..N of an identical model typically
        loads replica 1's compiles from the disk cache; synthetic rows
        are zeros (always in-range for int id feeds).  After warmup the
        recompile counter arms: any further jit-cache miss on any
        replica increments ``metrics()['recompiles']``.

        NOTE ``configure_cache=True`` mutates PROCESS-GLOBAL state (the
        JAX_COMPILATION_CACHE_* env vars + jax.config); pass
        ``configure_cache=False`` when the embedding application owns
        its own jax cache configuration.  Any failure to wire the cache
        (helper missing, or an unrelated ``bench_common`` shadowing it)
        degrades to cold compiles, never a crashed warmup.
        """
        if configure_cache:
            try:
                import bench_common

                bench_common.configure_compile_cache(
                    cache_dir or bench_common.HOME_CACHE_DIR)
            except (ImportError, AttributeError):
                pass  # standalone use / foreign bench_common: compile cold
        compiles = self._warm_rungs(self._policy.ladder)
        for rep in self._replicas:
            # a mesh-spanning (sharded) replica publishes its per-device
            # HBM footprint now that warmup placed every param per its
            # rule (sharding_group_hbm_bytes gauge, one series per
            # model-parallel group)
            stats_fn = getattr(rep.predictor, "sharding_stats", None)
            if callable(stats_fn) and getattr(rep.predictor, "sharded",
                                              False):
                stats_fn(group="%s/%s" % (self.name, rep.name))
            # a pipelined replica publishes its schedule shape (bubble
            # ratio + per-stage occupancy gauges) once warmup compiled
            # every rung's GPipe executable
            pstats_fn = getattr(rep.predictor, "pipeline_stats", None)
            if callable(pstats_fn):
                self._metrics.set_pipeline(pstats_fn())
        self._metrics.count("warmup_compiles", compiles)
        self._warmed = True
        return compiles

    def _warm_rungs(self, rungs) -> int:
        """Compile ``rungs`` on every replica, for EVERY precision
        dtype the endpoint serves, skipping rungs already warmed —
        shared by ``warmup()`` and the autotuner's re-plan barrier
        (a new ladder compiles HERE, while the old ladder still serves
        traffic, so a ladder change never serves a cold cache).
        Returns the number of XLA compiles performed."""
        compiles = 0
        todo = [b for b in rungs if b not in self._warmed_rungs]
        if not todo:
            return 0
        for rep in self._replicas:
            misses0 = rep.predictor.jit_cache_stats()["misses"]
            for bucket in todo:
                feed = {
                    name: np.zeros((bucket,) + tuple(shape), dtype)
                    for name, (shape, dtype) in self._specs.items()
                }
                for pdtype in (self._precision_dtypes if rep.precision
                               else (None,)):
                    kw = {"precision": pdtype} if pdtype is not None else {}
                    with rep.lock:
                        with profiler.RecordEvent(
                                "serving/%s/warmup" % self.name):
                            rep.predictor.run_padded(
                                feed, n_valid=bucket, **kw)
            compiles += rep.predictor.jit_cache_stats()["misses"] - misses0
        self._warmed_rungs.update(todo)
        return compiles

    # ------------------------------------------------------------------
    def replan_ladder(self, ladder: Optional[Sequence[int]] = None,
                      batch_timeout_ms: Optional[float] = None,
                      max_rungs: int = 8) -> Dict[str, object]:
        """Re-plan the bucket ladder behind a warmup barrier.

        With ``ladder=None`` the new ladder (and, unless overridden,
        the batch window) comes from ``serving.autotune.plan`` over
        this server's observed arrival-size histogram and queue-wait
        EWMA.  Any NEW rungs are compiled on every replica (every
        precision dtype) BEFORE the policy reference is swapped, so a
        re-plan never causes a recompiled request — the old ladder
        keeps serving until the new one is hot.  Returns the applied
        plan; increments ``serving_ladder_replans_total`` only when the
        ladder actually changed."""
        from paddle_tpu.serving import autotune

        with self._replan_lock:
            proposal = None
            if ladder is None:
                proposal = autotune.plan(
                    self._metrics.arrival_histogram(),
                    self.max_batch_size, self._policy.ladder,
                    queue_wait_ewma_ms=self._batcher.queue.wait_ewma_ms,
                    current_timeout_ms=self._batcher.batch_timeout_s * 1e3,
                    max_rungs=max_rungs)
                ladder = proposal["ladder"]
                if batch_timeout_ms is None:
                    batch_timeout_ms = proposal["batch_timeout_ms"]
            new_policy = BucketPolicy(self.max_batch_size, ladder)
            changed = new_policy.ladder != self._policy.ladder
            compiles = 0
            if changed:
                compiles = self._warm_rungs(new_policy.ladder)  # barrier
                self._policy = new_policy  # atomic reference swap
                self._metrics.count_replan()
                monitor.record_instant(
                    "serving/ladder_replan", cat="serving",
                    server=self.name, ladder=str(new_policy.ladder))
            if batch_timeout_ms is not None:
                self._batcher.batch_timeout_s = float(batch_timeout_ms) / 1e3
            return {
                "ladder": list(new_policy.ladder),
                "changed": changed,
                "barrier_compiles": compiles,
                "batch_timeout_ms": (
                    float(batch_timeout_ms) if batch_timeout_ms is not None
                    else self._batcher.batch_timeout_s * 1e3),
                **({"proposal": proposal} if proposal else {}),
            }

    def start_autotuner(self, interval_s: float = 10.0,
                        max_rungs: int = 8) -> None:
        """Periodic online re-plan: every ``interval_s`` the autotuner
        thread re-derives the ladder + batch window from the live
        arrival histogram and applies any change behind the warmup
        barrier.  Idempotent; stopped by ``stop()``."""
        if self._autotune_thread is not None:
            return
        self._autotune_stop = threading.Event()

        def _loop():
            while not self._autotune_stop.wait(interval_s):
                try:
                    self.replan_ladder(max_rungs=max_rungs)
                except Exception as e:  # noqa: BLE001 — keep re-planning
                    # a failed re-plan must never kill the tuner loop
                    # (the server keeps serving on the current ladder);
                    # leave a timeline breadcrumb instead of stderr
                    monitor.record_instant(
                        "serving/ladder_replan_error", cat="serving",
                        server=self.name, error=repr(e))

        self._autotune_thread = threading.Thread(
            target=_loop, name="serving-%s-autotune" % self.name,
            daemon=True)
        self._autotune_thread.start()

    # ------------------------------------------------------------------
    def submit(self, feed, timeout_ms: Optional[float] = None,
               trace_id: Optional[str] = None,
               parent_span: Optional[str] = None,
               priority: int = PRIORITY_NORMAL,
               precision: Optional[str] = None) -> ServingRequest:
        """Enqueue one request; returns its future (ServingRequest).

        ``precision``: compiled-variant choice on a mixed-precision
        endpoint — None serves the policy default, ``"fp32"`` is the
        per-request opt-out; both are pre-compiled by warmup, so the
        choice never costs an XLA compile.  An unknown dtype fails
        typed here, before anything enqueues.

        ``feed``: dict (or positional sequence) of arrays whose shared
        leading dim is the request's row count (1..max_batch_size).
        ``priority`` is the admission class (lower = more important,
        ``serving.admission.PRIORITY_*``): a full queue sheds
        strictly-lower-priority entries first, and brownout level 3
        sheds the lowest class outright.  ``trace_id`` joins the request
        to a caller-owned trace (the Client mints one per call); spans
        recorded while its batch executes carry it.  ``parent_span`` is
        the submitter-side span id this request's spans parent under
        (client infer span, or the wire server's request span on a
        transport hop).  Raises ServerOverloaded (with a computed
        ``retry_after_ms`` hint) when shed, ServerClosed after stop();
        a ``timeout_ms`` that is already <= 0 — expired work arriving
        over the wire — fails fast typed at admission
        (``admission_expired_total``) instead of dispatching stale work.
        """
        if self._closed:
            raise ServerClosed("server %r is stopped" % self.name)
        if timeout_ms is not None and float(timeout_ms) <= 0:
            # deadline propagation fail-fast: the remaining deadline the
            # wire hop carried is already gone — shed at admission, never
            # burn a batch slot dispatching work nobody is waiting for
            self._admission_expired.inc()
            self._metrics.count("expired")
            raise DeadlineExceeded(
                "deadline exhausted before admission (%.1f ms)"
                % float(timeout_ms))
        if _faults.active is not None:  # disarmed: one is-None gate
            _faults.active.faultpoint(
                "server.admit", server=self.name, priority=int(priority))
        # sample the ladder HERE too: at L3 the door sheds low priority
        # before anything enqueues, so low-priority-only traffic would
        # otherwise never wake the parked dispatcher and the level
        # could latch at 3 on an idle server forever
        self._apply_brownout(
            self._brownout.update(self._batcher.depth_ratio()))
        if (self._brownout.level >= 3
                and int(priority) >= PRIORITY_LOW):
            # brownout L3: the lowest priority class sheds at the door
            self._metrics.count("shed")
            raise ServerOverloaded(
                "brownout level %d sheds priority %d"
                % (self._brownout.level, int(priority)),
                retry_after_ms=self._batcher.queue.retry_after_ms())
        if precision is not None:
            precision = _PRECISION_ALIASES.get(
                str(precision).lower(), str(precision))
            if precision not in self._precision_dtypes:
                raise ValueError(
                    "unknown precision %r for endpoint %r (serves %s)"
                    % (precision, self.name, self._precision_dtypes))
            if precision == self._default_dtype:
                precision = None  # one batch group for the default
        feed, n_rows = self._normalize_feed(feed)
        self._metrics.observe_arrival(n_rows)
        deadline = (
            time.monotonic() + float(timeout_ms) / 1e3
            if timeout_ms is not None else None)
        req = ServingRequest(feed, n_rows, deadline, trace_id=trace_id,
                             parent_span=parent_span, priority=priority,
                             precision=precision)
        try:
            self._batcher.offer(req)
        except Exception:
            self._metrics.count("shed")
            raise
        self._metrics.count("requests")
        # close the submit-vs-stop race: if stop() won between the
        # admission check above and the offer, the dispatcher may already
        # be gone — nothing would ever serve this queue, so fail the
        # stragglers (first completion wins, so a request the dispatcher
        # DID pick up keeps its real result)
        if self._stop.is_set() and not self._worker.is_alive():
            self._fail_stragglers()
            if req.done():
                raise ServerClosed("server %r is stopped" % self.name)
        return req

    def _normalize_feed(self, feed) -> Tuple[Dict[str, np.ndarray], int]:
        if not isinstance(feed, dict):
            feed = dict(zip(self._feed_names, feed))
        if set(feed) != set(self._feed_names):
            raise ValueError(
                "feed names %s != endpoint inputs %s"
                % (sorted(feed), sorted(self._feed_names)))
        out, n_rows = {}, None
        for name, val in feed.items():
            shape, dtype = self._specs[name]
            # coerce to the spec dtype so every request produces the
            # SAME compiled signature the warmup buckets did — a stray
            # float64 feed must not become a novel compile
            arr = np.asarray(val, dtype=dtype)
            if arr.shape[1:] != tuple(shape):
                raise ValueError(
                    "feed %r rows have shape %s, endpoint expects %s"
                    % (name, arr.shape[1:], tuple(shape)))
            if n_rows is None:
                n_rows = arr.shape[0]
            elif arr.shape[0] != n_rows:
                raise ValueError(
                    "inconsistent request row counts: %r has %d rows, "
                    "expected %d" % (name, arr.shape[0], n_rows))
            out[name] = arr
        if not n_rows:
            raise ValueError("empty request (0 rows)")
        if n_rows > self._policy.max_batch_size:
            raise ValueError(
                "request of %d rows exceeds max_batch_size=%d — split it"
                % (n_rows, self._policy.max_batch_size))
        return out, n_rows

    # ------------------------------------------------------------------
    def _apply_brownout(self, level: int) -> None:
        """Side effects of a (possibly new) brownout level that live
        outside the controller: the embedding cache's cache-only rung
        engages at the ladder's 4th threshold and releases — with the
        controller's 4x-slower descent hysteresis — when the ladder
        steps back down."""
        if self._embedding_cache is not None:
            self._embedding_cache.set_cache_only(level >= 4)

    def _fail_stragglers(self) -> None:
        """Fail every request still queued once no worker will ever
        serve it — stuck requests must surface as typed errors, never
        hangs (the subsystem's core contract)."""
        for req in self._batcher.drain_pending():
            req.fail(ServerClosed("server %r stopped" % self.name))

    def _on_queue_shed(self, req: ServingRequest,
                       retry_after_ms: float) -> None:
        """A queued request evicted by priority shedding: counted as a
        shed (it never ran) and failed typed with the retry hint."""
        self._metrics.count("shed")
        req.fail(ServerOverloaded(
            "evicted by a higher-priority request",
            retry_after_ms=retry_after_ms))

    def _on_expired(self, req: ServingRequest) -> None:
        self._metrics.count("expired")
        fr = _flight.get()
        if fr is not None:
            # deadline-missed requests are always tail-sampled; the
            # client's span attaches to this record when its future
            # raises (flight merges by trace id)
            fr.consider(
                req.trace_id, time.perf_counter() - req.submit_t,
                "deadline", (), server=self.name)
        req.fail(DeadlineExceeded("deadline passed while queued"))

    # ------------------------------------------------------------------
    # Dispatcher: one thread owns the batcher (single-consumer
    # coalescing) and routes each batch to the least-loaded live replica
    # ------------------------------------------------------------------
    def _dispatch_loop(self) -> None:
        _mon_spans.set_thread_lane("serving/%s/dispatcher" % self.name)
        try:
            while True:
                # one pressure sample per dispatch turn drives the
                # brownout ladder; eager batching (L2+) collapses the
                # coalescing window so a saturated server ships what it
                # has instead of waiting for more
                level = self._brownout.update(self._batcher.depth_ratio())
                self._batcher.eager = level >= 2
                self._apply_brownout(level)
                batch = self._batcher.next_batch(
                    self._stop, self._on_expired, block=True)
                if batch is None:
                    return  # stopped and drained
                self._maybe_readmit()
                self._route(batch, retries=max(1, len(self._replicas)))
        finally:
            for rep in self._replicas:
                rep.q.put(None)  # drain sentinel (idempotent)

    def _maybe_readmit(self) -> None:
        """Half-open re-admission pass (readmit_cooldown_s set): a
        failure-retired replica whose cooldown elapsed rejoins routing
        with one remaining strike — the next routed batch IS the probe
        (success resets the streak in _finalize, failure re-retires in
        _replica_failure)."""
        if self._readmit_cooldown is None:
            return
        now = time.monotonic()
        with self._route_cv:
            for rep in self._replicas:
                if (rep.alive or rep.removed or rep.retired_at is None
                        or now - rep.retired_at < self._readmit_cooldown):
                    continue
                rep.alive = True
                rep.retired_at = None
                rep.consec_failures = _REPLICA_FAIL_LIMIT - 1
                BACKEND_HALFOPEN_PROBES.labels(
                    pool="server/%s" % self.name).inc()
                monitor.record_instant(
                    "serving/replica_readmit", cat="serving",
                    server=self.name, replica=rep.name)
                self._route_cv.notify_all()

    def _pick_replica(self, exclude: Optional[_Replica]):
        """Least-loaded live replica with capacity, or None.  Caller
        holds ``_route_cv``."""
        live = [r for r in self._replicas
                if r.alive and r is not exclude
                and r.in_flight < _MAX_IN_FLIGHT]
        if not live:
            return None
        return min(live, key=lambda r: r.in_flight)

    def _route(self, batch: List[ServingRequest], retries: int,
               exclude: Optional[_Replica] = None) -> None:
        """Hand a coalesced batch to a replica (least loaded wins);
        blocks while every live replica is at its in-flight bound —
        the batcher queue, not replica queues, is the admission buffer.
        With no live replica (or an aborting stop) the batch fails
        typed, never hangs.

        The enqueue happens INSIDE the routing lock: a replica thread
        marks itself dead under the same lock before its final queue
        drain, so every put either targets a replica that will still
        drain it or never picks the dead one — a batch can never strand
        in a queue nobody serves."""
        rep = None
        with self._route_cv:
            while True:
                if self._abort:
                    break
                rep = self._pick_replica(exclude)
                if rep is None and exclude is not None:
                    # the excluded (failing) replica is the only one
                    # left: routing back would loop, so give up
                    if not any(r.alive and r is not exclude
                               for r in self._replicas):
                        break
                if rep is not None:
                    rep.in_flight += 1
                    rep.q.put((batch, retries))
                    return
                if not any(r.alive for r in self._replicas):
                    break
                self._route_cv.wait(timeout=_ROUTE_WAIT_S)
        exc: Exception
        if self._abort or self._closed:
            exc = ServerClosed("server %r is stopped" % self.name)
        else:
            exc = ServingError(
                "no live replicas on server %r" % self.name)
        self._metrics.count("failed", len(batch))
        for r in batch:
            r.fail(exc)

    def _release(self, rep: _Replica) -> None:
        with self._route_cv:
            rep.in_flight -= 1
            self._route_cv.notify_all()

    def _retire_replica(self, rep: _Replica) -> None:
        with self._route_cv:
            rep.alive = False
            rep.retired_at = time.monotonic()  # re-admission cooldown
            self._route_cv.notify_all()

    def _count_requeue(self, rep: _Replica) -> None:
        """One re-routed batch: the ``serving_requeued_total`` counter
        and the timeline marker move together (tests assert they agree),
        tagged with the replica the batch bounced off."""
        self._metrics.count("requeued")
        monitor.record_instant(
            "serving/batch_requeue", cat="serving",
            server=self.name, replica=rep.name)

    def _requeue(self, rep: _Replica, batch: List[ServingRequest],
                 retries: int) -> None:
        """Re-route a batch off ``rep`` — failing already-expired
        requests fast with DeadlineExceeded BEFORE they burn a
        retry/replica slot (an expired request re-routed to a survivor
        would occupy real capacity just to be shed there)."""
        live = []
        for r in batch:
            if r.expired():
                self._on_expired(r)
            else:
                live.append(r)
        if not live:
            return
        self._count_requeue(rep)
        self._route(live, retries, exclude=rep)

    def _replica_exit(self, rep: _Replica) -> None:
        """Terminal bookkeeping for a replica thread: mark dead under
        the routing lock (so no further _route can pick it — the put is
        inside the same lock), then drain anything that landed before
        the mark.  Without this a late failure re-route could strand a
        batch in an exited replica's queue forever."""
        self._retire_replica(rep)
        self._drain_replica_queue(rep)

    # ------------------------------------------------------------------
    def remove_replica(self, replica, timeout: float = 30.0) -> None:
        """Gracefully remove one replica at runtime: stop routing to it,
        wait for its in-flight work to finish (re-routing anything still
        queued).  ``replica``: index or ``r<idx>`` name.  Refuses to
        remove the last live replica (stop() the server instead).

        The replica's thread parks as a cheap re-route forwarder until
        the server stops — it must outlive the removal so a batch routed
        concurrently with it cannot strand in a dead queue."""
        if isinstance(replica, int):
            rep = self._replicas[replica]
        else:
            matches = [r for r in self._replicas if r.name == str(replica)]
            if not matches:
                raise ValueError("unknown replica %r" % (replica,))
            rep = matches[0]
        with self._route_cv:
            if not rep.alive:
                return  # already retired/removed
            if sum(1 for r in self._replicas if r.alive) <= 1:
                raise ValueError(
                    "cannot remove the last live replica of server %r"
                    % self.name)
            monitor.record_instant(
                "serving/replica_drain", cat="serving",
                server=self.name, replica=rep.name)
            rep.alive = False
            rep.removed = True  # deliberate: re-admission never undoes it
            self._route_cv.notify_all()
            deadline = time.monotonic() + timeout
            while rep.in_flight > 0 and time.monotonic() < deadline:
                self._route_cv.wait(timeout=0.1)

    # ------------------------------------------------------------------
    # Replica worker: per-replica double buffer — dispatch batch N+1
    # (async jit call, return_numpy=False) BEFORE materializing batch
    # N's outputs, so N's device compute + d2h overlap N+1's host-side
    # merge/pad/dispatch.
    # ------------------------------------------------------------------
    def _replica_loop(self, rep: _Replica) -> None:
        # stable named lane per replica worker: the merged Chrome trace
        # renders the fleet as parallel tracks
        _mon_spans.set_thread_lane(
            "serving/%s/%s worker" % (self.name, rep.name))
        pending = None
        _unset = object()
        while True:
            item = _unset
            if not rep.alive:
                # retired (failure) or removed (remove_replica): finish
                # the in-flight batch, re-route the rest, then PARK as a
                # forwarder until the server-wide stop sentinel — a
                # batch routed concurrently with the retirement can
                # still land in this queue, and exiting early would
                # strand it (the request would hang to its deadline)
                if pending is not None:
                    self._finalize(rep, *pending)
                    pending = None
                self._drain_replica_queue(rep)
                item = rep.q.get()
                if item is not None and not rep.alive:
                    batch, retries = item
                    self._release(rep)
                    self._requeue(rep, batch, retries)
                    continue
                # item is the stop sentinel (exit below), or the replica
                # was RE-ADMITTED while parked (half-open probe): the
                # batch that just arrived is the probe — serve it via
                # the normal path
            if item is _unset:
                if pending is None:
                    item = rep.q.get()
                else:
                    try:
                        item = rep.q.get_nowait()
                    except queue.Empty:
                        self._finalize(rep, *pending)
                        pending = None
                        continue  # re-enter blocking wait
            if item is None:
                if pending is not None:
                    self._finalize(rep, *pending)
                    pending = None
                self._replica_exit(rep)
                return  # server drained
            batch, retries = item
            live = []
            for r in batch:
                # deadlines are re-checked at the replica: a batch can
                # sit behind a slow predecessor after routing
                if r.expired():
                    self._on_expired(r)
                else:
                    live.append(r)
            if not live:
                self._release(rep)
                continue
            nxt = self._execute(rep, live, retries)
            if pending is not None:
                self._finalize(rep, *pending)
                pending = None
            if nxt is not None and not rep.nonblocking:
                # synchronous predictor: outs are already materialized —
                # deferring would just delay completions by one batch
                self._finalize(rep, *nxt)
                nxt = None
            pending = nxt

    def _drain_replica_queue(self, rep: _Replica) -> None:
        """Re-route (never drop) batches queued on a dead replica.  A
        stop sentinel encountered mid-drain is RE-QUEUED, not swallowed
        — it is the one-per-replica shutdown signal, and consuming it
        here would park the forwarder loop's next ``rep.q.get()``
        forever (stop() would hang on the join)."""
        saw_sentinel = False
        while True:
            try:
                item = rep.q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                saw_sentinel = True
                continue
            batch, retries = item
            self._release(rep)  # give up this replica's slot...
            self._requeue(rep, batch, retries)  # ...take one elsewhere
        if saw_sentinel:
            rep.q.put(None)

    # hot-path: begin serve_execute (merge/pad/dispatch; the d2h sync lives
    # in _finalize, one batch behind)
    def _execute(self, rep: _Replica, batch: List[ServingRequest],
                 retries: int):
        """Merge + pad + DISPATCH one batch on ``rep`` (non-blocking
        fetch); returns the pending tuple for _finalize, or None on
        failure (the failure path re-routes or fails the requests).

        Tracing: with a session or flight recorder live, the whole
        merge/pad/dispatch runs under the batch's trace context (so the
        executor's h2d/execute spans carry the requests' ids) and —
        recorder only — under a span capture whose buffer rides the
        pending tuple into _finalize; otherwise the only rent is two
        gate checks."""
        valid = sum(r.n_rows for r in batch)
        # brownout L1+: flight-recorder capture is the first rent shed
        # under sustained saturation (tracing is a luxury; goodput isn't)
        fr = _flight.get() if self._brownout.level < 1 else None
        cap = [] if fr is not None else None
        tids = ()
        if cap is not None or _mon_spans.recording():
            tids = tuple(r.trace_id for r in batch if r.trace_id)
        try:
            with contextlib.ExitStack() as stack:
                if cap is not None:
                    stack.enter_context(_mon_spans.capture(cap))
                if tids or cap is not None:
                    now = time.perf_counter()
                    for r in batch:
                        # per-request queue wait: submit -> picked up
                        # here, each span owning its single trace id and
                        # parenting under its submitter's span (client
                        # infer span / wire server request span)
                        with _mon_spans.trace_context(
                                (r.trace_id,) if r.trace_id else ()):
                            _mon_spans.record_span(
                                "serving/queue_wait", r.submit_t,
                                now - r.submit_t, cat="serving",
                                parent=r.parent_span,
                                server=self.name, replica=rep.name,
                                n_rows=r.n_rows)
                    stack.enter_context(_mon_spans.trace_context(tids))
                    if len(batch) == 1 and batch[0].parent_span:
                        # an unshared batch can keep a fully connected
                        # tree: the batch/predictor/executor spans graft
                        # under the request's submitter span (a shared
                        # batch has no single parent — its subtree roots
                        # at the RecordEvent batch span instead)
                        stack.enter_context(
                            _mon_spans.parent_scope(batch[0].parent_span))
                if _faults.active is not None:  # disarmed: one is-None gate
                    _faults.active.faultpoint(
                        "replica.dispatch", server=self.name,
                        replica=rep.name)
                merged = {
                    name: (
                        np.concatenate([r.feed[name] for r in batch], axis=0)
                        if len(batch) > 1 else batch[0].feed[name])
                    for name in self._feed_names
                }
                bucket = self._policy.bucket_for(valid)
                padded = self._policy.pad_feed(merged, bucket)
                misses0 = rep.predictor.jit_cache_stats()["misses"]
                t0 = time.perf_counter()
                kw = {"return_numpy": False} if rep.nonblocking else {}
                # one batch = one precision variant (the batcher never
                # mixes); the select itself is a dict lookup downstream
                prec = getattr(batch[0], "precision", None)
                if prec is not None and rep.precision:
                    kw["precision"] = prec
                with rep.lock:
                    with profiler.RecordEvent("serving/%s/batch" % self.name):
                        outs = rep.predictor.run_padded(
                            padded, n_valid=valid, **kw)
                recompiled = (
                    rep.predictor.jit_cache_stats()["misses"] > misses0)
        except BaseException as exc:  # noqa: BLE001 — reroute/fail, keep serving
            self._replica_failure(rep, batch, retries, exc, cap=cap)
            return None
        return (batch, outs, valid, bucket, t0, recompiled, retries, cap)
    # hot-path: end serve_execute

    def _replica_failure(self, rep: _Replica, batch: List[ServingRequest],
                         retries: int, exc: BaseException,
                         cap: Optional[list] = None) -> None:
        """A batch failed on ``rep``: retire the replica when it fails
        repeatedly, and re-route the batch to a surviving replica so
        accepted requests don't drop — only with no survivor (or no
        retry budget) do the requests fail.  Terminally-failed requests
        are always tail-sampled (with whatever spans the batch captured
        before dying); a re-routed batch is not recorded here — it may
        still complete cleanly on the survivor."""
        rep.failed += 1
        rep.consec_failures += 1
        if rep.consec_failures >= _REPLICA_FAIL_LIMIT and rep.alive:
            # an incident marker ONLY for failure retirement (the clean
            # shutdown path also retires replicas — that is not an
            # incident); near-zero cost, gated on recording
            monitor.record_instant(
                "serving/replica_retired", cat="serving",
                server=self.name, replica=rep.name)
            self._retire_replica(rep)
        self._release(rep)
        with self._route_cv:
            survivors = any(
                r.alive and r is not rep for r in self._replicas)
        if retries > 0 and survivors:
            self._requeue(rep, batch, retries - 1)
            return
        self._metrics.count("failed", len(batch))
        fr = _flight.get()
        if fr is not None:
            now = time.perf_counter()
            for r in batch:
                fr.consider(
                    r.trace_id, now - r.submit_t, "error", cap or (),
                    server=self.name, replica=rep.name,
                    error=repr(exc))
        for r in batch:
            r.fail(exc)

    def _finalize(self, rep: _Replica, batch: List[ServingRequest], outs,
                  valid: int, bucket: int, t0: float, recompiled: bool,
                  retries: int, cap: Optional[list] = None) -> None:
        """Materialize a dispatched batch (the d2h sync) and complete its
        requests.  Deferred XLA runtime errors surface here — same
        reroute-or-fail handling as a dispatch failure.  The batch is
        observed HERE so ``run_s`` spans dispatch -> outputs materialized
        (the real batch duration; timing only the async dispatch call
        would report ~0).  ``cap``: the span buffer _execute captured
        for this batch (flight recorder live) — the materialize span
        joins it, then each request is tail-sampled."""
        tids = ()
        rec = cap is not None or _mon_spans.recording()
        if rec:
            tids = tuple(r.trace_id for r in batch if r.trace_id)
        try:
            with contextlib.ExitStack() as stack:
                if cap is not None:
                    stack.enter_context(_mon_spans.capture(cap))
                if tids:
                    stack.enter_context(_mon_spans.trace_context(tids))
                if rec and len(batch) == 1 and batch[0].parent_span:
                    # unshared batch: the d2h span keeps the connected
                    # tree (same graft rule as _execute)
                    stack.enter_context(
                        _mon_spans.parent_scope(batch[0].parent_span))
                if rec:
                    m0 = time.perf_counter()
                outs = [np.asarray(o) for o in outs]
                if rec:
                    _mon_spans.record_span(
                        "serving/materialize", m0,
                        time.perf_counter() - m0, cat="serving",
                        server=self.name, replica=rep.name)
        except BaseException as exc:  # noqa: BLE001
            self._replica_failure(rep, batch, retries, exc, cap=cap)
            return
        rep.executed += 1
        rep.consec_failures = 0
        self._metrics.observe_batch(
            valid, bucket, time.perf_counter() - t0,
            recompiled=recompiled and self._warmed, replica=rep.name)
        self._metrics.count_precision(
            getattr(batch[0], "precision", None) or self._default_dtype,
            len(batch))
        off = 0
        now = time.perf_counter()
        for r in batch:
            per_req = [
                o[off:off + r.n_rows]
                if o.ndim >= 1 and o.shape[0] == valid else o
                for o in outs
            ]
            off += r.n_rows
            r.complete(per_req)
            self._metrics.observe_request(now - r.submit_t,
                                          trace_id=r.trace_id)
        fr = _flight.get() if cap is not None else None
        if fr is not None:
            # tail-sampling decision per request: slow ones keep the
            # batch's full span tree (shared spans, per-request record)
            for r in batch:
                fr.consider(
                    r.trace_id, now - r.submit_t, "ok", cap,
                    server=self.name, replica=rep.name,
                    bucket=int(bucket), n_rows=int(r.n_rows))
        self._release(rep)

    # ------------------------------------------------------------------
    def stop(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Shut down.  ``drain=True`` (graceful): stop admitting, finish
        every queued request, then join the dispatcher and replicas.
        ``drain=False``: queued-but-unstarted requests fail with
        ServerClosed (batches already routed to a replica still
        complete)."""
        self._closed = True
        if self._autotune_stop is not None:
            self._autotune_stop.set()
            if self._autotune_thread is not None:
                self._autotune_thread.join(timeout=5.0)
                self._autotune_thread = None
        with self._admin_lock:
            admin, self._admin = self._admin, None
        if admin is not None:
            admin.shutdown()
            admin.server_close()
        if drain:
            monitor.record_instant(
                "serving/server_drain", cat="serving", server=self.name)
        else:
            # empty the queue before releasing the dispatcher so it
            # cannot route work we are abandoning
            self._abort = True
            self._fail_stragglers()
        self._stop.set()
        self._batcher.wake()
        with self._route_cv:
            self._route_cv.notify_all()
        # one shared deadline across every join — N wedged threads must
        # not stretch the caller's bound to (1+N) x timeout
        deadline = (time.monotonic() + timeout) if timeout is not None else None

        def _remaining():
            return (None if deadline is None
                    else max(0.0, deadline - time.monotonic()))

        self._worker.join(_remaining())
        for rep in self._replicas:
            rep.thread.join(_remaining())
        # a submit() that raced past the admission check may have
        # enqueued AFTER the dispatcher drained and exited — fail it
        # (and anything else left) rather than leaving its future pending
        if not self._worker.is_alive():
            self._fail_stragglers()
        # retire this instance's series from the registry exposition;
        # metrics()/statusz() keep working off the detached children
        self._metrics.close()
        self._batcher.close()
        self._brownout.close()
        ADMISSION_EXPIRED.remove_labels(server=self.name)
        if any(getattr(r.predictor, "sharded", False)
               for r in self._replicas):
            from paddle_tpu.sharding.metrics import GROUP_HBM_BYTES

            for rep in self._replicas:
                GROUP_HBM_BYTES.remove_labels(
                    group="%s/%s" % (self.name, rep.name))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop(drain=exc == (None, None, None))
        return False
