"""Speculative decoding: draft-then-verify rounds in the slot pool.

One token per target step is the autoregressive tax.  Speculative
decoding (Leviathan et al., ICML 2023) pays it with a SMALL draft
model: per round the draft proposes ``k - 1`` tokens one at a time,
then the target verifies all ``k`` consumed positions in ONE K-wide
forward (``decoding.make_transformer_lm_pooled_verify_fn``) — exactly
the prefill-shaped call the rung ladder already compiles, so the whole
round is one warmed ``spec_chunk`` executable per (slot, length) rung
pair and zero new shapes.

Acceptance is **greedy-exact**: a drafted token is accepted iff it
equals the target's own greedy argmax at that position, so the emitted
sequence is bit-identical to non-speculative greedy decode no matter
how bad the draft is (parity-pinned; a weak draft only costs speed).
The round's algebra, per slot (``pos`` = tokens consumed so far):

* consumption ``j`` eats position ``q_j = pos + j``: the stored prompt
  token while ``q_j < prompt_len`` (teacher forcing — prefill runs
  K-wide through the same call), else the draft's proposal;
* the chain stays alive through ``j`` iff every consumed draft token so
  far matched the target's prediction for its position; the target's
  ``argmax(logits[:, j])`` is the (verified) token for ``q_j + 1`` and
  is emitted while the chain is alive and past the prompt;
* ``pos`` advances by the accepted length (1..k): rejected positions'
  cache rows are simply re-written next round — the pool's
  write-before-read invariant makes rollback free, for the target AND
  the draft cache (both are state leaves the executables thread
  through).

Non-speculative slots sharing the pool degrade to one exact token per
round (their chain dies at ``j = 1`` by construction); the scheduler
only dispatches ``spec_chunk`` on ticks where some active slot opted
in, so a pool with speculation enabled but unused runs plain chunks.

Telemetry: ``serving_spec_tokens_{proposed,accepted}_total`` counters
(labeled like the decode series) and the per-server accepted-length
histogram in ``DecodeServer.metrics()``.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import numpy as np

from paddle_tpu import monitor

__all__ = ["SpeculativeConfig", "make_lm_speculative",
           "make_spec_chunk_fn", "dispatch_spec_chunk",
           "SPEC_PROPOSED", "SPEC_ACCEPTED"]

_LABELS = ("server", "instance")
SPEC_PROPOSED = monitor.counter(
    "serving_spec_tokens_proposed_total",
    "draft tokens proposed per speculative decode round (k - 1 per "
    "round per opted-in slot in its decode phase)", _LABELS)
SPEC_ACCEPTED = monitor.counter(
    "serving_spec_tokens_accepted_total",
    "draft tokens accepted by greedy-exact verification (acceptance "
    "rate = accepted / proposed; the speculative speedup lever)",
    _LABELS)


class SpeculativeConfig:
    """Everything a slot pool needs to run draft-then-verify rounds.

    ``verify_fn(cache, tokens [S, K], ts [S]) -> (logits [S, K, V],
    cache)``: the target's K-wide teacher-forced forward, exact-parity
    with its sequential step.  ``draft_step_fn``/``draft_make_cache``:
    the draft model in the same slot-pooled step contract — its cache
    rides the pool state as ``draft_cache`` so both models stay
    position-synced.  ``k``: consumed positions per round (>= 2; the
    draft proposes ``k - 1``).  ``draft_meta``: manifest fields for
    ``save_decode_endpoint`` (the per-endpoint ``draft`` block).
    """

    def __init__(self, verify_fn: Callable, draft_step_fn: Callable,
                 draft_make_cache: Callable, k: int = 4,
                 draft_meta: Optional[Dict[str, object]] = None):
        if int(k) < 2:
            raise ValueError(
                "speculative k must be >= 2 (k=1 is plain decode), "
                "got %r" % k)
        self.verify_fn = verify_fn
        self.draft_step_fn = draft_step_fn
        self.draft_make_cache = draft_make_cache
        self.k = int(k)
        self.draft_meta = dict(draft_meta or {})


def make_lm_speculative(target_state, *, vocab_size: int, d_model: int,
                        n_layer: int, n_head: int, d_inner: int,
                        draft_state, draft_d_model: int,
                        draft_n_layer: int, draft_n_head: int,
                        draft_d_inner: int, k: int = 4,
                        name: str = "lm",
                        draft_name: str = "draft",
                        kv_dtype: str = "fp32") -> SpeculativeConfig:
    """A :class:`SpeculativeConfig` for a transformer-LM target + a
    (smaller) transformer-LM draft sharing the vocabulary — the
    in-tree pair ``save/load_decode_endpoint`` persists.

    ``kv_dtype``: the TARGET's KV-cache storage dtype — must match the
    step fn the pool runs, so the verify call reads/writes the same
    int8-coded cache leaves.  The draft always keeps fp32 KV (it is
    small by construction; quantizing it buys nothing)."""
    from paddle_tpu.decoding import (
        make_transformer_lm_pooled_step_fn,
        make_transformer_lm_pooled_verify_fn,
    )

    verify_fn = make_transformer_lm_pooled_verify_fn(
        target_state, vocab_size, d_model, n_layer, n_head, d_inner,
        name=name, kv_dtype=kv_dtype)
    draft_step_fn, draft_make_cache = make_transformer_lm_pooled_step_fn(
        draft_state, vocab_size, draft_d_model, draft_n_layer,
        draft_n_head, draft_d_inner, name=draft_name)
    return SpeculativeConfig(
        verify_fn, draft_step_fn, draft_make_cache, k=k,
        draft_meta={
            "d_model": int(draft_d_model), "n_layer": int(draft_n_layer),
            "n_head": int(draft_n_head), "d_inner": int(draft_d_inner),
            "name": draft_name, "k": int(k),
        })


def make_spec_chunk_fn(verify_fn, draft_step_fn, eos_id: int, k: int):
    """The pure per-round function the pool compiles as ``spec_chunk``
    for each rung pair: draft ``k - 1`` proposals, verify all ``k``
    consumptions in one target call, commit the accepted run.  See the
    module docstring for the algebra; the acceptance chain is unrolled
    statically over ``j`` (k is a compile-time constant)."""
    import jax.numpy as jnp

    K = int(k)

    def spec_chunk(state):
        tokens = state["tokens"]
        pos = state["pos"]
        active = state["active"]
        spec = state["spec"]
        prompt_len = state["prompt_len"]
        total_len = state["total_len"]
        S, T = tokens.shape
        rows = jnp.arange(S)
        # --- draft phase: K sequential small steps.  Consumption c_0 is
        # always the stored buffer token at pos (prompt token, or the
        # previously verified emission); later consumptions teacher-
        # force the prompt while q_j < prompt_len, else take the
        # draft's proposal.  The draft consumes ALL K tokens so its
        # cache rows cover a fully accepted round (write-before-read
        # re-covers rejected rows next round).
        dcache = state["draft_cache"]
        tok = tokens[rows, jnp.minimum(pos, T - 1)]
        consumed = []
        for j in range(K):
            qj = pos + j
            consumed.append(tok)
            dlogits, dcache = draft_step_fn(
                dcache, tok, jnp.minimum(qj, T - 1))
            if j < K - 1:
                prop = jnp.argmax(dlogits, axis=-1).astype("int32")
                nxt_q = qj + 1
                tok = jnp.where(
                    nxt_q < prompt_len,
                    tokens[rows, jnp.minimum(nxt_q, T - 1)], prop)
        ctoks = jnp.stack(consumed, axis=1)  # [S, K]
        # --- verify: ONE K-wide target forward (prefill-shaped);
        # g[:, j] is the target's verified token for position q_j + 1
        logits, cache = verify_fn(state["cache"], ctoks, pos)
        g = jnp.argmax(logits, axis=-1).astype("int32")  # [S, K]
        # --- greedy-exact acceptance chain + commit
        new_tokens = tokens
        alive = active
        newly_fin = jnp.zeros((S,), bool)
        n_emit = jnp.zeros((S,), jnp.int32)
        adv = jnp.zeros((S,), jnp.int32)
        for j in range(K):
            qj = pos + j
            if j > 0:
                # a stored prompt token is correct by construction; a
                # drafted one must equal the target's own prediction
                # for its position (and only spec slots draft at all)
                corr = jnp.where(qj < prompt_len,
                                 jnp.ones((S,), bool),
                                 spec & (ctoks[:, j] == g[:, j - 1]))
                alive = alive & corr
            adv = adv + alive.astype(jnp.int32)
            wr = qj + 1
            emit = alive & (wr >= prompt_len) & (wr < total_len)
            wclamp = jnp.minimum(wr, T - 1)
            cur = new_tokens[rows, wclamp]
            new_tokens = new_tokens.at[rows, wclamp].set(
                jnp.where(emit, g[:, j], cur))
            n_emit = n_emit + emit.astype(jnp.int32)
            fin = emit & ((g[:, j] == eos_id) | ((qj + 2) >= total_len))
            newly_fin = newly_fin | fin
            alive = alive & ~fin
        out = dict(state)
        out.update(
            cache=cache,
            draft_cache=dcache,
            tokens=new_tokens,
            pos=pos + adv,
            active=active & ~newly_fin,
            finished=state["finished"] | newly_fin,
            n_gen=state["n_gen"] + n_emit)
        return out

    return spec_chunk


def dispatch_spec_chunk(pool, state):
    """Run one speculative round on ``state`` through the pool's warmed
    ``spec_chunk`` executable for its current rung pair (the scheduler's
    tick-path call — mirror of ``KVSlotPool.chunk``)."""
    s, t = pool.state_rungs(state)
    # hot-path: begin spec_verify (executable lookup + async dispatch of
    # the fused draft+verify round; the scheduler materializes results
    # OUTSIDE this region)
    exe = pool._get_exe("spec_chunk", s, t)
    out = exe(state)
    # hot-path: end spec_verify
    return out
