"""RemoteClient: the in-process ``Client`` surface over a wire hop.

Keeps the transport-agnostic contract the serving layer promised: the
same ``infer`` / ``infer_named`` / ``infer_many`` / ``infer_stream``
signatures (streaming rides chunked codec messages on one response),
the same typed errors (``ServerOverloaded`` / ``DeadlineExceeded`` /
``ServerClosed`` re-raised from the response's in-band error channel,
``BackendUnavailable`` / ``WireProtocolError`` for transport/framing
failures), and the same per-request trace-id minting — now carried
across the process boundary in a W3C ``traceparent`` header, with the
server's retained span tree merged back into the local flight recorder
so ``/tracez`` shows ONE tree per request spanning both processes.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import monitor
from paddle_tpu.monitor import flight as _flight
from paddle_tpu.monitor import spans as _spans
from paddle_tpu.serving import errors as _errors
from paddle_tpu.serving.admission import PRIORITY_NORMAL
from paddle_tpu.serving.errors import DeadlineExceeded, ServingError
from paddle_tpu.serving.wire.codec import format_traceparent
from paddle_tpu.serving.wire.http import HttpTransport, Transport

__all__ = ["RemoteClient", "raise_in_band_error", "wire_call",
           "wire_stream_open", "flight_report"]

# the response meta "error" field names a type from serving.errors (or
# the validation builtin); an unknown name degrades to the base
# ServingError (typed, never a crash)
_ERROR_TYPES = {
    name: getattr(_errors, name)
    for name in _errors.__all__
}
_ERROR_TYPES["ValueError"] = ValueError


def flight_report(fr, tid: str, sid: str, t0: float, dur: float,
                  err: Optional[BaseException],
                  server_spans: Sequence[Dict] = (), **extra) -> None:
    """Merge one wire request into the LOCAL flight recorder: the
    client-side span plus the server-side tree the response carried
    (one cross-process record under one trace id).  Mirrors the
    in-process client's retention policy: errors other than a deadline
    are recorded only when the request came back with server spans or
    was already retained — a storm of shed/unreachable requests must
    not flood the bounded ring and evict the slow tail."""
    span = {
        "name": "serving/client_infer", "cat": "client", "id": sid,
        "ts": _spans.wall_ts(t0), "dur": dur,
        "tid": threading.get_ident(), "trace_ids": [tid],
    }
    if err is not None:
        span["error"] = True
    spans = [span] + [dict(s) for s in server_spans]
    status = ("ok" if err is None else
              "deadline" if isinstance(err, _errors.DeadlineExceeded)
              else "error")
    if fr.get_record(tid) is not None:
        for s in spans:
            fr.add_span(tid, s)
        return
    if err is not None and status == "error" and not server_spans:
        return
    fr.consider(tid, dur, status, spans, **extra)


def raise_in_band_error(meta: Dict[str, object]) -> None:
    """Re-raise the typed serving error a response meta carries (no-op
    for a success meta).  A ``retry_after_ms`` hint in the meta (the
    server's computed overload backoff) is re-attached to the raised
    error so the fleet's retry pacing can honor it."""
    name = meta.get("error")
    if not name:
        return
    etype = _ERROR_TYPES.get(str(name), ServingError)
    err = etype(str(meta.get("message") or name))
    retry_ms = meta.get("retry_after_ms")
    if retry_ms is not None:
        try:
            err.retry_after_ms = float(retry_ms)
        except (TypeError, ValueError):
            pass  # a malformed hint never masks the typed error
    load = meta.get("load")
    if isinstance(load, dict):
        # a typed error still carries the server's load report: the
        # balancer's least-loaded routing learns from sheds too
        err.load = load
    raise err


def wire_call(transport: Transport, feed_names: Sequence[str],
              arrays: Sequence[np.ndarray], timeout_ms: Optional[float],
              tid: str, extra_meta: Optional[Dict[str, object]] = None,
              priority: Optional[int] = None,
              ) -> Tuple[Dict[str, object], List[np.ndarray]]:
    """One traced ``/infer`` exchange (shared by ``RemoteClient`` and
    the fleet balancer): records the client-side ``wire/request`` span,
    sends its id as the ``traceparent`` parent so the server's request
    span is its child, and asks for the server-side span tree whenever a
    local sink could use it.  ``timeout_ms`` is the REMAINING deadline
    at send time (the server sheds <= 0 at admission); ``priority`` is
    the admission class carried in the request meta."""
    fr = _flight.get()
    rec = _spans.recording() or fr is not None
    meta: Dict[str, object] = {"feed_names": list(feed_names)}
    if timeout_ms is not None:
        meta["timeout_ms"] = float(timeout_ms)
    if priority is not None:
        meta["priority"] = int(priority)
    if extra_meta:
        meta.update(extra_meta)
    # hot-path: begin wire_dispatch (trace gates + the transport POST;
    # the request path must not add blocking work beyond the socket)
    timeout_s = (
        float(timeout_ms) / 1e3 if timeout_ms is not None else None)
    if not rec:
        rmeta, routs = transport.request(
            "/infer", meta, arrays, timeout_s=timeout_s)
        raise_in_band_error(rmeta)
        return rmeta, routs
    sid = _spans.new_span_id()
    headers = {"traceparent": format_traceparent(tid, sid),
               "X-Wire-Spans": "1"}
    t0 = time.perf_counter()
    err: Optional[BaseException] = None
    try:
        with _spans.trace_context((tid,)):
            with _spans.parent_scope(sid):
                rmeta, routs = transport.request(
                    "/infer", meta, arrays, timeout_s=timeout_s,
                    headers=headers)
        raise_in_band_error(rmeta)
        return rmeta, routs
    except BaseException as e:  # noqa: BLE001 — observed, re-raised
        err = e
        raise
    finally:
        with _spans.trace_context((tid,)):
            _spans.record_span(
                "wire/request", t0, time.perf_counter() - t0, cat="wire",
                span_id=sid, error=err is not None,
                backend="%s:%d" % transport.address)
    # hot-path: end wire_dispatch


def wire_stream_open(transport: Transport, feed_names: Sequence[str],
                     arrays: Sequence[np.ndarray],
                     timeout_ms: Optional[float], tid: str,
                     extra_meta: Optional[Dict[str, object]] = None,
                     priority: Optional[int] = None):
    """Open one ``/infer_stream`` exchange and read its FIRST message
    (shared by ``RemoteClient`` and the fleet balancer): a pre-stream
    failure — unreachable backend, admission shed, expired deadline —
    surfaces typed AT THIS CALL, before the caller commits to the
    stream, which is what lets the fleet requeue to a survivor.
    Returns ``(iterator, first_message)``; subsequent messages come off
    the iterator, each either a token chunk or the ``final`` meta (a
    mid-stream error travels in-band on the final message)."""
    meta: Dict[str, object] = {"feed_names": list(feed_names)}
    if timeout_ms is not None:
        meta["timeout_ms"] = float(timeout_ms)
    if priority is not None:
        meta["priority"] = int(priority)
    if extra_meta:
        meta.update(extra_meta)
    timeout_s = (
        float(timeout_ms) / 1e3 if timeout_ms is not None else None)
    headers = {"traceparent": format_traceparent(tid, _spans.new_span_id())}
    it = transport.stream("/infer_stream", meta, arrays,
                          timeout_s=timeout_s, headers=headers)
    first = next(iter(it), None)
    if first is None:
        raise _errors.WireProtocolError(
            "stream closed before the first message")
    raise_in_band_error(first[0])
    return it, first


def pump_stream_messages(it, first, counter: List[int]):
    """The one client/fleet stream-consumption protocol: yield token
    chunks off a wire message iterator (``yield from`` this), re-raising
    in-band typed errors, and RETURN the ``final`` meta message.
    ``counter``: one-element list incremented per chunk, so the caller's
    accounting survives an abandoned (closed mid-yield) generator."""
    rmeta, rarrays = first
    while True:
        raise_in_band_error(rmeta)
        if rmeta.get("final"):
            return rmeta
        counter[0] += 1
        yield rarrays[0]
        nxt = next(it, None)
        if nxt is None:
            raise _errors.WireProtocolError(
                "stream ended without a final message")
        rmeta, rarrays = nxt


class RemoteClient:
    """Client for ONE remote ``ServingProcess``.

    ``address``: ``(host, port)`` (an ``HttpTransport`` is built over
    it) or any ``Transport`` instance — the gRPC seam.  Endpoint shape
    (feed/fetch names) is discovered from ``/healthz`` on first use."""

    def __init__(self, address, timeout_s: float = 30.0):
        if isinstance(address, Transport):
            self._transport = address
        else:
            host, port = address
            self._transport = HttpTransport(host, port, timeout_s=timeout_s)
        self._shape_lock = threading.Lock()
        self._feed_names: Optional[List[str]] = None
        self._fetch_names: Optional[List[str]] = None
        self._pool = None  # lazy persistent executor (infer_many)

    def _executor(self):
        """Persistent worker pool for scatter/gather: long-lived threads
        mean the transport's PER-THREAD keep-alive connections are
        actually reused across infer_many calls (fresh threads per call
        would redial every request)."""
        with self._shape_lock:
            if self._pool is None:
                import concurrent.futures

                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="wire-client")
            return self._pool

    # ------------------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return self._transport.address

    def _endpoint_shape(self) -> Tuple[List[str], List[str]]:
        with self._shape_lock:
            if self._feed_names is None:
                doc = self._transport.get_json("/healthz")
                self._feed_names = [str(n) for n in doc["input_names"]]
                self._fetch_names = [str(n) for n in doc["output_names"]]
            return self._feed_names, self._fetch_names

    def healthz(self) -> Dict[str, object]:
        return self._transport.get_json("/healthz")

    # -- admin observability surfaces (tooling/test conveniences) ------
    def statusz(self) -> Dict[str, object]:
        return self._transport.get_json("/statusz")

    def tracez(self) -> Dict[str, object]:
        return self._transport.get_json("/tracez")

    def sloz(self) -> Dict[str, object]:
        return self._transport.get_json("/sloz")

    def eventz(self) -> Dict[str, object]:
        return self._transport.get_json("/eventz")

    def metrics_text(self) -> str:
        """The raw ``/metrics`` exposition (what a scraper sees)."""
        return self._transport.get_text("/metrics")

    def warmup(self, timeout_s: float = 600.0) -> int:
        """Trigger the remote server's bucket-ladder warmup; returns the
        XLA compile count it performed."""
        meta, _ = self._transport.request(
            "/warmup", {}, (), timeout_s=timeout_s)
        raise_in_band_error(meta)
        return int(meta.get("compiles", 0))

    def _normalize(self, feed) -> Tuple[List[str], List[np.ndarray]]:
        names, _ = self._endpoint_shape()
        if not isinstance(feed, dict):
            feed = dict(zip(names, feed))
        if set(feed) != set(names):
            raise ValueError(
                "feed names %s != endpoint inputs %s"
                % (sorted(feed), sorted(names)))
        return names, [feed[n] for n in names]

    # ------------------------------------------------------------------
    def infer(self, feed, timeout_ms: Optional[float] = None,
              trace_id: Optional[str] = None,
              priority: int = PRIORITY_NORMAL,
              precision: Optional[str] = None) -> List[np.ndarray]:
        """Submit one request over the wire and block for its outputs
        (ordered like the endpoint's fetch list).  Same deadline /
        overload / closed error types as the in-process client, plus
        ``BackendUnavailable`` when the remote process is gone.

        ``precision`` rides the request meta to the server's
        mixed-precision dispatch (``"fp32"`` = per-request opt-out of
        the endpoint's policy default); an unknown dtype re-raises the
        server's typed ValueError.

        ``priority`` (``serving.admission.PRIORITY_*``, lower = more
        important) rides the request meta into the server's priority
        shedding.  The deadline is anchored at THIS call's entry: what
        goes over the wire is the remaining budget at send time, so
        work done inside the call (endpoint-shape discovery on first
        use, feed normalization) debits the caller's clock and the
        server sheds already-expired work at admission instead of
        dispatching it.  (``infer_many`` pool waits happen before the
        per-request ``infer`` starts, so each request's budget starts
        when its worker picks it up.)"""
        tid = trace_id or monitor.new_trace_id()
        self.last_trace_id = tid
        deadline = (
            time.monotonic() + float(timeout_ms) / 1e3
            if timeout_ms is not None else None)
        names, arrays = self._normalize(feed)
        remaining_ms = self._remaining_ms(deadline)
        extra = {"precision": str(precision)} if precision is not None else None
        fr = _flight.get()
        rec = _spans.recording() or fr is not None
        if not rec:
            _, routs = wire_call(
                self._transport, names, arrays, remaining_ms, tid,
                priority=priority, extra_meta=extra)
            return routs
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        sid = _spans.new_span_id()
        # the capture buffer collects this thread's wire/request span so
        # the flight record carries the hop, not just its endpoints
        cap: List[Dict] = []
        extra_spans: List[Dict] = []
        try:
            with _spans.trace_context((tid,)):
                with _spans.parent_scope(sid):
                    with _spans.capture(cap):
                        rmeta, routs = wire_call(
                            self._transport, names, arrays, remaining_ms,
                            tid, priority=priority, extra_meta=extra)
            extra_spans = list(rmeta.get("spans") or ())
            return routs
        except BaseException as e:  # noqa: BLE001 — observed, re-raised
            err = e
            raise
        finally:
            dur = time.perf_counter() - t0
            with _spans.trace_context((tid,)):
                _spans.record_span(
                    "serving/client_infer", t0, dur, cat="client",
                    span_id=sid, error=err is not None)
            if fr is not None:
                flight_report(fr, tid, sid, t0, dur, err,
                              cap + extra_spans)

    @staticmethod
    def _remaining_ms(deadline: Optional[float]) -> Optional[float]:
        """Remaining budget at send time.  Already-expired fails fast
        HERE, typed — never burns a wire exchange on dead work."""
        if deadline is None:
            return None
        remaining = (deadline - time.monotonic()) * 1e3
        if remaining <= 0:
            raise DeadlineExceeded(
                "deadline exhausted before the wire send")
        return remaining

    def infer_named(self, feed, timeout_ms: Optional[float] = None,
                    trace_id: Optional[str] = None,
                    priority: int = PRIORITY_NORMAL) -> Dict[str, np.ndarray]:
        """``infer()``, but keyed by the endpoint's output names."""
        _, fetch_names = self._endpoint_shape()
        return dict(zip(fetch_names,
                        self.infer(feed, timeout_ms, trace_id=trace_id,
                                   priority=priority)))

    def infer_many(self, feeds, timeout_ms: Optional[float] = None,
                   priority: int = PRIORITY_NORMAL
                   ) -> List[List[np.ndarray]]:
        """Issue every request concurrently (so the remote batcher can
        coalesce them into shared batches) and gather results in order.
        Each request gets its own trace id (``last_trace_ids``)."""
        tids = [monitor.new_trace_id() for _ in feeds]
        self.last_trace_ids = tids
        futures = [
            self._executor().submit(
                self.infer, f, timeout_ms, trace_id=t, priority=priority)
            for f, t in zip(feeds, tids)
        ]
        return [f.result() for f in futures]

    def infer_stream(self, feed, timeout_ms: Optional[float] = None,
                     trace_id: Optional[str] = None,
                     priority: int = PRIORITY_NORMAL,
                     max_new_tokens: Optional[int] = None,
                     speculative: Optional[bool] = None):
        """Stream generated-token chunks from a remote decode endpoint
        (``serving.decode.DecodeServer`` behind a ``ServingProcess``):
        each yielded 1-D int32 array is one chunk, received over the
        wire as its own codec message on the chunked response body —
        the first arrives as soon as the server's scheduler completes
        the request's first tick, long before the sequence finishes.

        Pre-stream failures (unreachable backend, admission shed,
        expired deadline, a non-streaming endpoint) raise typed AT THIS
        CALL; a mid-stream failure re-raises typed from the iterator.
        Every chunk carries the one trace id (``last_trace_id``); the
        final message's meta lands in ``last_stream_final`` (chunk
        count, output names, the server's load report).  Abandoning the
        iterator drops the pooled connection — and the server, seeing
        the peer gone, aborts the decode so its slot frees."""
        tid = trace_id or monitor.new_trace_id()
        self.last_trace_id = tid
        deadline = (
            time.monotonic() + float(timeout_ms) / 1e3
            if timeout_ms is not None else None)
        names, arrays = self._normalize(feed)
        remaining_ms = self._remaining_ms(deadline)
        extra = {}
        if max_new_tokens is not None:
            extra["max_new_tokens"] = int(max_new_tokens)
        if speculative is not None:
            # decode tier 2: ask the endpoint to draft-and-verify this
            # stream (greedy-exact — same tokens, fewer target steps)
            extra["speculative"] = bool(speculative)
        it, first = wire_stream_open(
            self._transport, names, arrays, remaining_ms, tid,
            extra_meta=extra, priority=priority)
        return self._stream_chunks(it, first, tid)

    def _stream_chunks(self, it, first, tid: str):
        t0 = time.perf_counter()
        sid = _spans.new_span_id() if _spans.recording() else None
        err: Optional[BaseException] = None
        counter = [0]
        try:
            self.last_stream_final = yield from pump_stream_messages(
                it, first, counter)
            return
        except GeneratorExit:
            raise  # abandoned: neutral, not a stream failure
        except BaseException as e:  # noqa: BLE001 — observed, re-raised
            err = e
            raise
        finally:
            # abandoning mid-stream closes the transport iterator, which
            # drops the (desynced) pooled connection
            close = getattr(it, "close", None)
            if close is not None:
                close()
            if sid is not None:
                with _spans.trace_context((tid,)):
                    _spans.record_span(
                        "serving/client_stream", t0,
                        time.perf_counter() - t0, cat="client",
                        span_id=sid, chunks=counter[0],
                        error=err is not None,
                        backend="%s:%d" % self._transport.address)

    def close(self) -> None:
        with self._shape_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        self._transport.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
