"""Wire-layer metrics (process-global registry, always on).

Registered at import like every other subsystem's metrics — the
``/metrics`` exposition of any process that loaded the wire layer
carries them, and ``tools/check_metrics_docs.py`` holds the README
table to this set.

``role`` distinguishes the two ends of the hop: ``client`` series are
stamped by ``RemoteClient``/the fleet balancer, ``server`` series by
``ServingProcess``.  The codec histogram is the wire tax's measured
half: encode/decode seconds per message, labeled by direction.
"""
from __future__ import annotations

from paddle_tpu.monitor import registry as _registry

__all__ = [
    "WIRE_REQUESTS", "WIRE_BYTES_SENT", "WIRE_BYTES_RECEIVED",
    "WIRE_CODEC_SECONDS", "WIRE_BACKEND_RETIRED",
    "WIRE_HEALTH_CHECKS", "WIRE_HEALTH_CHECK_FAILURES",
    "WIRE_BACKEND_RELAUNCHES", "RETRY_THROTTLED",
    "FLEET_AFFINITY_HITS",
    "FEDERATION_SCRAPES", "FEDERATION_STALENESS",
]

WIRE_REQUESTS = _registry.REGISTRY.counter(
    "wire_requests_total",
    "wire RPC exchanges (role=client: sent; role=server: served)",
    ("role",))
WIRE_BYTES_SENT = _registry.REGISTRY.counter(
    "wire_bytes_sent_total",
    "wire message bytes written (bodies, post-codec)", ("role",))
WIRE_BYTES_RECEIVED = _registry.REGISTRY.counter(
    "wire_bytes_received_total",
    "wire message bytes read (bodies, pre-codec)", ("role",))
# codec cost buckets: a wire message should encode/decode in well under
# a millisecond for small feeds — the sub-ms rungs are where the signal
# lives, the tail rungs catch giant-array bodies
WIRE_CODEC_SECONDS = _registry.REGISTRY.histogram(
    "wire_codec_seconds",
    "per-message codec time (op=encode|decode)", ("op",),
    buckets=(1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 0.025, 0.1, 0.5))
WIRE_BACKEND_RETIRED = _registry.REGISTRY.counter(
    "wire_backend_retired_total",
    "backends the front-end balancer retired from routing "
    "(consecutive request failures or failed health checks)", ("fleet",))
WIRE_HEALTH_CHECKS = _registry.REGISTRY.counter(
    "wire_health_checks_total",
    "balancer /healthz probes issued", ("fleet",))
WIRE_HEALTH_CHECK_FAILURES = _registry.REGISTRY.counter(
    "wire_health_check_failures_total",
    "balancer /healthz probes that failed or timed out", ("fleet",))
WIRE_BACKEND_RELAUNCHES = _registry.REGISTRY.counter(
    "wire_backend_relaunches_total",
    "supervisor relaunch attempts for crashed serving children "
    "(each attempt counts; compare against RelaunchFailed give-ups)",
    ("fleet",))
RETRY_THROTTLED = _registry.REGISTRY.counter(
    "retry_throttled_total",
    "fleet re-dispatches the token-bucket retry throttle denied: the "
    "typed error propagated to the caller instead of amplifying load "
    "on a saturated backend (back-pressure, not a retry storm)",
    ("fleet",))
FEDERATION_SCRAPES = _registry.REGISTRY.counter(
    "wire_federation_scrapes_total",
    "balancer observability scrapes of child admin surfaces "
    "(status=ok|error; one count per backend per scrape pass)",
    ("fleet", "status"))
FEDERATION_STALENESS = _registry.REGISTRY.gauge(
    "wire_federation_staleness_seconds",
    "age of the OLDEST live backend's last successful observability "
    "scrape (worst-case staleness of the balancer's federated view)",
    ("fleet",))
FLEET_AFFINITY_HITS = _registry.REGISTRY.counter(
    "serving_fleet_affinity_hits_total",
    "fleet requests routed to the backend their prompt-prefix hash was "
    "last served by (cache-affinity routing: the hinted backend's "
    "prefix KV cache is warm for this prompt head)", ("fleet",))
