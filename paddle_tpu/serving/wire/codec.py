"""Wire message codec: length-prefixed JSON + npy frames.

One wire message is::

    b"PTW1"                          magic (protocol/version)
    [b"J"][u32 len][json bytes]      exactly one meta frame, first
    [b"A"][u32 len][npy bytes] ...   zero or more array frames, in order
    [b"E"][u32 0]                    end frame

msgpack-free by design: the only dependencies are ``struct``, ``json``
and ``numpy.lib.format`` (the ``.npy`` serialization — dtype, shape and
byte order travel in the payload, so arbitrary dtype/shape/contiguity
round-trips exactly; pickle is never enabled).  Every read is BOUNDED:
a frame longer than ``max_frame_bytes``, more frames than
``max_frames``, a torn length prefix, or a missing end frame raises a
typed ``WireProtocolError`` instead of wedging the reader on a
malformed peer.

W3C ``traceparent`` helpers live here too — they are wire-format
encoding exactly like the frames: ``00-<32hex trace>-<16hex parent>-01``
carries the request's trace id and the client-side parent span id
across the process boundary, so the flight recorder can merge one span
tree per request (``monitor.spans`` parent ids).
"""
from __future__ import annotations

import io
import json
import re
import struct
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.monitor import spans as _spans
from paddle_tpu.serving.errors import WireProtocolError
from paddle_tpu.serving.wire.metrics import WIRE_CODEC_SECONDS

__all__ = [
    "MAGIC", "DEFAULT_MAX_FRAME_BYTES", "DEFAULT_MAX_FRAMES",
    "encode_message", "decode_message", "read_message",
    "format_traceparent", "parse_traceparent",
]

MAGIC = b"PTW1"
_KIND_META = b"J"
_KIND_ARRAY = b"A"
_KIND_END = b"E"
_HEADER = struct.Struct("!cI")  # frame kind + payload length (network order)

DEFAULT_MAX_FRAME_BYTES = 1 << 28   # 256 MiB per frame
DEFAULT_MAX_FRAMES = 4096           # meta + arrays + end

_ENC = WIRE_CODEC_SECONDS.labels(op="encode")
_DEC = WIRE_CODEC_SECONDS.labels(op="decode")


def _codec_exemplar() -> Optional[Dict[str, str]]:
    """Exemplar linking a codec observation to the request being
    encoded/decoded: the calling thread's active trace context (a
    tuple read — free when no request attribution is live), so
    ``/metrics?openmetrics`` tails point into ``/tracez`` here exactly
    like the executor and serving-latency histograms."""
    ids = _spans.current_trace_ids()
    return {"trace_id": ids[0]} if ids else None


def encode_message(meta: Dict[str, object],
                   arrays: Sequence[np.ndarray] = ()) -> bytes:
    """Serialize one message.  ``meta`` must be JSON-serializable;
    ``arrays`` are positional (callers carry names in the meta — e.g.
    ``feed_names``/``output_names``).  Object-dtype arrays are refused
    (they would need pickle, which never crosses the wire)."""
    t0 = time.perf_counter()
    # hot-path: begin wire_encode (per-message serialization on the
    # request path; no blocking device sync, no sleeps)
    buf = io.BytesIO()
    buf.write(MAGIC)
    payload = json.dumps(meta, separators=(",", ":")).encode("utf-8")
    buf.write(_HEADER.pack(_KIND_META, len(payload)))
    buf.write(payload)
    for arr in arrays:
        if getattr(arr, "dtype", None) is not None and arr.dtype.hasobject:
            raise WireProtocolError(
                "object-dtype arrays cannot cross the wire (no pickle)")
        abuf = io.BytesIO()
        try:
            np.lib.format.write_array(abuf, arr, allow_pickle=False)
        except (TypeError, ValueError) as e:
            raise WireProtocolError("unencodable array: %s" % e) from e
        payload = abuf.getvalue()
        buf.write(_HEADER.pack(_KIND_ARRAY, len(payload)))
        buf.write(payload)
    buf.write(_HEADER.pack(_KIND_END, 0))
    out = buf.getvalue()
    # hot-path: end wire_encode
    _ENC.observe(time.perf_counter() - t0, exemplar=_codec_exemplar())
    return out


def _read_exact(f, n: int, what: str) -> bytes:
    """Read exactly ``n`` bytes (bounded by the caller's frame checks);
    EOF mid-read is a typed truncation error, never a hang or a short
    silent result."""
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = f.read(n - got)
        if not chunk:
            raise WireProtocolError(
                "truncated %s: wanted %d bytes, got %d" % (what, n, got))
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


def read_message(f, max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                 max_frames: int = DEFAULT_MAX_FRAMES,
                 ) -> Tuple[Dict[str, object], List[np.ndarray]]:
    """Read one message from a binary file-like.  Every frame length is
    validated BEFORE its payload is read, so an adversarial length
    prefix costs nothing; a stream that ends before the end frame, or
    exceeds the frame/count bounds, raises ``WireProtocolError``."""
    t0 = time.perf_counter()
    # hot-path: begin wire_decode (per-message parse on the request path)
    magic = _read_exact(f, len(MAGIC), "magic")
    if magic != MAGIC:
        raise WireProtocolError("bad magic %r (want %r)" % (magic, MAGIC))
    meta: Optional[Dict[str, object]] = None
    arrays: List[np.ndarray] = []
    for _ in range(max_frames):
        kind, length = _HEADER.unpack(
            _read_exact(f, _HEADER.size, "frame header"))
        if kind == _KIND_END:
            if length != 0:
                raise WireProtocolError(
                    "end frame carries length %d" % length)
            if meta is None:
                raise WireProtocolError("message has no meta frame")
            break
        if length > max_frame_bytes:
            raise WireProtocolError(
                "oversized frame: %d bytes exceeds the %d-byte bound"
                % (length, max_frame_bytes))
        payload = _read_exact(f, length, "frame payload")
        if kind == _KIND_META:
            if meta is not None:
                raise WireProtocolError("duplicate meta frame")
            try:
                meta = json.loads(payload.decode("utf-8"))
            except (UnicodeDecodeError, ValueError) as e:
                raise WireProtocolError("undecodable meta: %s" % e) from e
            if not isinstance(meta, dict):
                raise WireProtocolError(
                    "meta frame must hold a JSON object, got %s"
                    % type(meta).__name__)
        elif kind == _KIND_ARRAY:
            try:
                arrays.append(np.lib.format.read_array(
                    io.BytesIO(payload), allow_pickle=False))
            except (ValueError, OSError) as e:
                raise WireProtocolError("undecodable array: %s" % e) from e
        else:
            raise WireProtocolError("unknown frame kind %r" % kind)
    else:
        raise WireProtocolError(
            "message exceeds %d frames without an end frame" % max_frames)
    # hot-path: end wire_decode
    _DEC.observe(time.perf_counter() - t0, exemplar=_codec_exemplar())
    return meta, arrays


def decode_message(data: bytes,
                   max_frame_bytes: int = DEFAULT_MAX_FRAME_BYTES,
                   max_frames: int = DEFAULT_MAX_FRAMES,
                   ) -> Tuple[Dict[str, object], List[np.ndarray]]:
    """``read_message`` over an in-memory buffer; trailing garbage after
    the end frame is rejected (one body, one message)."""
    buf = io.BytesIO(data)
    meta, arrays = read_message(buf, max_frame_bytes, max_frames)
    if buf.read(1):
        raise WireProtocolError("trailing bytes after end frame")
    return meta, arrays


# ---------------------------------------------------------------------------
# W3C trace context (https://www.w3.org/TR/trace-context/)
# ---------------------------------------------------------------------------
_TRACEPARENT_RE = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def format_traceparent(trace_id: str, parent_span_id: str) -> str:
    """Render the ``traceparent`` header for one hop.  The repo's
    16-hex Dapper-style trace ids are left-padded to the W3C 32-hex
    field; the parent id is the CLIENT-side wire span's id, so the
    server records its request span as that span's child."""
    return "00-%s-%s-01" % (
        str(trace_id).rjust(32, "0")[:32],
        str(parent_span_id).rjust(16, "0")[:16])


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    """``(trace_id, parent_span_id)`` from a ``traceparent`` header, or
    None when absent/malformed (a bad header degrades to a fresh local
    trace — never an error: trace plumbing must not fail requests).  A
    32-hex trace id that is a left-padded 16-hex repo id is returned in
    its native 16-hex form so both processes key the same record."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    _, trace32, parent, _ = m.groups()
    if trace32 == "0" * 32 or parent == "0" * 16:
        return None  # the spec's all-zero ids are invalid
    trace = trace32[16:] if trace32[:16] == "0" * 16 else trace32
    return trace, parent
