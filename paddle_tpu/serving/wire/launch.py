"""Launch and manage serving child processes.

``launch_server()`` spawns ``python -m paddle_tpu.serving.wire.launch``
as a detached child: the child loads the saved inference model, builds
an ``InferenceServer`` (optionally multi-replica), binds a
``ServingProcess`` on an ephemeral port, and announces readiness by
printing one ``WIRE_READY {json}`` line on stdout — the parent learns
the bound port without a port-assignment race.  The returned
``ServerHandle`` is the management surface the fleet balancer (and
tests) drive: health probes, graceful shutdown (``/quitquitquit``
drain), and hard kill (the lost-process failure mode the requeue
machinery must survive).

This is the reference stack's ``fluid.distributed.launch`` idea applied
to serving: processes, not threads, are the unit of replication, so a
crash takes out one ladder of jit caches — not the fleet.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
from typing import Dict, List, Optional, Tuple

__all__ = ["ServerHandle", "launch_server", "relaunch", "Supervisor",
           "main"]

READY_PREFIX = "WIRE_READY "


class ServerHandle:
    """One launched serving child: its process + wire address."""

    def __init__(self, proc: subprocess.Popen, host: str, port: int,
                 name: str, spec: Optional[Dict[str, object]] = None):
        self.proc = proc
        self.host = host
        self.port = int(port)
        self.name = name
        self.spec = dict(spec or {})  # relaunch recipe (rolling replace)

    # ------------------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return (self.host, self.port)

    @property
    def pid(self) -> int:
        return self.proc.pid

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def healthz(self, timeout_s: float = 5.0) -> Dict[str, object]:
        from paddle_tpu.serving.wire.http import HttpTransport

        t = HttpTransport(self.host, self.port, timeout_s=timeout_s)
        try:
            return t.get_json("/healthz", timeout_s=timeout_s)
        finally:
            t.close()

    def warmup(self, timeout_s: float = 600.0) -> int:
        from paddle_tpu.serving.wire.client import raise_in_band_error
        from paddle_tpu.serving.wire.http import HttpTransport

        t = HttpTransport(self.host, self.port, timeout_s=timeout_s)
        try:
            meta, _ = t.request("/warmup", {}, (), timeout_s=timeout_s)
            raise_in_band_error(meta)
            return int(meta.get("compiles", 0))
        finally:
            t.close()

    # ------------------------------------------------------------------
    def shutdown(self, timeout_s: float = 30.0) -> Optional[int]:
        """Graceful: ask the child to drain and exit; escalate to
        terminate/kill only when the deadline passes."""
        from paddle_tpu.serving.errors import ServingError
        from paddle_tpu.serving.wire.http import HttpTransport

        if self.proc.poll() is None:
            t = HttpTransport(self.host, self.port, timeout_s=5.0)
            try:
                t.request("/quitquitquit", {}, (), timeout_s=5.0)
            except ServingError:
                pass  # already gone/unreachable: fall through to wait
            finally:
                t.close()
        try:
            return self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.terminate()
            try:
                return self.proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                self.kill()
                return self.proc.wait(timeout=5.0)

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()

    def kill(self) -> None:
        """Hard kill — the crash the balancer's requeue path must eat."""
        if self.proc.poll() is None:
            self.proc.kill()

    def wait(self, timeout_s: Optional[float] = None) -> Optional[int]:
        return self.proc.wait(timeout=timeout_s)


def _drain_stdout(proc: subprocess.Popen) -> None:
    """Keep reading the child's stdout after READY so a chatty child
    can never block on a full pipe (stderr has its own bounded
    collector from launch time)."""
    try:
        for _ in proc.stdout:
            pass
    except Exception:
        pass


def launch_server(
    model_dir: str,
    host: str = "127.0.0.1",
    port: int = 0,
    name: str = "wire",
    replicas: int = 1,
    max_batch_size: int = 32,
    batch_timeout_ms: float = 5.0,
    queue_capacity: int = 256,
    warmup: bool = False,
    flight_slow_ms: Optional[float] = None,
    ready_timeout_s: float = 180.0,
    env: Optional[Dict[str, str]] = None,
    pipeline_stages: Optional[int] = None,
    pipeline_microbatches: int = 4,
) -> ServerHandle:
    """Spawn one serving child process and wait for its READY line.

    ``flight_slow_ms``: install a flight recorder in the child at this
    tail-sampling threshold (0 retains everything) — required for the
    cross-process span merge; omitted, the child pays zero tracing rent.
    A child that exits (or stays silent) before READY raises with its
    captured stderr tail, never hangs the parent.

    ``pipeline_stages``: serve the model as a micro-batched
    ``PipelinePredictor`` group of this depth (over a ``{"pp": K}``
    mesh inside the child) instead of single-device replicas;
    ``pipeline_microbatches`` caps the GPipe micro-batch count.  The
    child's ``/healthz`` then advertises the pipeline group."""
    spec = {
        "model_dir": model_dir, "host": host, "port": port, "name": name,
        "replicas": replicas, "max_batch_size": max_batch_size,
        "batch_timeout_ms": batch_timeout_ms,
        "queue_capacity": queue_capacity, "warmup": warmup,
        "flight_slow_ms": flight_slow_ms,
        "pipeline_stages": pipeline_stages,
        "pipeline_microbatches": pipeline_microbatches,
    }
    argv = [
        sys.executable, "-m", "paddle_tpu.serving.wire.launch",
        "--model-dir", model_dir, "--host", host, "--port", str(port),
        "--name", name, "--replicas", str(replicas),
        "--max-batch-size", str(max_batch_size),
        "--batch-timeout-ms", str(batch_timeout_ms),
        "--queue-capacity", str(queue_capacity),
    ]
    if warmup:
        argv.append("--warmup")
    if flight_slow_ms is not None:
        argv += ["--flight-slow-ms", str(flight_slow_ms)]
    if pipeline_stages is not None:
        argv += ["--pipeline-stages", str(pipeline_stages),
                 "--pipeline-microbatches", str(pipeline_microbatches)]
    child_env = dict(os.environ)
    if env:
        child_env.update(env)
    # the child must import paddle_tpu from THIS checkout (it is not
    # installed); prepend, never clobber, any caller PYTHONPATH
    repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))
    prev = child_env.get("PYTHONPATH")
    child_env["PYTHONPATH"] = (
        repo_root + os.pathsep + prev if prev else repo_root)
    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, env=child_env)
    # stderr drains from the FIRST moment on its own thread into a
    # bounded tail buffer: a child whose model load logs more than the
    # OS pipe buffer pre-READY must not deadlock on a full pipe (and
    # the tail is the diagnostic the failure path reports)
    err_tail: List[str] = []

    def _collect_stderr():
        try:
            for line in proc.stderr:
                err_tail.append(line)
                if len(err_tail) > 200:
                    del err_tail[:100]
        except Exception:
            pass

    threading.Thread(target=_collect_stderr, name="wire-stderr",
                     daemon=True).start()
    # the READY scan runs on a thread too: a silent/hung child must trip
    # the parent's DEADLINE, not park it on a blocking readline forever
    box: Dict[str, object] = {}
    seen = threading.Event()

    def _scan():
        try:
            for line in proc.stdout:
                if line.startswith(READY_PREFIX):
                    box["ready"] = json.loads(line[len(READY_PREFIX):])
                    seen.set()
                    return
                # pre-ready chatter (jax logs etc.): ignore
        except Exception as e:  # noqa: BLE001 — surfaced via the waiter
            box["scan_error"] = repr(e)
        seen.set()  # EOF: the child died before READY — wake the waiter

    threading.Thread(target=_scan, name="wire-ready-scan",
                     daemon=True).start()
    if not seen.wait(ready_timeout_s):
        proc.kill()
        raise RuntimeError(
            "serving child %r never reported ready within %.0fs:\n%s"
            % (name, ready_timeout_s, "".join(err_tail)[-4000:]))
    ready = box.get("ready")
    if ready is None:
        # kill FIRST: the collected tail is already in memory, and a
        # blocking stderr read on a still-live child would hang here
        proc.kill()
        raise RuntimeError(
            "serving child %r failed before ready (rc=%s, scan=%s):\n%s"
            % (name, proc.poll(), box.get("scan_error"),
               "".join(err_tail)[-4000:]))
    threading.Thread(target=_drain_stdout, args=(proc,),
                     daemon=True).start()
    return ServerHandle(proc, ready["host"], ready["port"], name, spec=spec)


def relaunch(handle: ServerHandle, port: int = 0) -> ServerHandle:
    """Launch a FRESH child from an existing handle's recipe (rolling
    replacement; the new child gets its own ephemeral port)."""
    spec = dict(handle.spec)
    if not spec:
        raise ValueError(
            "handle %r carries no launch spec (constructed from a bare "
            "address?) — cannot relaunch" % handle.name)
    spec["port"] = port
    return launch_server(**spec)


class Supervisor:
    """Relaunch crash-looped serving children with capped backoff.

    The re-admission story's last resort: a retired backend whose
    PROCESS is gone cannot pass a half-open probe, so the balancer hands
    its handle here.  ``revive()`` retries :func:`relaunch` under a
    ``RetryPolicy`` budget (exponential backoff, capped at
    ``max_delay_s``, full jitter) and gives up with a typed
    ``RelaunchFailed`` after ``max_attempts`` — a child that dies on
    every boot must not be relaunch-stormed forever.  Every attempt
    (successful or not) increments
    ``wire_backend_relaunches_total{fleet=...}``.

    ``sleep`` is injectable so crash-loop tests run in milliseconds.
    """

    def __init__(self, max_attempts: int = 3, base_delay_s: float = 0.5,
                 max_delay_s: float = 10.0, multiplier: float = 2.0,
                 fleet: str = "supervisor", sleep=None):
        import time as _time

        from paddle_tpu.faults.retry import RetryPolicy

        self.fleet = fleet
        self._policy = RetryPolicy(
            max_attempts=max(1, int(max_attempts)),
            base_delay_s=base_delay_s, multiplier=multiplier,
            max_delay_s=max_delay_s,
            sleep=sleep if sleep is not None else _time.sleep)

    def revive(self, handle: ServerHandle, port: int = 0) -> ServerHandle:
        """A fresh, READY child from ``handle``'s launch spec, or a
        ``RelaunchFailed`` chaining the last boot error."""
        from paddle_tpu.serving.errors import RelaunchFailed
        from paddle_tpu.serving.wire.metrics import WIRE_BACKEND_RELAUNCHES

        relaunches = WIRE_BACKEND_RELAUNCHES.labels(fleet=self.fleet)
        budget = self._policy.budget(op="wire.relaunch")
        last: Exception
        while True:
            relaunches.inc()
            try:
                return relaunch(handle, port=port)
            except Exception as e:  # noqa: BLE001 — typed give-up below
                last = e
            if not budget.backoff():
                raise RelaunchFailed(
                    "giving up on child %r after %d relaunch attempt(s): %r"
                    % (handle.name, budget.attempts, last)) from last


# ---------------------------------------------------------------------------
# child-process main
# ---------------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import signal

    parser = argparse.ArgumentParser(
        description="paddle_tpu serving child process")
    parser.add_argument("--model-dir", required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--name", default="wire")
    parser.add_argument("--replicas", type=int, default=1)
    parser.add_argument("--max-batch-size", type=int, default=32)
    parser.add_argument("--batch-timeout-ms", type=float, default=5.0)
    parser.add_argument("--queue-capacity", type=int, default=256)
    parser.add_argument("--warmup", action="store_true")
    parser.add_argument("--flight-slow-ms", type=float, default=None)
    parser.add_argument("--pipeline-stages", type=int, default=None)
    parser.add_argument("--pipeline-microbatches", type=int, default=4)
    args = parser.parse_args(argv)

    from paddle_tpu import monitor
    from paddle_tpu.serving.wire.server import ServingProcess

    # the endpoint-kind marker is checked WITHOUT importing
    # serving.decode (is_decode_endpoint is just this exists()):
    # non-decode children keep the package's lazy-import policy — no
    # decode metric families registered in processes that never stream
    if os.path.exists(os.path.join(args.model_dir, "decode.json")):
        # a decode endpoint dir (decode.json + weights) hosts the
        # continuous-batching scheduler instead of a request batcher;
        # slot/steps config comes from the saved endpoint
        from paddle_tpu.serving.decode import load_decode_endpoint

        server = load_decode_endpoint(
            args.model_dir,
            queue_capacity=args.queue_capacity,
            name=args.name,
        )
    elif args.pipeline_stages:
        # a pipelined child hosts ONE pp-group predictor per replica:
        # the GPipe schedule spans the child's local devices, and the
        # server routes to the group exactly like a single-chip replica
        from paddle_tpu.parallel.pipeline_predictor import PipelinePredictor
        from paddle_tpu.serving.server import InferenceServer

        predictors = [
            PipelinePredictor(
                args.model_dir, n_stages=args.pipeline_stages,
                num_microbatches=args.pipeline_microbatches)
            for _ in range(max(1, args.replicas))
        ]
        server = InferenceServer(
            predictors,
            max_batch_size=args.max_batch_size,
            batch_timeout_ms=args.batch_timeout_ms,
            queue_capacity=args.queue_capacity,
            name=args.name,
        )
    else:
        from paddle_tpu.inference import (
            AnalysisConfig,
            create_paddle_predictor,
        )
        from paddle_tpu.serving.server import InferenceServer

        predictors = [
            create_paddle_predictor(AnalysisConfig(args.model_dir))
            for _ in range(max(1, args.replicas))
        ]
        server = InferenceServer(
            predictors,
            max_batch_size=args.max_batch_size,
            batch_timeout_ms=args.batch_timeout_ms,
            queue_capacity=args.queue_capacity,
            name=args.name,
        )
    if args.flight_slow_ms is not None:
        monitor.flight_recorder(slow_ms=args.flight_slow_ms)
    if args.warmup:
        server.warmup()
    sp = ServingProcess(server, host=args.host, port=args.port)
    host, port = sp.start()
    done = threading.Event()
    sp._shutdown_cb = done.set

    def _on_term(signum, frame):
        threading.Thread(target=sp._quit, daemon=True).start()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)
    print(READY_PREFIX + json.dumps(
        {"host": host, "port": port, "pid": os.getpid(),
         "name": args.name}), flush=True)
    done.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())
