"""FleetBalancer: least-loaded routing over N serving processes.

The in-process replica fleet's state machine — least-loaded routing,
bounded per-backend in-flight, retirement after consecutive failures,
requeue-to-survivor so accepted requests never drop — promoted from
threads to PROCESSES: each backend is a ``ServingProcess`` on the other
side of a wire transport, failure detection is typed transport errors
(``BackendUnavailable``: the process died mid-exchange) plus an active
``/healthz`` probe loop, and requeues re-SEND the request to a
surviving backend (idempotent by construction: a request whose response
never arrived was never delivered).

Overload control closes the loop fleet-wide: every response meta
carries the server's load report (queue depth, adaptive admit limit,
brownout level), and routing ranks backends by ``in_flight + reported
backlog`` instead of in-flight counts alone — a server drowning in its
own queue stops attracting traffic before it ever fails a health check.
A ``ServerOverloaded`` answer pauses that backend until its
``retry_after_ms`` hint elapses, and EVERY re-dispatch (requeue or
overload retry) spends a token from a token-bucket throttle
(``retry_throttled_total``): under saturation the fleet propagates
back-pressure to callers instead of amplifying its own retries into a
metastable collapse.

Client surface: the same ``infer`` / ``infer_named`` / ``infer_many``
/ ``infer_stream`` contract as ``Client``/``RemoteClient``, so
the balancer drops in wherever a single endpoint handle did.  Fleet
accounting reuses ``ServingMetrics`` — the balancer IS a server-shaped
thing: ``serving_requests_total``/``serving_requeued_total``/the
latency histogram all expose with ``server=<fleet name>``, and
balancer-specific health/retirement counters live in ``wire.metrics``.

Operations: ``warmup()`` pre-compiles every bucket rung on EVERY
backend concurrently (the zero-recompile guarantee becomes fleet-wide
across processes), and ``rolling_replace()`` swaps each launched
backend for a fresh warmed child one at a time — capacity never drops
below N-1 and cold jit caches never see traffic.
"""
from __future__ import annotations

import hashlib
import json
import random
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu import monitor
from paddle_tpu.faults.metrics import BACKEND_HALFOPEN_PROBES
from paddle_tpu.faults.retry import RetryPolicy
from paddle_tpu.monitor import events as _events
from paddle_tpu.monitor import flight as _flight
from paddle_tpu.monitor import registry as _registry
from paddle_tpu.monitor import slo as _slo
from paddle_tpu.monitor import spans as _spans
from paddle_tpu.serving import errors as _errors
from paddle_tpu.serving.errors import (
    BackendUnavailable,
    DeadlineExceeded,
    RelaunchFailed,
    ServerOverloaded,
    ServingError,
    WireProtocolError,
)
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.wire import launch as _launch
from paddle_tpu.serving.wire.client import flight_report as _flight_report
from paddle_tpu.serving.wire.client import (
    pump_stream_messages,
    raise_in_band_error,
    wire_call,
    wire_stream_open,
)
from paddle_tpu.serving.wire.http import HttpTransport
from paddle_tpu.serving.wire.metrics import (
    FEDERATION_SCRAPES,
    FEDERATION_STALENESS,
    FLEET_AFFINITY_HITS,
    RETRY_THROTTLED,
    WIRE_BACKEND_RETIRED,
    WIRE_HEALTH_CHECK_FAILURES,
    WIRE_HEALTH_CHECKS,
)

__all__ = ["FleetBalancer"]

# consecutive request/health failures before a backend leaves routing —
# same limit the in-process replica fleet uses for its workers
_BACKEND_FAIL_LIMIT = 3

# safety-net bound for the all-backends-busy wait (real wakeups are
# notifies from releases/retirements)
_ROUTE_WAIT_S = 0.5

# transport failures the balancer may re-route: the process died
# mid-exchange (no response), it answered that it is shutting down, or
# the frame was corrupted in flight.  Inference is stateless and
# idempotent, so re-sending a corrupted-or-lost exchange to a survivor
# cannot double-apply anything.
_RETRYABLE = (BackendUnavailable, _errors.ServerClosed, WireProtocolError)

# a backend's reported load (queue depth + admit limit in every response
# meta) participates in routing only while this fresh; after that the
# balancer falls back to its own in-flight counts (a stale report from
# a quiet backend must not repel traffic forever)
_LOAD_FRESH_S = 5.0

# cache-affinity routing is a bounded TIE-BREAK, never a mandate: the
# hinted backend (whose prefix KV cache is warm for this prompt head)
# wins only while its load score is within this slack of the
# least-loaded candidate.  A hot-prefix herd therefore spills to other
# backends exactly when least-loaded routing says it should, and a
# browned-out / overloaded / paused backend never attracts traffic on
# the strength of a warm cache (those filters run BEFORE the tie-break).
_AFFINITY_SLACK = 1.0


class _RetryThrottle:
    """Token-bucket pacing for fleet re-dispatch: tokens accrue at
    ``rate_per_s`` up to ``burst``; every requeue/retry spends one.  A
    dry bucket means the fleet's own retries have become the load — the
    typed error propagates to the caller (who holds the retry hint)
    instead of re-storming a saturated backend into metastable
    collapse."""

    def __init__(self, rate_per_s: float = 100.0, burst: int = 32):
        self.rate = float(rate_per_s)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._stamp) * self.rate)
            self._stamp = now
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False


def _probe_jitter(interval_s: float, rng: random.Random) -> float:
    """Per-backend probe spacing: the interval +-15%.  N backends probed
    on one lockstep clock would thundering-herd a server that is just
    coming back; de-phased clocks spread the load."""
    return interval_s * (0.85 + 0.3 * rng.random())


class _Backend:
    """One serving process behind the balancer: transport + health and
    in-flight accounting (the routing state)."""

    __slots__ = ("idx", "name", "transport", "handle", "alive", "in_flight",
                 "executed", "failed", "consec_failures",
                 "consec_health_failures", "retired_at", "removed",
                 "give_up", "next_probe_at", "reported_depth",
                 "reported_limit", "reported_brownout", "load_ts",
                 "not_before", "prefix_hints", "affinity_hits")

    def __init__(self, idx: int, name: str, transport: HttpTransport,
                 handle: Optional[_launch.ServerHandle] = None):
        self.idx = idx
        self.name = name
        self.transport = transport
        self.handle = handle  # launched child (None: bare address)
        self.alive = True
        self.in_flight = 0  # guarded by the balancer's _route_cv
        self.executed = 0
        self.failed = 0
        self.consec_failures = 0
        self.consec_health_failures = 0
        self.retired_at = 0.0     # monotonic stamp of failure retirement
        self.removed = False      # deliberate removal: never re-admit
        self.give_up = False      # supervisor exhausted its relaunches
        self.next_probe_at = 0.0  # per-backend jittered probe clock
        # the server's self-reported load (response meta "load"): queue
        # depth + adaptive admit limit + brownout level, folded into
        # least-loaded routing while fresh (guarded by _route_cv)
        self.reported_depth = 0
        self.reported_limit = 0
        self.reported_brownout = 0
        self.load_ts = None  # monotonic stamp of the last report
        # retry-after pacing: routing skips this backend until the stamp
        # (set from ServerOverloaded.retry_after_ms — a shedding backend
        # must not be re-dispatched to before its own hint elapses)
        self.not_before = 0.0
        # cache-affinity bookkeeping: prompt-prefix hashes this backend
        # served last (bounded LRU, guarded by _route_cv) and how many
        # requests landed here BECAUSE of the hint
        self.prefix_hints: "OrderedDict[str, None]" = OrderedDict()
        self.affinity_hits = 0


class FleetBalancer:
    """Front-end balancer over serving processes.

    ``backends``: ``(host, port)`` tuples and/or ``ServerHandle``s from
    ``launch_server`` (handles enable ``rolling_replace``/
    ``stop(shutdown_backends=True)``).  ``max_in_flight`` bounds
    concurrent requests PER BACKEND (admission control: with every live
    backend at the bound, submitters wait — and time out typed against
    their deadline rather than queuing unboundedly).

    ``prefix_affinity=True`` folds prompt-prefix cache affinity into
    routing: requests whose ``tokens`` feed shares its first
    ``affinity_block`` tokens with an earlier request prefer the backend
    that served it (whose ``PrefixKVCache`` retains that prefix's KV),
    as a bounded tie-break on the load score (``_AFFINITY_SLACK``) —
    never overriding the alive/capacity/retry-after filters or genuine
    load imbalance.  Each backend remembers its last ``affinity_hints``
    prefix hashes; ``serving_fleet_affinity_hits_total`` counts routes
    the hint decided.
    """

    def __init__(self, backends: Sequence, name: str = "fleet",
                 max_in_flight: int = 8,
                 timeout_s: float = 30.0,
                 health_interval_s: Optional[float] = 1.0,
                 cooldown_s: float = 5.0,
                 supervisor: Optional[_launch.Supervisor] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 retry_rate_per_s: float = 100.0,
                 retry_burst: int = 32,
                 prefix_affinity: bool = False,
                 affinity_block: int = 16,
                 affinity_hints: int = 1024,
                 admin_port: Optional[int] = None,
                 scrape_interval_s: float = 2.0):
        if not backends:
            raise ValueError("FleetBalancer needs at least one backend")
        self.name = name
        self._timeout_s = float(timeout_s)
        self._max_in_flight = int(max_in_flight)
        self._backends: List[_Backend] = []
        for i, b in enumerate(backends):
            self._add_backend_obj(i, b)
        # requeue budget per request: enough attempts to try every
        # backend once plus one survivor retry, with a short
        # full-jitter backoff so a fleet-wide blip isn't re-stormed
        self._retry_policy = retry_policy or RetryPolicy(
            max_attempts=max(2, len(self._backends) + 1),
            base_delay_s=0.005, multiplier=2.0, max_delay_s=0.1)
        # token-bucket pacing for EVERY re-dispatch (requeue after a
        # transport failure, paced retry after an overload shed): a dry
        # bucket fails the request typed instead of letting the fleet's
        # own retries amplify saturation into metastable collapse
        self._throttle = _RetryThrottle(retry_rate_per_s, retry_burst)
        self._throttled_counter = RETRY_THROTTLED.labels(fleet=name)
        self._prefix_affinity = bool(prefix_affinity)
        self._affinity_block = int(affinity_block)
        self._affinity_hints = int(affinity_hints)
        self._affinity_counter = FLEET_AFFINITY_HITS.labels(fleet=name)
        # circuit-breaker re-admission: a failure-retired backend goes
        # half-open after cooldown_s and takes one probe; a backend
        # whose PROCESS died is revived through the supervisor (capped
        # backoff) before the probe
        self._cooldown_s = float(cooldown_s)
        self._supervisor = supervisor
        self._metrics = ServingMetrics(name)
        self._retired_counter = WIRE_BACKEND_RETIRED.labels(fleet=name)
        self._health_counter = WIRE_HEALTH_CHECKS.labels(fleet=name)
        self._health_failures = WIRE_HEALTH_CHECK_FAILURES.labels(fleet=name)
        self._halfopen_probes = BACKEND_HALFOPEN_PROBES.labels(
            pool="fleet/%s" % name)
        self._route_cv = threading.Condition()
        self._closed = False
        self._warmed = False
        self._shape_lock = threading.Lock()
        self._feed_names: Optional[List[str]] = None
        self._fetch_names: Optional[List[str]] = None
        self._pool = None  # lazy persistent executor (infer_many)
        self._health_stop = threading.Event()
        self._health_thread = None
        # observability federation: the health thread doubles as the
        # scraper (admin tier only — a balancer without an admin port
        # never issues a scrape), caching each child's /metrics text and
        # /statusz /tracez /eventz docs for the federated admin surface
        self._scrape_interval_s = float(scrape_interval_s)
        self._scrape_lock = threading.Lock()
        self._scrapes: Dict[int, Dict[str, object]] = {}
        # scrape-only children (add_scrape_target): federated into the
        # admin surface but never routed to — how a TRAINING admin
        # (Executor.start_train_admin) joins the fleet's pane of glass.
        # Negative idx keys them into _scrapes without colliding with
        # routing backends.
        self._scrape_only: List[_Backend] = []
        self._scrape_ok = FEDERATION_SCRAPES.labels(fleet=name, status="ok")
        self._scrape_err = FEDERATION_SCRAPES.labels(
            fleet=name, status="error")
        self._staleness = FEDERATION_STALENESS.labels(fleet=name)
        self._admin_server = None
        self._admin_thread = None
        if health_interval_s:
            self._health_thread = threading.Thread(
                target=self._health_loop, args=(float(health_interval_s),),
                name="wire-fleet-health-%s" % name, daemon=True)
            self._health_thread.start()
        if admin_port is not None:
            self.start_admin(admin_port)

    # ------------------------------------------------------------------
    @classmethod
    def from_launch(cls, model_dir: str, n: int, name: str = "fleet",
                    launch_kwargs: Optional[Dict[str, object]] = None,
                    **fleet_kwargs) -> "FleetBalancer":
        """Launch ``n`` serving children for ``model_dir`` and balance
        over them (the one-call fleet constructor)."""
        kw = dict(launch_kwargs or {})
        kw.setdefault("name", name)
        handles = []
        try:
            for i in range(n):
                per = dict(kw)
                per["name"] = "%s-%d" % (kw["name"], i)
                handles.append(_launch.launch_server(model_dir, **per))
        except Exception:
            for h in handles:
                h.kill()
            raise
        return cls(handles, name=name, **fleet_kwargs)

    def _add_backend_obj(self, idx: int, b) -> _Backend:
        if isinstance(b, _launch.ServerHandle):
            be = _Backend(
                idx, "b%d@%s:%d" % (idx, b.host, b.port),
                HttpTransport(b.host, b.port, timeout_s=self._timeout_s),
                handle=b)
        else:
            host, port = b
            be = _Backend(
                idx, "b%d@%s:%d" % (idx, host, port),
                HttpTransport(host, port, timeout_s=self._timeout_s))
        self._backends.append(be)
        return be

    def add_scrape_target(self, name: str, address) -> None:
        """Register a scrape-ONLY child: its ``/metrics`` ``/statusz``
        ``/tracez`` ``/eventz`` surfaces federate into this balancer's
        admin endpoints under ``backend=<name>``, but it never receives
        routed inference traffic or health-gated retirement.  This is
        how a trainer (``Executor.start_train_admin``) shows up in the
        same pane of glass as the serving backends.  ``address`` is a
        ``(host, port)`` tuple (e.g. the value ``start_train_admin``
        returned)."""
        host, port = address
        be = _Backend(
            -1, str(name),
            HttpTransport(host, int(port), timeout_s=self._timeout_s))
        with self._route_cv:
            be.idx = -(len(self._scrape_only) + 1)
            self._scrape_only.append(be)

    # ------------------------------------------------------------------
    @property
    def num_backends(self) -> int:
        with self._route_cv:
            return sum(1 for b in self._backends if b.alive)

    def backend_stats(self) -> Dict[str, Dict[str, object]]:
        with self._route_cv:
            now = time.monotonic()
            return {
                b.name: {
                    "alive": b.alive,
                    "in_flight": b.in_flight,
                    "executed": b.executed,
                    "failed": b.failed,
                    "reported_depth": b.reported_depth,
                    "reported_limit": b.reported_limit,
                    "brownout_level": b.reported_brownout,
                    "load_fresh": (b.load_ts is not None
                                   and now - b.load_ts <= _LOAD_FRESH_S),
                    "paused_ms": max(0.0, (b.not_before - now) * 1e3),
                    "prefix_hints": len(b.prefix_hints),
                    "affinity_hits": b.affinity_hits,
                }
                for b in self._backends
            }

    def metrics(self) -> Dict[str, object]:
        snap = self._metrics.snapshot()
        snap["warmed_up"] = self._warmed
        snap["backends"] = self.backend_stats()
        return snap

    # ------------------------------------------------------------------
    def warmup(self, timeout_s: float = 600.0) -> int:
        """Fleet-wide warmup: every backend pre-compiles every bucket
        rung CONCURRENTLY (backend 2..N typically loads backend 1's
        compiles from the shared persistent cache); returns total
        compiles.  After this, steady-state traffic performs zero XLA
        compiles anywhere in the fleet."""
        results: Dict[str, object] = {}

        def one(be: _Backend):
            try:
                meta, _ = be.transport.request(
                    "/warmup", {}, (), timeout_s=timeout_s)
                from paddle_tpu.serving.wire.client import raise_in_band_error

                raise_in_band_error(meta)
                results[be.name] = int(meta.get("compiles", 0))
            except BaseException as e:  # noqa: BLE001 — surfaced below
                results[be.name] = e

        with self._route_cv:
            live = [b for b in self._backends if b.alive]
        threads = [threading.Thread(target=one, args=(b,), daemon=True)
                   for b in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        errs = {n: r for n, r in results.items()
                if isinstance(r, BaseException)}
        if errs:
            raise ServingError("fleet warmup failed on %s" % sorted(errs))
        compiles = sum(int(r) for r in results.values())
        self._metrics.count("warmup_compiles", compiles)
        self._warmed = True
        return compiles

    # ------------------------------------------------------------------
    # routing: least-loaded live backend, bounded in-flight, requeue on
    # transport failure — the replica state machine across processes
    # ------------------------------------------------------------------
    def _load_score(self, be: _Backend, now: float) -> float:
        """Routing weight: this balancer's own in-flight count plus the
        backend's self-reported backlog (queue depth, while the report
        is fresh) plus its brownout level — a server drowning in its own
        queue stops attracting traffic even when its in-flight count
        looks fine from the outside, and a degraded (browned-out)
        backend ranks behind a healthy equal."""
        score = float(be.in_flight)
        if be.load_ts is not None and now - be.load_ts <= _LOAD_FRESH_S:
            score += float(be.reported_depth) + float(be.reported_brownout)
        return score

    def _affinity_key(self, names, arrays) -> Optional[str]:
        """The routing affinity key for one request: a hash of the first
        ``affinity_block`` tokens of its ``tokens`` feed (the same
        prompt head a backend's ``PrefixKVCache`` keys on), or ``None``
        when affinity is off / the feed has no token prompt / the prompt
        is shorter than one block.  Computed on the submitting thread
        BEFORE dispatch — never inside the routing hot region."""
        if not self._prefix_affinity:
            return None
        try:
            idx = names.index("tokens")
        except ValueError:
            return None
        head = np.asarray(arrays[idx]).reshape(-1)[:self._affinity_block]
        if head.size < self._affinity_block:
            return None
        return hashlib.sha1(
            np.ascontiguousarray(head, np.int32).tobytes()).hexdigest()

    def _pick(self, exclude: Optional[_Backend],
              now: Optional[float] = None,
              affinity_key: Optional[str] = None) -> Optional[_Backend]:
        now = time.monotonic() if now is None else now
        live = [b for b in self._backends
                if b.alive and b is not exclude
                and b.in_flight < self._max_in_flight
                and b.not_before <= now]
        if not live:
            return None
        best = min(live, key=lambda b: self._load_score(b, now))
        if affinity_key is not None:
            # bounded tie-break: the hinted backend (warm prefix KV for
            # this prompt head) wins only within _AFFINITY_SLACK of the
            # least-loaded score, and only after the same eligibility
            # filters every candidate passed — affinity never defeats
            # balancing, overload pacing, or retirement
            bound = self._load_score(best, now) + _AFFINITY_SLACK
            for b in live:
                if (affinity_key in b.prefix_hints
                        and self._load_score(b, now) <= bound):
                    return b
        return best

    def _update_load(self, be: _Backend, load) -> None:
        """Fold one response's load report (success meta ``load``, or
        the same dict re-attached to a typed in-band error) into the
        routing state."""
        if not isinstance(load, dict):
            return
        with self._route_cv:
            try:
                be.reported_depth = int(load.get("queue_depth") or 0)
                be.reported_limit = int(load.get("admit_limit") or 0)
                be.reported_brownout = int(load.get("brownout_level") or 0)
            except (TypeError, ValueError):
                return  # a malformed report never breaks routing
            be.load_ts = time.monotonic()

    def _acquire(self, exclude: Optional[_Backend],
                 deadline: Optional[float],
                 affinity_key: Optional[str] = None) -> _Backend:
        with self._route_cv:
            while True:
                if self._closed:
                    raise _errors.ServerClosed(
                        "fleet %r is stopped" % self.name)
                now = time.monotonic()
                if deadline is not None and now >= deadline:
                    # expired BEFORE taking a slot: fail fast typed —
                    # never burn a backend's in-flight capacity on a
                    # request whose caller already gave up
                    self._metrics.count("expired")
                    raise DeadlineExceeded(
                        "deadline passed before acquiring a backend")
                be = self._pick(exclude, now, affinity_key)
                if be is None and exclude is not None and not any(
                        b.alive and b is not exclude for b in self._backends):
                    be = self._pick(None, now)  # only the excluded one: reuse
                if be is not None:
                    be.in_flight += 1
                    if affinity_key is not None:
                        self._note_affinity_locked(be, affinity_key)
                    return be
                if not any(b.alive for b in self._backends):
                    raise ServingError(
                        "no live backends in fleet %r" % self.name)
                wait = _ROUTE_WAIT_S
                # a retry-after pause expires on a clock, not a notify:
                # wake exactly when the earliest paused backend frees up
                nxt = min((b.not_before for b in self._backends
                           if b.alive and b.not_before > now),
                          default=None)
                if nxt is not None:
                    wait = min(wait, max(0.001, nxt - now))
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        self._metrics.count("expired")
                        raise DeadlineExceeded(
                            "deadline passed waiting for fleet capacity")
                self._route_cv.wait(timeout=wait)

    def _note_affinity_locked(self, be: _Backend, key: str) -> None:
        """Record where this prefix landed (holding _route_cv): a
        returning prefix on its hinted backend is an affinity hit; any
        landing re-hints the key here (the request is about to warm THIS
        backend's prefix cache — after a spill or retirement, future
        requests should follow the KV, not the stale hint)."""
        if key in be.prefix_hints:
            be.prefix_hints.move_to_end(key)
            be.affinity_hits += 1
            self._affinity_counter.inc()
            return
        for other in self._backends:
            other.prefix_hints.pop(key, None)
        be.prefix_hints[key] = None
        while len(be.prefix_hints) > self._affinity_hints:
            be.prefix_hints.popitem(last=False)

    def _release(self, be: _Backend, ok: bool) -> None:
        with self._route_cv:
            be.in_flight -= 1
            if ok:
                be.executed += 1
                be.consec_failures = 0
            self._route_cv.notify_all()

    def _record_failure(self, be: _Backend) -> None:
        with self._route_cv:
            be.failed += 1
            be.consec_failures += 1
            if be.consec_failures >= _BACKEND_FAIL_LIMIT and be.alive:
                self._retire_locked(be, "request failures")

    def _retire_locked(self, be: _Backend, why: str) -> None:
        be.alive = False
        be.retired_at = time.monotonic()  # half-open cooldown starts now
        self._retired_counter.inc()
        # event ring + span-stream instant in one call (emit forwards)
        _events.emit(
            "wire/backend_retired", severity="error", cat="wire",
            fleet=self.name, backend=be.name, reason=why)
        self._route_cv.notify_all()

    def _count_requeue(self, be: _Backend) -> None:
        """One re-routed request: counter + timeline marker move
        together, exactly like the in-process replica requeue."""
        self._metrics.count("requeued")
        monitor.record_instant(
            "serving/batch_requeue", cat="serving",
            server=self.name, replica=be.name)

    # ------------------------------------------------------------------
    def infer(self, feed, timeout_ms: Optional[float] = None,
              trace_id: Optional[str] = None,
              priority: Optional[int] = None,
              precision: Optional[str] = None) -> List[np.ndarray]:
        """One request through the fleet.  A backend that dies
        mid-exchange (``BackendUnavailable``) or answers that it is
        shutting down (``ServerClosed``) retires after repeated failures
        and the request REQUEUES to a survivor — an accepted request
        completes or fails typed, never silently drops.  Deadline /
        validation answers are NOT retried: they are end-state answers
        from a live backend, not lost work.  An overload shed is
        retried — PACED: the shedding backend is skipped until its
        ``retry_after_ms`` hint elapses and every re-dispatch spends a
        token from the fleet's retry throttle
        (``retry_throttled_total`` counts denials), so saturation
        propagates back-pressure instead of a retry storm.
        ``priority`` (``serving.admission.PRIORITY_*``) rides the wire
        meta into the backend's priority shedding; ``precision`` into
        the backend's mixed-precision variant dispatch (every backend
        serves the same saved manifest, so any survivor a requeue
        lands on honors the same choice)."""
        tid = trace_id or monitor.new_trace_id()
        self.last_trace_id = tid
        names, arrays = self._normalize(feed)
        akey = self._affinity_key(names, arrays)
        deadline = (
            time.monotonic() + float(timeout_ms) / 1e3
            if timeout_ms is not None else None)
        self._metrics.count("requests")
        fr = _flight.get()
        rec = _spans.recording() or fr is not None
        if not rec:
            _, routs = self._route(names, arrays, timeout_ms, deadline, tid,
                                   priority=priority, precision=precision,
                                   affinity_key=akey)
            return routs
        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        sid = _spans.new_span_id()
        # capture this thread's wire/request span(s) — a requeued
        # request records one per attempted backend, and the flight
        # record should show every hop it took
        cap: List[Dict] = []
        extra_spans: List[Dict] = []
        try:
            with _spans.trace_context((tid,)):
                with _spans.parent_scope(sid):
                    with _spans.capture(cap):
                        rmeta, routs = self._route(
                            names, arrays, timeout_ms, deadline, tid,
                            priority=priority, precision=precision,
                            affinity_key=akey)
            extra_spans = list(rmeta.get("spans") or ())
            return routs
        except BaseException as e:  # noqa: BLE001 — observed, re-raised
            err = e
            raise
        finally:
            dur = time.perf_counter() - t0
            with _spans.trace_context((tid,)):
                _spans.record_span(
                    "serving/client_infer", t0, dur, cat="client",
                    span_id=sid, error=err is not None, fleet=self.name)
            if fr is not None:
                _flight_report(fr, tid, sid, t0, dur, err,
                               cap + extra_spans, fleet=self.name)

    # hot-path: begin fleet_dispatch (acquire -> wire exchange -> release;
    # the only waits are the bounded capacity CV, the retry budget's
    # jittered backoff, and socket I/O)
    def _route(self, names, arrays, timeout_ms, deadline, tid,
               priority=None, precision=None, affinity_key=None):
        t_submit = time.perf_counter()
        extra = {"precision": str(precision)} if precision is not None else None
        budget = self._retry_policy.budget(
            deadline=deadline, op="fleet.requeue")
        exclude: Optional[_Backend] = None
        while True:
            be = self._acquire(exclude, deadline, affinity_key)
            remaining_ms = timeout_ms
            if deadline is not None:
                remaining_ms = (deadline - time.monotonic()) * 1e3
                if remaining_ms <= 0:
                    # expired while acquiring: a deadline is a typed END
                    # STATE — it must never reach the socket as a 0s
                    # timeout (non-blocking mode), which would read as a
                    # backend failure and retire a healthy fleet.  The
                    # release is NEUTRAL (ok=False only decrements): the
                    # backend never saw the request, so neither its
                    # executed count nor its failure streak may move
                    self._release(be, ok=False)
                    self._metrics.count("expired")
                    raise DeadlineExceeded(
                        "deadline passed before the wire exchange")
            try:
                # the fault gate lives INSIDE the try: an error-mode
                # injection follows the exact release/requeue path a real
                # transport failure takes (never leaks the in-flight slot)
                if _faults.active is not None:  # disarmed: one is-None gate
                    _faults.active.faultpoint(
                        "fleet.dispatch", backend=be.name,
                        pid=be.handle.pid if be.handle is not None else None)
                rmeta, routs = wire_call(
                    be.transport, names, arrays, remaining_ms, tid,
                    priority=priority, extra_meta=extra)
            except _RETRYABLE:
                # retryable: the process died mid-exchange (no response
                # ever arrived), answered that it is shutting down, or
                # the frame corrupted in flight — the request did NOT
                # complete there, so re-sending a stateless inference to
                # a survivor cannot double-run anything
                self._release(be, ok=False)
                self._record_failure(be)
                if deadline is not None and time.monotonic() >= deadline:
                    # fail fast typed at the REQUEUE site: an expired
                    # request must not burn another retry/backend slot
                    self._metrics.count("expired")
                    raise DeadlineExceeded(
                        "deadline passed at requeue after backend failure")
                if not self._throttle.try_acquire():
                    # the token bucket is the anti-storm backstop: a dry
                    # bucket means the fleet's own re-dispatches have
                    # become the load — propagate the failure instead
                    self._throttled_counter.inc()
                    self._metrics.count("failed")
                    raise
                if not budget.backoff():
                    self._metrics.count("failed")
                    raise
                self._count_requeue(be)
                exclude = be
                continue
            except ServerOverloaded as e:
                # the backend ANSWERED (it is alive and shedding):
                # release clean, learn its load report, and honor its
                # retry hint — routing skips it until the hint elapses,
                # so a sick backend never sees a retry storm
                self._release(be, ok=True)
                self._update_load(be, getattr(e, "load", None))
                hint_ms = getattr(e, "retry_after_ms", None)
                if hint_ms:
                    with self._route_cv:
                        be.not_before = max(
                            be.not_before,
                            time.monotonic() + float(hint_ms) / 1e3)
                self._metrics.count("shed")
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                # paced re-dispatch (another backend may have room; this
                # one is paused by not_before): token bucket first, then
                # the jittered backoff budget — either refusing means
                # the shed propagates with its hint intact
                if not self._throttle.try_acquire():
                    self._throttled_counter.inc()
                    raise
                if not budget.backoff():
                    raise
                exclude = be
                continue
            except _errors.ServingError as e:
                # typed end states from a LIVE backend: deadline /
                # validation answers propagate; they also clear the
                # backend's failure streak (it answered)
                self._release(be, ok=True)
                self._update_load(be, getattr(e, "load", None))
                self._metrics.count(
                    "expired" if isinstance(e, DeadlineExceeded)
                    else "failed")
                raise
            except BaseException:
                # anything non-serving (an injected builtin error type, a
                # bug in the transport): the slot must still release, and
                # it counts as a backend failure like any other
                self._release(be, ok=False)
                self._record_failure(be)
                self._metrics.count("failed")
                raise
            self._release(be, ok=True)
            self._update_load(be, rmeta.get("load"))
            self._metrics.observe_request(
                time.perf_counter() - t_submit, trace_id=tid)
            return rmeta, routs
    # hot-path: end fleet_dispatch

    def _normalize(self, feed) -> Tuple[List[str], List[np.ndarray]]:
        names, _ = self._endpoint_shape()
        if not isinstance(feed, dict):
            feed = dict(zip(names, feed))
        if set(feed) != set(names):
            raise ValueError(
                "feed names %s != endpoint inputs %s"
                % (sorted(feed), sorted(names)))
        return names, [feed[n] for n in names]

    def _endpoint_shape(self) -> Tuple[List[str], List[str]]:
        with self._shape_lock:
            if self._feed_names is None:
                last_err: Optional[BaseException] = None
                for be in list(self._backends):
                    try:
                        doc = be.transport.get_json("/healthz")
                        self._feed_names = [
                            str(n) for n in doc["input_names"]]
                        self._fetch_names = [
                            str(n) for n in doc["output_names"]]
                        break
                    except ServingError as e:
                        last_err = e
                else:
                    raise last_err or ServingError(
                        "no backend answered /healthz")
            return self._feed_names, self._fetch_names

    def infer_named(self, feed, timeout_ms: Optional[float] = None,
                    trace_id: Optional[str] = None,
                    priority: Optional[int] = None) -> Dict[str, np.ndarray]:
        _, fetch_names = self._endpoint_shape()
        return dict(zip(fetch_names,
                        self.infer(feed, timeout_ms, trace_id=trace_id,
                                   priority=priority)))

    def infer_many(self, feeds, timeout_ms: Optional[float] = None,
                   priority: Optional[int] = None
                   ) -> List[List[np.ndarray]]:
        """Scatter/gather through a PERSISTENT worker pool: long-lived
        threads keep the transports' per-thread keep-alive connections
        warm across calls (fresh threads would redial every request)."""
        tids = [monitor.new_trace_id() for _ in feeds]
        self.last_trace_ids = tids
        futures = [
            self._executor().submit(self.infer, f, timeout_ms, trace_id=t,
                                    priority=priority)
            for f, t in zip(feeds, tids)
        ]
        return [f.result() for f in futures]

    def _executor(self):
        with self._shape_lock:
            if self._pool is None:
                import concurrent.futures

                self._pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="wire-fleet")
            return self._pool

    def infer_stream(self, feed, timeout_ms: Optional[float] = None,
                     trace_id: Optional[str] = None,
                     priority: Optional[int] = None,
                     max_new_tokens: Optional[int] = None,
                     speculative: Optional[bool] = None):
        """Stream generated-token chunks through the fleet: the request
        routes like ``infer`` (least loaded, retry pacing, requeue), and
        a failure BEFORE the first message — unreachable backend, shed,
        shutdown answer — requeues to a survivor with the same throttle
        and backoff discipline, so opening a stream is as fault-tolerant
        as a unary call.  Once the first message arrives the stream is
        COMMITTED to its backend: generated tokens were already handed
        to the caller, so a mid-stream death re-raises typed
        (``BackendUnavailable``) instead of silently replaying the
        sequence on a survivor — the caller decides whether to resubmit.
        Every chunk carries one trace id (``last_trace_id``); the final
        meta lands in ``last_stream_final``.  ``speculative=True`` asks
        the backend to decode this stream with its draft model
        (greedy-exact, so the tokens are identical either way); the
        backend must have been loaded with a ``draft`` manifest."""
        tid = trace_id or monitor.new_trace_id()
        self.last_trace_id = tid
        names, arrays = self._normalize(feed)
        akey = self._affinity_key(names, arrays)
        deadline = (
            time.monotonic() + float(timeout_ms) / 1e3
            if timeout_ms is not None else None)
        self._metrics.count("requests")
        extra = {}
        if max_new_tokens is not None:
            extra["max_new_tokens"] = int(max_new_tokens)
        if speculative is not None:
            extra["speculative"] = bool(speculative)
        budget = self._retry_policy.budget(
            deadline=deadline, op="fleet.requeue")
        exclude: Optional[_Backend] = None
        while True:
            be = self._acquire(exclude, deadline, akey)
            remaining_ms = timeout_ms
            if deadline is not None:
                remaining_ms = (deadline - time.monotonic()) * 1e3
                if remaining_ms <= 0:
                    self._release(be, ok=False)
                    self._metrics.count("expired")
                    raise DeadlineExceeded(
                        "deadline passed before the wire exchange")
            try:
                if _faults.active is not None:  # disarmed: one is-None gate
                    _faults.active.faultpoint(
                        "fleet.dispatch", backend=be.name,
                        pid=be.handle.pid if be.handle is not None else None)
                it, first = wire_stream_open(
                    be.transport, names, arrays, remaining_ms, tid,
                    extra_meta=extra, priority=priority)
            except _RETRYABLE:
                # nothing streamed yet: the exact unary requeue
                # discipline applies (stateless until the first chunk)
                self._release(be, ok=False)
                self._record_failure(be)
                if deadline is not None and time.monotonic() >= deadline:
                    self._metrics.count("expired")
                    raise DeadlineExceeded(
                        "deadline passed at requeue after backend failure")
                if not self._throttle.try_acquire():
                    self._throttled_counter.inc()
                    self._metrics.count("failed")
                    raise
                if not budget.backoff():
                    self._metrics.count("failed")
                    raise
                self._count_requeue(be)
                exclude = be
                continue
            except ServerOverloaded as e:
                self._release(be, ok=True)
                self._update_load(be, getattr(e, "load", None))
                hint_ms = getattr(e, "retry_after_ms", None)
                if hint_ms:
                    with self._route_cv:
                        be.not_before = max(
                            be.not_before,
                            time.monotonic() + float(hint_ms) / 1e3)
                self._metrics.count("shed")
                if deadline is not None and time.monotonic() >= deadline:
                    raise
                if not self._throttle.try_acquire():
                    self._throttled_counter.inc()
                    raise
                if not budget.backoff():
                    raise
                exclude = be
                continue
            except _errors.ServingError as e:
                self._release(be, ok=True)
                self._update_load(be, getattr(e, "load", None))
                self._metrics.count(
                    "expired" if isinstance(e, DeadlineExceeded)
                    else "failed")
                raise
            except BaseException:
                self._release(be, ok=False)
                self._record_failure(be)
                self._metrics.count("failed")
                raise
            return self._make_stream(be, it, first, tid)

    def _make_stream(self, be: _Backend, it, first, tid: str):
        # a generator abandoned BEFORE its first next() never enters its
        # body, so _stream_chunks' finally can't run and the backend's
        # in-flight slot would leak forever — a GC finalizer covers that
        # window.  ``settled`` makes release one-shot; the finalizer and
        # the generator body can't race (the finalizer only fires once
        # the generator is unreachable, i.e. not executing).
        settled = [False]

        def _abandoned():
            if settled[0]:
                return
            settled[0] = True
            close = getattr(it, "close", None)
            if close is not None:
                close()
            self._release(be, ok=False)  # neutral: not a backend failure

        gen = self._stream_chunks(be, it, first, tid, settled)
        weakref.finalize(gen, _abandoned)
        return gen

    def _stream_chunks(self, be: _Backend, it, first, tid: str,
                       settled: List[bool]):
        t_submit = time.perf_counter()
        fr = _flight.get()
        sid = (_spans.new_span_id()
               if (_spans.recording() or fr is not None) else None)
        err: Optional[BaseException] = None
        clean = False
        counter = [0]
        try:
            rmeta = yield from pump_stream_messages(it, first, counter)
            self.last_stream_final = rmeta
            self._update_load(be, rmeta.get("load"))
            self._metrics.observe_request(
                time.perf_counter() - t_submit, trace_id=tid)
            clean = True
            return
        except GeneratorExit:
            raise  # abandoned: neutral, not a request failure
        except BaseException as e:  # noqa: BLE001 — observed, re-raised
            err = e
            raise
        finally:
            if not settled[0]:
                settled[0] = True
                close = getattr(it, "close", None)
                if close is not None:
                    close()
                if clean:
                    self._release(be, ok=True)
                elif err is None:
                    # abandoned: neutral — the slot frees, the backend's
                    # failure streak does not move
                    self._release(be, ok=False)
                elif (isinstance(err, _errors.ServingError)
                        and not isinstance(err, _RETRYABLE)):
                    # an in-band typed answer (deadline, overload...):
                    # the backend SERVED it — same accounting as the
                    # unary path (release ok, expired vs failed split)
                    self._release(be, ok=True)
                    self._update_load(be, getattr(err, "load", None))
                    self._metrics.count(
                        "expired" if isinstance(err, DeadlineExceeded)
                        else "failed")
                else:
                    # transport death / protocol break mid-stream
                    self._release(be, ok=False)
                    if isinstance(err, _RETRYABLE):
                        self._record_failure(be)
                    self._metrics.count("failed")
            dur = time.perf_counter() - t_submit
            if sid is not None:
                with _spans.trace_context((tid,)):
                    _spans.record_span(
                        "serving/client_stream", t_submit, dur,
                        cat="client", span_id=sid, chunks=counter[0],
                        error=err is not None, fleet=self.name,
                        backend=be.name)
            if fr is not None:
                # the stream's flight record names the backend that
                # served it: /tracez answers "which process decoded this
                # stream" without correlating server-side logs
                span = {
                    "name": "serving/client_stream", "cat": "client",
                    "id": sid, "ts": _spans.wall_ts(t_submit), "dur": dur,
                    "tid": threading.get_ident(), "trace_ids": [tid],
                    "chunks": counter[0], "fleet": self.name,
                    "backend": be.name,
                }
                if err is not None:
                    span["error"] = True
                if fr.get_record(tid) is not None:
                    fr.add_span(tid, span)
                elif clean or err is not None:
                    # abandonment (err None, not clean) is neutral: it
                    # must not occupy the bounded ring
                    status = ("ok" if err is None else
                              "deadline" if isinstance(err, DeadlineExceeded)
                              else "error")
                    fr.consider(tid, dur, status, [span],
                                fleet=self.name, backend=be.name)

    # ------------------------------------------------------------------
    # health checking + rolling replacement
    # ------------------------------------------------------------------
    def _health_loop(self, interval_s: float) -> None:
        # each backend owns a de-phased probe clock (see _probe_jitter):
        # N backends must not fire /healthz in lockstep at a server that
        # is just recovering.  The same loop runs the circuit breaker's
        # re-admission pass for retired backends.
        rng = random.Random("probe-jitter:%s" % self.name)
        now = time.monotonic()
        with self._route_cv:
            for be in self._backends:
                be.next_probe_at = now + interval_s * rng.random()
        while True:
            with self._route_cv:
                targets = [b for b in self._backends if b.alive]
            now = time.monotonic()
            for be in targets:
                if be.next_probe_at > now:
                    continue
                be.next_probe_at = now + _probe_jitter(interval_s, rng)
                self._health_counter.inc()
                try:
                    doc = be.transport.get_json("/healthz", timeout_s=2.0)
                    healthy = bool(doc.get("ok"))
                except ServingError:
                    healthy = False
                if healthy:
                    be.consec_health_failures = 0
                    continue
                self._health_failures.inc()
                be.consec_health_failures += 1
                if be.consec_health_failures >= _BACKEND_FAIL_LIMIT:
                    with self._route_cv:
                        if be.alive:
                            self._retire_locked(be, "health checks")
            # observability federation rides the same background loop
            # (never the request path): scrape due backends' admin
            # surfaces into the cache the admin endpoints serve from
            if self._admin_server is not None:
                self._scrape_pass()
            self._reanimate()
            with self._route_cv:
                nxt = min((b.next_probe_at for b in self._backends
                           if b.alive), default=now + interval_s)
            wait = max(0.01, min(interval_s, nxt - time.monotonic()))
            if self._health_stop.wait(wait):
                return

    # ------------------------------------------------------------------
    # circuit-breaker re-admission: retired -> (cooldown) -> half-open
    # probe -> rejoined, with the supervisor reviving dead processes
    # ------------------------------------------------------------------
    def _reanimate(self) -> None:
        """One re-admission pass.  A backend retired for FAILURES (not
        removed by an operator/rolling replacement) whose cooldown
        elapsed goes half-open: a dead child process is first revived
        through the supervisor (capped-backoff relaunch), then ONE
        ``/healthz`` probe decides — success rejoins routing with one
        remaining strike, failure restarts the cooldown.  Runs on the
        health thread; also callable directly (tests, no-thread use)."""
        now = time.monotonic()
        with self._route_cv:
            candidates = [
                b for b in self._backends
                if not b.alive and not b.removed and not b.give_up
                and now - b.retired_at >= self._cooldown_s
            ]
        for be in candidates:
            if be.handle is not None and be.handle.poll() is not None:
                if self._supervisor is None:
                    continue  # process is gone and nothing can revive it
                try:
                    handle = self._supervisor.revive(be.handle)
                except RelaunchFailed:
                    with self._route_cv:
                        be.give_up = True
                    continue
                if self._warmed:
                    # the fleet promised zero recompiles: a revived child
                    # warms before it can rejoin routing
                    try:
                        handle.warmup()
                    except ServingError:
                        handle.kill()
                        with self._route_cv:
                            be.retired_at = time.monotonic()
                        continue
                with self._route_cv:
                    old_transport = be.transport
                    be.handle = handle
                    be.transport = HttpTransport(
                        handle.host, handle.port, timeout_s=self._timeout_s)
                    be.name = "b%d@%s:%d" % (be.idx, handle.host, handle.port)
                old_transport.close()
            self._halfopen_probes.inc()
            try:
                ok = bool(be.transport.get_json(
                    "/healthz", timeout_s=2.0).get("ok"))
            except ServingError:
                ok = False
            with self._route_cv:
                if be.removed or be.alive:
                    continue
                if ok:
                    be.alive = True
                    be.consec_health_failures = 0
                    # half-open: ONE remaining strike — the next request
                    # failure re-retires immediately, a success resets
                    be.consec_failures = _BACKEND_FAIL_LIMIT - 1
                    # a rejoined backend starts with a clean load slate:
                    # pre-retirement reports and pauses describe a
                    # process state that no longer exists
                    be.not_before = 0.0
                    be.load_ts = None
                    self._route_cv.notify_all()
                else:
                    be.retired_at = time.monotonic()
            if ok:
                _events.emit(
                    "wire/backend_readmitted", severity="info", cat="wire",
                    fleet=self.name, backend=be.name)

    def check_health(self) -> Dict[str, bool]:
        """One synchronous probe round (bench/test convenience; the
        background loop does this continuously)."""
        out = {}
        for be in list(self._backends):
            self._health_counter.inc()
            try:
                doc = be.transport.get_json("/healthz", timeout_s=2.0)
                out[be.name] = bool(doc.get("ok"))
            except ServingError:
                self._health_failures.inc()
                out[be.name] = False
        return out

    # ------------------------------------------------------------------
    # observability federation: scrape cache + fleet-merged admin docs
    # ------------------------------------------------------------------
    def _scrape_backend(self, be: _Backend) -> None:
        """Fetch one backend's observability surfaces into the cache.
        Partial failure keeps the previous (stale) docs — the federated
        view degrades to older data, never to a hole."""
        docs: Dict[str, object] = {}
        ok = True
        try:
            docs["metrics_text"] = be.transport.get_text(
                "/metrics", timeout_s=2.0)
        except (ServingError, NotImplementedError):
            ok = False
        for key, path in (("statusz", "/statusz"), ("tracez", "/tracez"),
                          ("eventz", "/eventz")):
            try:
                docs[key] = be.transport.get_json(path, timeout_s=2.0)
            except ServingError:
                ok = False
        (self._scrape_ok if ok else self._scrape_err).inc()
        if not docs:
            return
        with self._scrape_lock:
            ent = self._scrapes.setdefault(be.idx, {})
            ent.update(docs)
            ent["backend"] = be.name
            ent["ts"] = time.monotonic()
            ent["wall_ts"] = time.time()

    def _scrape_pass(self, force: bool = False) -> None:
        """One scrape round over live backends whose per-backend clock
        is due (``force`` ignores the clocks), then refresh the
        worst-case staleness gauge."""
        with self._route_cv:
            targets = [b for b in self._backends if b.alive]
            targets.extend(self._scrape_only)
        now = time.monotonic()
        for be in targets:
            with self._scrape_lock:
                due = self._scrapes.get(be.idx, {}).get("next_at", 0.0)
            if not force and due > now:
                continue
            with self._scrape_lock:
                self._scrapes.setdefault(be.idx, {})["next_at"] = (
                    now + self._scrape_interval_s)
            self._scrape_backend(be)
        with self._scrape_lock:
            ages = [time.monotonic() - s["ts"]
                    for b in targets
                    for s in (self._scrapes.get(b.idx),)
                    if s is not None and "ts" in s]
        if ages:
            self._staleness.set(round(max(ages), 3))

    def scrape_once(self) -> None:
        """Synchronously refresh every live backend's cached
        observability docs (bench/test convenience; the health loop
        does this continuously once the admin tier is up)."""
        self._scrape_pass(force=True)

    def _scrape_snapshot(self) -> List[Dict[str, object]]:
        with self._scrape_lock:
            return [dict(self._scrapes[i]) for i in sorted(self._scrapes)
                    if "backend" in self._scrapes[i]]

    def federated_metrics(self) -> str:
        """The balancer's own registry plus every scraped child
        exposition re-labeled ``backend=<id>`` (an already-labeled
        child — itself a federating balancer — gets prefixed, so a
        routing tree federates transitively), merged into one
        Prometheus text-0.0.4 document."""
        parts = [_registry.parse_exposition(monitor.render_text())]
        for s in self._scrape_snapshot():
            text = s.get("metrics_text")
            if not text:
                continue
            parts.append(_registry.relabel_exposition(
                _registry.parse_exposition(text), "backend",
                str(s["backend"])))
        return _registry.render_exposition(
            _registry.merge_expositions(parts))

    def federated_statusz(self) -> Dict[str, object]:
        """Fleet-merged ``/statusz``: the balancer's own routing view,
        every child's cached statusz verbatim, and TRUE cross-fleet
        aggregates over the scraped expositions (summed counters,
        bucket-merged histograms with estimated quantiles, worst-case
        gauges)."""
        now = time.monotonic()
        scrapes = self._scrape_snapshot()
        children = {}
        parts = []
        for s in scrapes:
            entry: Dict[str, object] = {
                "age_s": round(now - s["ts"], 3) if "ts" in s else None}
            if "statusz" in s:
                entry["statusz"] = s["statusz"]
            children[str(s["backend"])] = entry
            if s.get("metrics_text"):
                parts.append(_registry.parse_exposition(s["metrics_text"]))
        return {
            "fleet": self.name,
            "role": "balancer",
            "balancer": self.metrics(),
            "backends": children,
            "aggregate": _registry.aggregate_families(
                _registry.merge_expositions(parts)),
        }

    def federated_tracez(self) -> Dict[str, object]:
        """One slow-request list across the fleet: the balancer's own
        flight recorder plus every child's cached ``/tracez``, records
        tagged with the backend they came from (trace trees intact),
        newest first."""
        requests: List[Dict[str, object]] = []
        retained: Dict[str, int] = {}
        fr = _flight.get()
        if fr is not None:
            own = fr.statusz()
            retained["_balancer"] = own.get("retained", 0)
            for r in own.get("requests", ()):
                r = dict(r)
                r["backend"] = "_balancer"
                requests.append(r)
        for s in self._scrape_snapshot():
            doc = s.get("tracez")
            if not isinstance(doc, dict):
                continue
            name = str(s["backend"])
            retained[name] = doc.get("retained", 0)
            for r in doc.get("requests", ()):
                r = dict(r)
                r["backend"] = name
                requests.append(r)
        requests.sort(key=lambda r: r.get("ts") or 0.0, reverse=True)
        return {"fleet": self.name, "role": "balancer",
                "backends": retained, "requests": requests}

    def federated_eventz(self) -> Dict[str, object]:
        """Fleet-merged operational event tail: the balancer's own ring
        plus every child's cached ``/eventz``, backend-tagged, ordered
        by wall timestamp."""
        merged: List[Dict[str, object]] = []
        own = _events.eventz()
        for e in own.get("events", ()):
            e = dict(e)
            e["backend"] = "_balancer"
            merged.append(e)
        backends = {"_balancer": own.get("retained", 0)}
        for s in self._scrape_snapshot():
            doc = s.get("eventz")
            if not isinstance(doc, dict):
                continue
            name = str(s["backend"])
            backends[name] = doc.get("retained", 0)
            for e in doc.get("events", ()):
                e = dict(e)
                e["backend"] = name
                merged.append(e)
        merged.sort(key=lambda e: e.get("ts") or 0.0)
        return {"fleet": self.name, "role": "balancer",
                "backends": backends, "events": merged}

    def admin_healthz(self) -> Dict[str, object]:
        with self._route_cv:
            alive = sum(1 for b in self._backends if b.alive)
            total = len(self._backends)
            closed = self._closed
        return {"ok": not closed and alive > 0, "role": "balancer",
                "fleet": self.name, "backends_alive": alive,
                "backends_total": total}

    # ------------------------------------------------------------------
    # admin HTTP tier: the balancer's own pane of glass
    # ------------------------------------------------------------------
    def start_admin(self, port: int = 0) -> Tuple[str, int]:
        """Serve the federated observability surface from this balancer:
        ``/healthz /metrics /statusz /tracez /sloz /eventz`` (GET) and
        ``/quitquitquit`` (POST).  ``port=0`` binds an ephemeral port;
        returns the bound ``(host, port)`` (also ``admin_address``).
        Starting the admin tier is what arms the federation scraper on
        the health loop — a balancer without one never scrapes."""
        if self._admin_server is not None:
            return self.admin_address
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        fleet = self

        class _AdminHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet, like the wire server
                pass

            def _send(self, status: int, body: bytes, ctype: str) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                try:
                    self.wfile.write(body)
                except (ConnectionError, BrokenPipeError):
                    pass

            def _send_json(self, doc, status: int = 200) -> None:
                self._send(status, json.dumps(doc).encode("utf-8"),
                           "application/json; charset=utf-8")

            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send_json(fleet.admin_healthz())
                    elif path == "/metrics":
                        self._send(
                            200,
                            fleet.federated_metrics().encode("utf-8"),
                            "text/plain; version=0.0.4; charset=utf-8")
                    elif path == "/statusz":
                        self._send_json(fleet.federated_statusz())
                    elif path == "/tracez":
                        self._send_json(fleet.federated_tracez())
                    elif path == "/sloz":
                        self._send_json(_slo.sloz())
                    elif path == "/eventz":
                        self._send_json(fleet.federated_eventz())
                    else:
                        self.send_error(404, "unknown path")
                except Exception as e:  # noqa: BLE001 — typed to the peer
                    self._send_json({"error": type(e).__name__,
                                     "message": str(e)}, status=500)

            def do_POST(self):
                path = self.path.split("?", 1)[0]
                if path == "/quitquitquit":
                    self._send_json({"ok": True, "admin_stopping": True})
                    threading.Thread(
                        target=fleet._stop_admin,
                        name="fleet-admin-quit", daemon=True).start()
                else:
                    self.send_error(404, "unknown path")

        class _QuietAdminServer(ThreadingHTTPServer):
            daemon_threads = True

            def handle_error(self, request, client_address):
                import sys
                exc = sys.exc_info()[1]
                if isinstance(exc, (ConnectionError, BrokenPipeError)):
                    return
                super().handle_error(request, client_address)

        srv = _QuietAdminServer(("127.0.0.1", int(port)), _AdminHandler)
        self._admin_server = srv
        self._admin_thread = threading.Thread(
            target=srv.serve_forever,
            name="fleet-admin-%s" % self.name, daemon=True)
        self._admin_thread.start()
        # first federated view without waiting a full scrape interval
        try:
            self.scrape_once()
        except Exception:
            pass
        return self.admin_address

    @property
    def admin_address(self) -> Optional[Tuple[str, int]]:
        """Bound ``(host, port)`` of the admin tier, or None."""
        srv = self._admin_server
        if srv is None:
            return None
        return srv.server_address[0], srv.server_address[1]

    def _stop_admin(self) -> None:
        srv, self._admin_server = self._admin_server, None
        thread, self._admin_thread = self._admin_thread, None
        if srv is not None:
            srv.shutdown()
            srv.server_close()
        if thread is not None and thread is not threading.current_thread():
            thread.join(timeout=5.0)

    def rolling_replace(self, warmup: bool = True,
                        drain_timeout_s: float = 30.0
                        ) -> List[_launch.ServerHandle]:
        """Replace every LAUNCHED backend with a fresh child, one at a
        time: launch new -> (optionally) warm it -> add to routing ->
        drain the old -> shut it down.  Routable capacity never drops
        below the current live count, and a cold jit cache never sees
        traffic.  Backends constructed from bare addresses are skipped
        (nothing to relaunch)."""
        new_handles: List[_launch.ServerHandle] = []
        with self._route_cv:
            olds = [b for b in self._backends
                    if b.alive and b.handle is not None]
        for old in olds:
            handle = _launch.relaunch(old.handle)
            if warmup:
                handle.warmup()
            with self._route_cv:
                be = self._add_backend_obj(len(self._backends), handle)
                self._route_cv.notify_all()
            new_handles.append(handle)
            # drain: stop routing to the old backend, let its in-flight
            # requests finish, then ask the process to exit gracefully.
            # removed (not retired): re-admission must never resurrect a
            # deliberately replaced backend
            with self._route_cv:
                old.alive = False
                old.removed = True
                self._route_cv.notify_all()
                deadline = time.monotonic() + drain_timeout_s
                while old.in_flight > 0 and time.monotonic() < deadline:
                    self._route_cv.wait(timeout=0.1)
            _events.emit(
                "wire/backend_replaced", severity="info", cat="wire",
                fleet=self.name, old=old.name, new=be.name)
            old.handle.shutdown(timeout_s=drain_timeout_s)
            old.transport.close()
        return new_handles

    # ------------------------------------------------------------------
    def stop(self, shutdown_backends: bool = False,
             timeout_s: float = 30.0) -> None:
        """Stop balancing (in-flight requests finish; new ones are
        refused typed).  ``shutdown_backends=True`` additionally drains
        and exits every LAUNCHED child."""
        with self._route_cv:
            if self._closed:
                return
            self._closed = True
            self._route_cv.notify_all()
        self._health_stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
        self._stop_admin()
        # retire this fleet's federation series from the exposition
        FEDERATION_SCRAPES.remove_labels(fleet=self.name, status="ok")
        FEDERATION_SCRAPES.remove_labels(fleet=self.name, status="error")
        FEDERATION_STALENESS.remove_labels(fleet=self.name)
        with self._shape_lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        if shutdown_backends:
            for be in self._backends:
                if be.handle is not None:
                    be.handle.shutdown(timeout_s=timeout_s)
        for be in self._backends:
            be.transport.close()
        for be in self._scrape_only:
            be.transport.close()
        self._metrics.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
