"""ServingProcess: one InferenceServer behind the wire transport.

The process-boundary half of cross-host serving: an HTTP front door
over one ``InferenceServer`` (which keeps its whole in-process story —
dynamic batching, bucket ladder, replica fleet, zero-recompile warmup),
exposing

* ``POST /infer``     — one request in the ``codec`` framing (meta
  carries ``feed_names``/``timeout_ms``; arrays positional), response
  carries ``output_names`` + output arrays.  Typed serving errors
  travel in-band (``error``/``message`` meta fields + a mapped status
  code) so the remote client re-raises the exact error type the
  in-process client would have seen.
* ``POST /warmup``    — fleet-wide warmup hook: pre-compiles every
  bucket rung on every replica, returns the compile count.
* ``GET  /healthz``   — liveness + endpoint shape (input/output names):
  the balancer's health-check and discovery surface.
* ``GET  /metrics`` ``/statusz`` ``/tracez`` — the same admin surface
  ``InferenceServer.start_admin()`` serves, on the wire port.
* ``POST /quitquitquit`` — graceful drain + exit (rolling replacement).

Tracing across the hop: a request carrying a W3C ``traceparent`` header
joins the client's trace — its trace id flows through the batcher →
replica → executor span chain, the server-side request span records the
client's wire span as its REMOTE PARENT, and (when this process has a
flight recorder installed) the retained server-side span tree is
returned in the response meta so the client-side recorder merges ONE
cross-process tree per request.
"""
from __future__ import annotations

import json
import threading
import time
from typing import Dict, Optional, Tuple

from paddle_tpu import monitor
from paddle_tpu.monitor import events as _events
from paddle_tpu.monitor import flight as _flight
from paddle_tpu.monitor import slo as _slo
from paddle_tpu.monitor import spans as _spans
from paddle_tpu.serving.errors import (
    DeadlineExceeded,
    ServerClosed,
    ServerOverloaded,
    ServingError,
    WireProtocolError,
)
from paddle_tpu.serving.wire import codec
from paddle_tpu.serving.wire.http import CONTENT_TYPE
from paddle_tpu.serving.wire.metrics import (
    WIRE_BYTES_RECEIVED,
    WIRE_BYTES_SENT,
    WIRE_REQUESTS,
)

__all__ = ["ServingProcess", "error_status"]

_REQS = WIRE_REQUESTS.labels(role="server")
_SENT = WIRE_BYTES_SENT.labels(role="server")
_RECV = WIRE_BYTES_RECEIVED.labels(role="server")

# /infer's grace poll for the flight recorder to finish filing the
# request's span tree after its future completed: the replica finalizer
# completes futures a few microseconds before it files the record, so a
# handful of short polls close the race — and a request the recorder
# chose NOT to retain (slow_ms tail sampling) gives up after the same
# small bound instead of stalling the response (tracing-only path)
_SPAN_MERGE_POLLS = 10
_SPAN_MERGE_POLL_S = 0.002

# typed error -> HTTP status (the in-band meta "error" field is the
# authoritative type channel; the status code is for generic tooling)
_STATUS = (
    (ServerOverloaded, 429),
    (DeadlineExceeded, 504),
    (ServerClosed, 503),
    (WireProtocolError, 400),
    (ValueError, 400),
    (ServingError, 500),
)


def error_status(exc: BaseException) -> int:
    for etype, status in _STATUS:
        if isinstance(exc, etype):
            return status
    return 500


class ServingProcess:
    """Bind an ``InferenceServer`` to a wire port.

    ``start()`` serves on a daemon thread and returns the bound address
    (``port=0`` = ephemeral); ``serve_forever()`` blocks the calling
    thread instead (the ``launch.py`` child main).  ``stop()`` closes
    the HTTP front door and then stops the wrapped server."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 max_frame_bytes: int = codec.DEFAULT_MAX_FRAME_BYTES,
                 max_body_bytes: Optional[int] = None):
        self.server = server
        self._host = host
        self._port = int(port)
        self._max_frame_bytes = int(max_frame_bytes)
        # whole-body cap: a codec MESSAGE may span several frames (one
        # per feed array), so the body bound is a multiple of the
        # per-frame bound, not equal to it
        self._max_body_bytes = (
            int(max_body_bytes) if max_body_bytes is not None
            else 4 * self._max_frame_bytes + 65536)
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._shutdown_cb = None  # launch.py hooks /quitquitquit

    # ------------------------------------------------------------------
    @property
    def address(self) -> Optional[Tuple[str, int]]:
        return self._httpd.server_address if self._httpd is not None else None

    def _bind(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        sp = self

        class _WireHandler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # keep-alive for pooled clients

            def log_message(self, *args):
                pass  # scrapes/requests stay out of stderr

            # -- plumbing ------------------------------------------------
            def _send(self, status: int, body: bytes, ctype: str,
                      extra_headers=None) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, doc, status: int = 200) -> None:
                self._send(status,
                           json.dumps(doc, sort_keys=True,
                                      default=str).encode("utf-8"),
                           "application/json")

            def _send_message(self, meta, arrays=(), status: int = 200,
                              extra_headers=None) -> None:
                body = codec.encode_message(meta, arrays)
                _SENT.inc(len(body))
                self._send(status, body, CONTENT_TYPE,
                           extra_headers=extra_headers)

            def _read_body(self) -> bytes:
                length = int(self.headers.get("Content-Length") or 0)
                if length > sp._max_body_bytes:
                    # reject WITHOUT reading — and drop the keep-alive
                    # connection, since the unread body would desync the
                    # next request on this socket
                    self.close_connection = True
                    raise WireProtocolError(
                        "request body of %d bytes exceeds the %d-byte "
                        "wire bound" % (length, sp._max_body_bytes))
                body = self.rfile.read(length)
                _RECV.inc(len(body))
                return body

            def _drain_body(self) -> None:
                """Consume a control POST's body so the HTTP/1.1
                keep-alive connection stays in sync for the client's
                next pooled request (an unread body would be parsed as
                the next request line)."""
                try:
                    self._read_body()
                except WireProtocolError:
                    pass  # close_connection already set

            # -- GET surfaces (health + admin) ---------------------------
            def do_GET(self):
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/healthz":
                        self._send_json(sp.healthz())
                    elif path == "/metrics":
                        om = "application/openmetrics-text" in (
                            self.headers.get("Accept") or "")
                        text, ctype = monitor.expose(openmetrics=om)
                        self._send(200, text.encode("utf-8"), ctype)
                    elif path == "/statusz":
                        self._send_json(sp.server.statusz())
                    elif path == "/tracez":
                        self._send_json(sp.server.tracez())
                    elif path == "/sloz":
                        self._send_json(_slo.sloz())
                    elif path == "/eventz":
                        self._send_json(_events.eventz())
                    else:
                        self.send_error(404, "unknown path")
                except Exception as e:  # noqa: BLE001 — typed to the peer
                    self._send_json({"error": type(e).__name__,
                                     "message": str(e)}, status=500)

            # -- POST surfaces (infer + control) -------------------------
            def do_POST(self):
                path = self.path.split("?", 1)[0]
                if path == "/infer":
                    self._do_infer()
                elif path == "/infer_stream":
                    self._do_infer_stream()
                elif path == "/warmup":
                    self._drain_body()
                    try:
                        compiles = sp.server.warmup()
                        self._send_message({"compiles": int(compiles)})
                    except Exception as e:  # noqa: BLE001
                        self._send_message(
                            {"error": type(e).__name__, "message": str(e)},
                            status=500)
                elif path == "/quitquitquit":
                    self._drain_body()
                    self._send_message({"ok": True, "draining": True})
                    threading.Thread(
                        target=sp._quit, name="wire-quit", daemon=True
                    ).start()
                else:
                    self.send_error(404, "unknown path")

            def _decode_infer_body(self):
                meta, arrays = codec.decode_message(
                    self._read_body(),
                    max_frame_bytes=sp._max_frame_bytes)
                feed_names = meta.get("feed_names")
                if (not isinstance(feed_names, list)
                        or len(feed_names) != len(arrays)):
                    raise WireProtocolError(
                        "feed_names/arrays mismatch: %r names, %d arrays"
                        % (feed_names, len(arrays)))
                return meta, dict(zip(feed_names, arrays))

            def _send_error_message(self, e: BaseException) -> None:
                """The one-message typed-error response (shared by
                /infer and a pre-stream /infer_stream failure)."""
                emeta = {"error": type(e).__name__, "message": str(e),
                         "load": sp._load_meta()}
                headers = None
                retry_ms = getattr(e, "retry_after_ms", None)
                if retry_ms is not None:
                    # the in-band channel carries the exact hint; the
                    # HTTP Retry-After header (whole seconds, ceil'd
                    # to stay >= the hint) is for generic tooling
                    emeta["retry_after_ms"] = float(retry_ms)
                    headers = {"Retry-After":
                               str(int(-(-float(retry_ms) // 1000)))}
                emeta["final"] = True  # a stream reader ends here too
                try:
                    self._send_message(
                        emeta, status=error_status(e),
                        extra_headers=headers)
                except Exception:
                    pass  # peer already gone; nothing to report to

            def _do_infer(self):
                _REQS.inc()
                try:
                    meta, feed = self._decode_infer_body()
                    rmeta, routs = sp._infer(
                        feed, meta.get("timeout_ms"),
                        traceparent=self.headers.get("traceparent"),
                        want_spans=self.headers.get("X-Wire-Spans") == "1",
                        priority=meta.get("priority"),
                        precision=meta.get("precision"))
                except BaseException as e:  # noqa: BLE001 — typed to the peer
                    self._send_error_message(e)
                    return
                self._send_message(rmeta, routs)

            # -- streaming (continuous-batching decode endpoints) --------
            def _write_chunk(self, payload: bytes) -> None:
                self.wfile.write(b"%x\r\n" % len(payload))
                self.wfile.write(payload)
                self.wfile.write(b"\r\n")

            def _do_infer_stream(self):
                """One decode request, answered as a CHUNKED stream of
                codec messages: one message per token chunk as the
                scheduler produces it (meta carries the trace id + a
                chunk sequence number), then one ``final`` message
                (completion, or the typed mid-stream error).  A
                pre-stream failure answers exactly like ``/infer`` —
                one typed-error message the stream reader also
                understands (``final`` set)."""
                _REQS.inc()
                try:
                    meta, feed = self._decode_infer_body()
                    req, tid = sp._submit_stream(
                        feed, meta,
                        traceparent=self.headers.get("traceparent"))
                except BaseException as e:  # noqa: BLE001 — typed to the peer
                    self._send_error_message(e)
                    return
                # headers commit here: everything after — including a
                # mid-stream failure — travels inside the chunked body
                self.send_response(200)
                self.send_header("Content-Type", CONTENT_TYPE)
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                t0 = time.perf_counter()
                seq = 0
                err: Optional[BaseException] = None
                try:
                    try:
                        for tokens in req.stream():
                            payload = codec.encode_message(
                                {"trace_id": tid, "seq": seq}, (tokens,))
                            self._write_chunk(payload)
                            _SENT.inc(len(payload))
                            seq += 1
                    except BaseException as e:  # noqa: BLE001 — in-band
                        err = e
                    fmeta: Dict[str, object] = {
                        "final": True, "trace_id": tid, "chunks": seq,
                        "output_names":
                            list(sp.server._predictor.get_output_names()),
                        "load": sp._load_meta()}
                    if err is not None:
                        fmeta["error"] = type(err).__name__
                        fmeta["message"] = str(err)
                        retry_ms = getattr(err, "retry_after_ms", None)
                        if retry_ms is not None:
                            fmeta["retry_after_ms"] = float(retry_ms)
                    payload = codec.encode_message(fmeta)
                    self._write_chunk(payload)
                    _SENT.inc(len(payload))
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    # the peer hung up mid-stream: abandon the decode so
                    # its slot frees for queued work, and drop the (now
                    # desynced) connection
                    req.fail(ServerClosed("stream consumer went away"))
                    self.close_connection = True
                finally:
                    with _spans.trace_context((tid,)):
                        _spans.record_span(
                            "wire/server_stream", t0,
                            time.perf_counter() - t0, cat="wire",
                            chunks=seq, error=err is not None,
                            server=sp.server.name)

        class _QuietServer(ThreadingHTTPServer):
            def handle_error(self, request, client_address):
                # a peer dropping its pooled connection (reset between
                # keep-alive requests, an abandoned stream) is a normal
                # event, not a server error worth a stderr traceback
                import sys

                etype = sys.exc_info()[0]
                if etype is not None and issubclass(
                        etype, (ConnectionError, BrokenPipeError)):
                    return
                super().handle_error(request, client_address)

        with self._lock:
            if self._httpd is None:
                self._httpd = _QuietServer(
                    (self._host, self._port), _WireHandler)
            return self._httpd

    # ------------------------------------------------------------------
    def healthz(self) -> Dict[str, object]:
        """Liveness + endpoint discovery: the balancer health-checks
        this and the remote client reads the feed/fetch names from it."""
        import os

        srv = self.server
        m = srv.metrics()
        return {
            "ok": srv.num_replicas > 0,
            "pid": os.getpid(),
            "server": srv.name,
            "warmed_up": bool(m.get("warmed_up")),
            "live_replicas": srv.num_replicas,
            "queue_depth": m.get("queue_depth"),
            "admit_limit": m.get("admit_limit"),
            "brownout_level": m.get("brownout_level"),
            "max_batch_size": srv.max_batch_size,
            "streaming": bool(getattr(srv, "supports_streaming", False)),
            # decode tier 2 discovery: the balancer's affinity routing
            # and the bench read whether this endpoint retains prefix KV
            # and/or carries a draft model (None on non-decode servers)
            "prefix_cache": (
                srv.prefix_cache.stats()
                if getattr(srv, "prefix_cache", None) is not None else None),
            "speculative_k": getattr(srv, "speculative_k", None),
            # a sharded backend is one MODEL-PARALLEL GROUP of devices
            # behind one address — the balancer routes to groups exactly
            # like single-chip replicas (in-flight accounting, warmup,
            # retirement unchanged)
            "sharded": bool(getattr(srv._predictor, "sharded", False)),
            # a pipelined backend is one pp-GROUP behind one address:
            # the balancer and bench read the stage count + structural
            # bubble ratio here (None on unpipelined endpoints)
            "pipeline": (
                srv._predictor.pipeline_stats()
                if callable(getattr(srv._predictor, "pipeline_stats",
                                    None)) else None),
            # mixed-precision discovery: the policy dtype this endpoint
            # serves by default (None = plain fp32) and every dtype a
            # request may ask for — clients and the bench read this
            # instead of guessing
            "precision": (getattr(srv, "_default_dtype", "fp32")
                          if getattr(srv, "_default_dtype", "fp32") != "fp32"
                          else None),
            "precision_dtypes": list(
                getattr(srv, "_precision_dtypes", ["fp32"])),
            # storage-dtype discovery: the decode pool's KV dtype and
            # the bound mesh tables' row dtype (None where the surface
            # doesn't apply) — fleet_top renders these as the dtype
            # column and capacity planners read them with the byte
            # gauges
            "kv_dtype": getattr(srv, "kv_dtype", None),
            "row_dtype": self._row_dtype(srv),
            "input_names": list(srv._feed_names),
            "output_names": list(srv._predictor.get_output_names()),
        }

    @staticmethod
    def _row_dtype(srv) -> Optional[str]:
        """Row storage dtype of the served program's bound mesh tables
        (``bind_mesh_tables``), None when it has none."""
        program = getattr(getattr(srv, "_predictor", None),
                          "_program", None)
        runtime = getattr(program, "_mesh_tables", None)
        return getattr(runtime, "row_dtype", None)

    # ------------------------------------------------------------------
    def _infer(self, feed, timeout_ms, traceparent: Optional[str],
               want_spans: bool, priority=None, precision=None):
        """Bridge one wire request into the in-process server: install
        the remote trace context, submit, wait, and (tracing on) hand
        the server-side span tree back for the client-side merge.
        ``timeout_ms`` is the REMAINING deadline the client computed at
        send time; an already-expired one is shed typed at admission
        (``admission_expired_total``) by ``InferenceServer.submit``.
        ``priority`` rides the request meta into priority shedding;
        ``precision`` into the mixed-precision variant dispatch."""
        parsed = codec.parse_traceparent(traceparent)
        tid = parsed[0] if parsed else monitor.new_trace_id()
        remote_parent = parsed[1] if parsed else None
        kw = {}
        if priority is not None:
            kw["priority"] = int(priority)
        if precision is not None:
            kw["precision"] = str(precision)
        fr = _flight.get()
        rec = _spans.recording() or fr is not None
        if not rec:
            outs = self.server.submit(
                feed, timeout_ms=timeout_ms, trace_id=tid, **kw).result()
            return self._result_meta(tid), outs

        t0 = time.perf_counter()
        err: Optional[BaseException] = None
        sid = _spans.new_span_id()
        try:
            with _spans.trace_context((tid,)):
                # this request span is the server-side root: its parent
                # is the CLIENT's wire span (from traceparent), and the
                # spans recorded downstream (queue wait via the request's
                # parent_span, batch/executor via the replica thread)
                # hang off it or off the batch tree
                with _spans.parent_scope(sid):
                    outs = self.server.submit(
                        feed, timeout_ms=timeout_ms, trace_id=tid,
                        parent_span=sid, **kw).result()
        except BaseException as e:  # noqa: BLE001 — observed, re-raised
            err = e
            raise
        finally:
            dur = time.perf_counter() - t0
            with _spans.trace_context((tid,)):
                _spans.record_span(
                    "wire/server_request", t0, dur, cat="wire",
                    span_id=sid, parent=remote_parent,
                    error=err is not None, server=self.server.name)
        meta = self._result_meta(tid)
        if want_spans and fr is not None:
            # the handler's own request span, as an explicit dict: the
            # batch pipeline files the OTHER server-side spans into the
            # flight record, but this one closes right here
            wire_span = {
                "name": "wire/server_request", "cat": "wire", "id": sid,
                "ts": _spans.wall_ts(t0), "dur": dur,
                "tid": threading.get_ident(), "trace_ids": [tid],
                "args": {"server": self.server.name},
            }
            if remote_parent:
                wire_span["parent"] = remote_parent
            # only requests tail sampling RETAINS are worth the grace
            # poll (this path is success-only — errors re-raised above);
            # a fast request under slow_ms will never grow a record, and
            # stalling its response would tax exactly the requests
            # sampling was built to leave untouched
            if dur * 1e3 >= fr.slow_ms:
                spans = self._collect_spans(fr, tid) or []
            else:
                rec_now = fr.get_record(tid)  # one check, no poll
                spans = (rec_now.get("spans") or []) if rec_now else []
            fr.add_span(tid, wire_span)  # local /tracez completeness
            meta["spans"] = list(spans) + [wire_span]
        return meta, outs

    def _submit_stream(self, feed, meta, traceparent: Optional[str]):
        """Bridge one wire stream request into the decode server:
        install the remote trace context and submit WITHOUT waiting —
        the handler streams the request's chunks as the scheduler
        produces them.  Returns ``(request, trace_id)``; every chunk
        message carries that one id, so the stream is a single trace
        end to end."""
        srv = self.server
        if not getattr(srv, "supports_streaming", False):
            raise ServingError(
                "endpoint %r does not stream (not a decode server)"
                % srv.name)
        parsed = codec.parse_traceparent(traceparent)
        tid = parsed[0] if parsed else monitor.new_trace_id()
        kw = {}
        if meta.get("priority") is not None:
            kw["priority"] = int(meta["priority"])
        if meta.get("max_new_tokens") is not None:
            kw["max_new_tokens"] = int(meta["max_new_tokens"])
        if meta.get("speculative"):
            kw["speculative"] = True
        with _spans.trace_context((tid,)):
            req = srv.submit(
                feed, timeout_ms=meta.get("timeout_ms"), trace_id=tid,
                **kw)
        return req, tid

    def _load_meta(self) -> Dict[str, object]:
        """The per-response load report (queue depth + adaptive admit
        limit + brownout level): the balancer folds it into least-loaded
        routing so a backlogged server stops attracting traffic even
        when its in-flight count looks fine from the outside."""
        load = getattr(self.server, "load", None)
        return load() if callable(load) else {}

    def _result_meta(self, tid: str) -> Dict[str, object]:
        return {"trace_id": tid,
                "output_names": list(self.server._predictor.get_output_names()),
                "load": self._load_meta()}

    @staticmethod
    def _collect_spans(fr, tid: str):
        """The retained server-side span tree for ``tid``, or None when
        the recorder didn't keep this request (see _SPAN_MERGE_POLLS)."""
        for i in range(_SPAN_MERGE_POLLS):
            rec = fr.get_record(tid)
            if rec is not None:
                return rec.get("spans") or []
            time.sleep(_SPAN_MERGE_POLL_S)
        return None

    def _quit(self) -> None:
        """Graceful exit for rolling replacement: drain, then unblock
        ``serve_forever``/the launch main."""
        try:
            self.stop(drain=True)
        finally:
            cb = self._shutdown_cb
            if cb is not None:
                cb()

    # ------------------------------------------------------------------
    def start(self) -> Tuple[str, int]:
        """Serve on a background thread; returns the bound address."""
        httpd = self._bind()
        if self._thread is None:
            self._thread = threading.Thread(
                target=httpd.serve_forever,
                name="wire-%s" % self.server.name, daemon=True)
            self._thread.start()
        return httpd.server_address

    def serve_forever(self) -> None:
        """Bind and serve on the CALLING thread (child-process main)."""
        self._bind().serve_forever()

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Close the front door first (stop admitting wire requests),
        then stop the wrapped server — in-flight requests finish under
        ``drain=True``."""
        with self._lock:
            httpd, self._httpd = self._httpd, None
            thread, self._thread = self._thread, None
        if httpd is not None:
            httpd.shutdown()
            httpd.server_close()
        if thread is not None:
            # clear the serve thread too, or a later start() would bind
            # a fresh listener that nothing serves (connections accepted
            # into the backlog would hang)
            thread.join(timeout=5.0)
        self.server.stop(drain=drain, timeout=timeout)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop(drain=exc == (None, None, None))
        return False
