"""paddle_tpu.serving.wire — cross-host serving.

The network edge over the in-process serving stack: the same
batching/bucketing/zero-recompile server, now reachable across the
process (and host) boundary.

* ``codec`` — msgpack-free length-prefixed JSON+npy message framing
  with BOUNDED reads (typed ``WireProtocolError`` on malformed peers)
  and the W3C ``traceparent`` helpers;
* ``Transport`` / ``HttpTransport`` (``http.py``) — the transport ABC
  seam (gRPC slots in later) and the stdlib-HTTP implementation with
  per-thread keep-alive;
* ``RemoteClient`` (``client.py``) — the in-process ``Client`` surface
  over a wire hop: same signatures, same typed errors, trace ids
  carried in ``traceparent`` and the server-side span tree merged into
  the local flight recorder;
* ``ServingProcess`` (``server.py``) — one ``InferenceServer`` behind
  the wire: ``/infer`` + ``/warmup`` + ``/healthz`` + the admin surface
  (``/metrics`` ``/statusz`` ``/tracez``) + ``/quitquitquit``;
* ``launch_server`` / ``ServerHandle`` (``launch.py``) — child-process
  spawning with a race-free ready handshake;
* ``FleetBalancer`` (``fleet.py``) — the front-end: least-loaded
  routing over N serving processes, retirement + requeue-to-survivor
  (accepted requests never drop), active health checks, fleet-wide
  warmup, rolling replica replacement.

Quickstart::

    from paddle_tpu.serving import wire

    fleet = wire.FleetBalancer.from_launch(model_dir, n=4)
    fleet.warmup()                      # every rung, every process
    out, = fleet.infer({"x": rows})     # least-loaded backend
    fleet.rolling_replace()             # zero-downtime restart
    fleet.stop(shutdown_backends=True)
"""
from paddle_tpu.serving.errors import BackendUnavailable, WireProtocolError
from paddle_tpu.serving.wire import codec, metrics
from paddle_tpu.serving.wire.client import RemoteClient
from paddle_tpu.serving.wire.codec import (
    decode_message,
    encode_message,
    format_traceparent,
    parse_traceparent,
)
from paddle_tpu.serving.wire.fleet import FleetBalancer
from paddle_tpu.serving.wire.http import HttpTransport, Transport
from paddle_tpu.serving.wire.launch import ServerHandle, launch_server
from paddle_tpu.serving.wire.server import ServingProcess

__all__ = [
    "codec", "metrics",
    "encode_message", "decode_message",
    "format_traceparent", "parse_traceparent",
    "Transport", "HttpTransport",
    "RemoteClient", "ServingProcess",
    "ServerHandle", "launch_server",
    "FleetBalancer",
    "WireProtocolError", "BackendUnavailable",
]
