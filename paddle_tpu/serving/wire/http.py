"""Wire transports: the ABC seam + the stdlib-HTTP implementation.

``Transport`` is the deliberate narrow waist between the serving client
surface and the bytes on the network: one ``request()`` that moves a
``(meta, arrays)`` message each way plus headers.  The HTTP transport
below implements it with nothing beyond ``http.client`` (POST bodies in
the ``codec`` framing, keep-alive via one pooled connection per calling
thread); a gRPC transport later implements the same four methods and
slots in behind ``RemoteClient``/``FleetBalancer`` untouched.

Failure typing is the transport's contract (the fleet's requeue state
machine routes on it):

* socket timeout            -> ``DeadlineExceeded``   (not retryable)
* refused/reset/half-close  -> ``BackendUnavailable`` (retryable: the
  process died — the balancer re-routes to a survivor)
* malformed response body   -> ``WireProtocolError``
* typed serving errors travel IN-BAND (response meta ``error`` field)
  and are re-raised by the caller, never guessed from status codes.
"""
from __future__ import annotations

import abc
import http.client
import socket
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu import faults as _faults
from paddle_tpu.serving.errors import BackendUnavailable, DeadlineExceeded
from paddle_tpu.serving.wire import codec
from paddle_tpu.serving.wire.metrics import (
    WIRE_BYTES_RECEIVED,
    WIRE_BYTES_SENT,
    WIRE_REQUESTS,
)

__all__ = ["Transport", "HttpTransport", "CONTENT_TYPE"]

CONTENT_TYPE = "application/x-paddle-tpu-wire"

_REQS = WIRE_REQUESTS.labels(role="client")
_SENT = WIRE_BYTES_SENT.labels(role="client")
_RECV = WIRE_BYTES_RECEIVED.labels(role="client")


class Transport(abc.ABC):
    """One bidirectional message exchange with a remote serving process.

    Implementations must be safe for concurrent ``request()`` calls from
    multiple threads (the fleet balancer and ``infer_many`` fan out)."""

    @abc.abstractmethod
    def request(self, path: str, meta: Dict[str, object],
                arrays: Sequence[np.ndarray] = (),
                timeout_s: Optional[float] = None,
                headers: Optional[Dict[str, str]] = None,
                ) -> Tuple[Dict[str, object], List[np.ndarray]]:
        """POST one message, return the response message.  ``timeout_s``
        bounds the whole exchange."""

    def stream(self, path: str, meta: Dict[str, object],
               arrays: Sequence[np.ndarray] = (),
               timeout_s: Optional[float] = None,
               headers: Optional[Dict[str, str]] = None):
        """POST one message, iterate RESPONSE messages as the peer
        produces them (the streaming-decode path: each yielded
        ``(meta, arrays)`` is one codec message read incrementally off
        the response body; the message carrying ``meta['final']`` ends
        the stream).  Optional: a transport that cannot stream raises —
        callers degrade to :meth:`request`."""
        raise NotImplementedError(
            "%s does not support streaming responses"
            % type(self).__name__)

    @abc.abstractmethod
    def get_json(self, path: str,
                 timeout_s: Optional[float] = None) -> Dict[str, object]:
        """GET a JSON control document (health/status surfaces)."""

    def get_text(self, path: str,
                 timeout_s: Optional[float] = None) -> str:
        """GET a plain-text document (the ``/metrics`` exposition — the
        federation scraper's fetch).  Optional: a transport that cannot
        serve raw text raises — the scraper just skips the backend."""
        raise NotImplementedError(
            "%s does not support text GETs" % type(self).__name__)

    @abc.abstractmethod
    def close(self) -> None:
        """Release pooled connections (idempotent)."""

    @property
    @abc.abstractmethod
    def address(self) -> Tuple[str, int]:
        """The remote ``(host, port)`` this transport targets."""


class _CountingReader:
    """File-like over an HTTPResponse that feeds the received-bytes
    counter as the codec pulls frames off the stream."""

    __slots__ = ("_resp", "_counter")

    def __init__(self, resp, counter):
        self._resp = resp
        self._counter = counter

    def read(self, n: int) -> bytes:
        data = self._resp.read(n)
        if data:
            self._counter.inc(len(data))
        return data


class HttpTransport(Transport):
    """stdlib ``http.client`` transport with per-thread keep-alive.

    Each calling thread owns one pooled ``HTTPConnection`` (HTTP/1.1
    keep-alive: steady-state requests reuse the TCP connection — no
    per-request handshake on the hot path); a connection that errors is
    torn down so the next call redials."""

    def __init__(self, host: str, port: int,
                 timeout_s: float = 30.0,
                 max_frame_bytes: int = codec.DEFAULT_MAX_FRAME_BYTES):
        self._host = str(host)
        self._port = int(port)
        self._timeout_s = float(timeout_s)
        self._max_frame_bytes = int(max_frame_bytes)
        self._tls = threading.local()
        self._closed = False

    # ------------------------------------------------------------------
    @property
    def address(self) -> Tuple[str, int]:
        return (self._host, self._port)

    def _conn(self, timeout_s: Optional[float]) -> http.client.HTTPConnection:
        if timeout_s is not None and timeout_s <= 0:
            # a 0/negative socket timeout means NON-BLOCKING mode, whose
            # BlockingIOError would masquerade as a dead backend — an
            # exhausted deadline is typed before it touches the socket
            raise DeadlineExceeded(
                "deadline exhausted before the wire exchange to %s:%d"
                % self.address)
        conn = getattr(self._tls, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                self._host, self._port,
                timeout=timeout_s if timeout_s is not None else self._timeout_s)
            self._tls.conn = conn
        else:
            conn.timeout = (
                timeout_s if timeout_s is not None else self._timeout_s)
            if conn.sock is not None:
                conn.sock.settimeout(conn.timeout)
        return conn

    def _drop_conn(self) -> None:
        conn = getattr(self._tls, "conn", None)
        self._tls.conn = None
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass

    # ------------------------------------------------------------------
    def request(self, path: str, meta: Dict[str, object],
                arrays: Sequence[np.ndarray] = (),
                timeout_s: Optional[float] = None,
                headers: Optional[Dict[str, str]] = None,
                ) -> Tuple[Dict[str, object], List[np.ndarray]]:
        body = codec.encode_message(meta, arrays)
        hdrs = {"Content-Type": CONTENT_TYPE}
        if headers:
            hdrs.update(headers)
        # hot-path: begin wire_request (client side of the hop: one POST
        # over the pooled keep-alive connection; the only waits are
        # socket I/O bounded by the timeout)
        if _faults.active is not None:  # disarmed: one is-None gate
            act = _faults.active.faultpoint(
                "wire.send", backend="%s:%d" % self.address)
            if act is not None:
                body = act.corrupt(body)
        conn = self._conn(timeout_s)
        try:
            conn.request("POST", path, body=body, headers=hdrs)
            resp = conn.getresponse()
            payload = resp.read()
        except socket.timeout as e:
            self._drop_conn()
            raise DeadlineExceeded(
                "wire request to %s:%d timed out" % self.address) from e
        except (ConnectionError, http.client.HTTPException, OSError) as e:
            self._drop_conn()
            raise BackendUnavailable(
                "backend %s:%d unreachable: %r" % (self._host, self._port, e)
            ) from e
        _REQS.inc()
        _SENT.inc(len(body))
        _RECV.inc(len(payload))
        rmeta, rarrays = codec.decode_message(
            payload, max_frame_bytes=self._max_frame_bytes)
        # hot-path: end wire_request
        return rmeta, rarrays

    def stream(self, path: str, meta: Dict[str, object],
               arrays: Sequence[np.ndarray] = (),
               timeout_s: Optional[float] = None,
               headers: Optional[Dict[str, str]] = None):
        """POST, then yield ``(meta, arrays)`` response messages as the
        server produces them (chunked transfer; ``http.client`` decodes
        the chunk framing transparently, the codec reads message by
        message).  The message carrying ``meta['final']`` ends the
        stream; an abandoned or failed stream DROPS the pooled
        connection — a half-read response body can never desync the
        next request on this thread's socket."""
        body = codec.encode_message(meta, arrays)
        hdrs = {"Content-Type": CONTENT_TYPE}
        if headers:
            hdrs.update(headers)
        if _faults.active is not None:  # disarmed: one is-None gate
            act = _faults.active.faultpoint(
                "wire.send", backend="%s:%d" % self.address)
            if act is not None:
                body = act.corrupt(body)
        conn = self._conn(timeout_s)
        try:
            conn.request("POST", path, body=body, headers=hdrs)
            resp = conn.getresponse()
        except socket.timeout as e:
            self._drop_conn()
            raise DeadlineExceeded(
                "wire stream to %s:%d timed out" % self.address) from e
        except (ConnectionError, http.client.HTTPException, OSError) as e:
            self._drop_conn()
            raise BackendUnavailable(
                "backend %s:%d unreachable: %r" % (self._host, self._port, e)
            ) from e
        _REQS.inc()
        _SENT.inc(len(body))
        return self._stream_messages(resp, conn)

    def _stream_messages(self, resp, conn):
        """Generator reading codec messages off one response body.  The
        connection stays pooled only after a CLEAN finish (final message
        seen, body drained); every other exit path drops it."""
        clean = False
        try:
            while True:
                try:
                    rmeta, rarrays = codec.read_message(
                        _CountingReader(resp, _RECV),
                        max_frame_bytes=self._max_frame_bytes)
                except socket.timeout as e:
                    raise DeadlineExceeded(
                        "wire stream from %s:%d timed out"
                        % self.address) from e
                except (ConnectionError, http.client.HTTPException,
                        OSError) as e:
                    raise BackendUnavailable(
                        "backend %s:%d died mid-stream: %r"
                        % (self._host, self._port, e)) from e
                final = bool(rmeta.get("final"))
                if final:
                    # drain + mark clean BEFORE yielding: consumers stop
                    # at the final message without advancing the
                    # generator again, so post-yield code would only run
                    # under GeneratorExit and every stream would drop
                    # its pooled connection
                    resp.read()  # drain the terminator for keep-alive
                    clean = True
                yield rmeta, rarrays
                if final:
                    return
        finally:
            if not clean:
                # this generator may be close()d from ANY thread (the
                # fleet's abandoned-stream GC finalizer) — _drop_conn
                # only clears the CALLING thread's pool slot, so close
                # the very connection the stream was reading; the
                # owning thread's pooled handle then auto-reopens on
                # its next request instead of reusing a half-read
                # socket
                if getattr(self._tls, "conn", None) is conn:
                    self._tls.conn = None
                try:
                    conn.close()
                except Exception:
                    pass

    def get_json(self, path: str,
                 timeout_s: Optional[float] = None) -> Dict[str, object]:
        import json

        conn = self._conn(timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            payload = resp.read()
        except socket.timeout as e:
            self._drop_conn()
            raise DeadlineExceeded(
                "wire GET %s on %s:%d timed out"
                % ((path,) + self.address)) from e
        except (ConnectionError, http.client.HTTPException, OSError) as e:
            self._drop_conn()
            raise BackendUnavailable(
                "backend %s:%d unreachable: %r" % (self._host, self._port, e)
            ) from e
        if resp.status != 200:
            raise BackendUnavailable(
                "GET %s on %s:%d -> HTTP %d"
                % (path, self._host, self._port, resp.status))
        try:
            return json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            from paddle_tpu.serving.errors import WireProtocolError

            raise WireProtocolError("undecodable JSON from %s: %s"
                                    % (path, e)) from e

    def get_text(self, path: str,
                 timeout_s: Optional[float] = None) -> str:
        conn = self._conn(timeout_s)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            payload = resp.read()
        except socket.timeout as e:
            self._drop_conn()
            raise DeadlineExceeded(
                "wire GET %s on %s:%d timed out"
                % ((path,) + self.address)) from e
        except (ConnectionError, http.client.HTTPException, OSError) as e:
            self._drop_conn()
            raise BackendUnavailable(
                "backend %s:%d unreachable: %r" % (self._host, self._port, e)
            ) from e
        if resp.status != 200:
            raise BackendUnavailable(
                "GET %s on %s:%d -> HTTP %d"
                % (path, self._host, self._port, resp.status))
        return payload.decode("utf-8", errors="replace")

    def close(self) -> None:
        self._closed = True
        self._drop_conn()
