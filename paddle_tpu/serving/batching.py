"""Request futures + the dynamic batcher.

Orca/Clipper-style coalescing: concurrent submitters enqueue
row-oriented requests into a BOUNDED queue; the server's worker pulls a
first request, then keeps absorbing arrivals until either
``max_batch_size`` rows are gathered or ``batch_timeout_ms`` has passed
since the batch opened — whichever fires first.  A request that would
overflow the open batch is carried into the next one (never split).

Admission control lives at the queue: a full queue sheds the request
with a typed ServerOverloaded at submit time, so overload back-pressure
reaches the caller immediately instead of growing an unbounded backlog.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from paddle_tpu.serving.errors import DeadlineExceeded, ServerOverloaded

__all__ = ["ServingRequest", "DynamicBatcher"]

# granularity of the shutdown-check poll while blocked on an empty queue
_IDLE_POLL_S = 0.02


class ServingRequest:
    """One submitted inference request: a row-oriented feed plus a
    future the submitter waits on.  ``n_rows`` is the leading dim shared
    by every feed array (validated by the server at submit)."""

    def __init__(self, feed: Dict[str, np.ndarray], n_rows: int,
                 deadline: Optional[float] = None):
        self.feed = feed
        self.n_rows = n_rows
        self.deadline = deadline  # time.monotonic() deadline, or None
        self.submit_t = time.perf_counter()
        self._done = threading.Event()
        self._value: Optional[List[np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    # --- producer (worker) side ---
    def complete(self, value: List[np.ndarray]) -> None:
        if self._done.is_set():
            return  # first completion wins (shutdown races)
        self._value = value
        self._done.set()

    def fail(self, exc: BaseException) -> None:
        if self._done.is_set():
            return  # first completion wins (shutdown races)
        self._exc = exc
        self._done.set()

    def expired(self, now: Optional[float] = None) -> bool:
        return self.deadline is not None and (now or time.monotonic()) >= self.deadline

    # --- consumer (submitter) side ---
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[np.ndarray]:
        """Block for the result.  Honors the request deadline even when
        the server never gets to this request (a deadline must surface
        as a typed error, never a hang)."""
        if timeout is None and self.deadline is not None:
            timeout = max(0.0, self.deadline - time.monotonic())
        if not self._done.wait(timeout):
            raise DeadlineExceeded(
                "no result within %.1f ms" % ((timeout or 0.0) * 1e3))
        if self._exc is not None:
            raise self._exc
        assert self._value is not None
        return self._value


class DynamicBatcher:
    """Bounded request queue + the coalescing policy."""

    def __init__(self, max_batch_size: int, batch_timeout_ms: float,
                 queue_capacity: int):
        self.max_batch_size = int(max_batch_size)
        self.batch_timeout_s = float(batch_timeout_ms) / 1e3
        self._q: "queue.Queue[ServingRequest]" = queue.Queue(maxsize=queue_capacity)
        self._carry: Optional[ServingRequest] = None  # worker-thread only

    def qsize(self) -> int:
        return self._q.qsize() + (1 if self._carry is not None else 0)

    # --- submitter side ---
    def offer(self, req: ServingRequest) -> None:
        try:
            self._q.put_nowait(req)
        except queue.Full:
            raise ServerOverloaded(
                "request queue full (%d waiting); shedding" % self._q.qsize()
            ) from None

    def drain_pending(self) -> List[ServingRequest]:
        """Pop and return every queued-but-unbatched request (shutdown
        without drain: the server fails them with ServerClosed).  Does
        not touch the carry slot — that one is the worker's."""
        out: List[ServingRequest] = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    # --- worker side (single consumer) ---
    def _take_first(self, stop: threading.Event, on_expired,
                    block: bool = True) -> Optional[ServingRequest]:
        if self._carry is not None:
            first, self._carry = self._carry, None
            if not first.expired():
                return first
            on_expired(first)
        while True:
            try:
                first = self._q.get_nowait()
            except queue.Empty:
                if not block or stop.is_set():
                    return None  # nothing ready / drained
                try:
                    first = self._q.get(timeout=_IDLE_POLL_S)
                except queue.Empty:
                    continue
            if first.expired():
                on_expired(first)
                continue
            return first

    def next_batch(self, stop: threading.Event, on_expired,
                   block: bool = True) -> Optional[List[ServingRequest]]:
        """Return the next coalesced batch, or None once stopped AND
        drained.  ``on_expired`` is called with each request whose
        deadline passed while queued (the server fails + counts it).

        ``block=False``: a non-blocking poll — returns None immediately
        when no live request is ready (the server uses this to finalize
        an in-flight d2h batch before idling).

        While draining (``stop`` set) the window is not awaited — only
        already-queued requests coalesce, so shutdown latency is bounded
        by the in-flight work, not by the timeout."""
        first = self._take_first(stop, on_expired, block=block)
        if first is None:
            return None
        batch = [first]
        rows = first.n_rows
        window_end = time.monotonic() + self.batch_timeout_s
        while rows < self.max_batch_size:
            wait = window_end - time.monotonic()
            try:
                if wait > 0 and not stop.is_set():
                    req = self._q.get(timeout=wait)
                else:
                    req = self._q.get_nowait()
            except queue.Empty:
                break
            if req.expired():
                on_expired(req)
                continue
            if rows + req.n_rows > self.max_batch_size:
                self._carry = req  # never split a request across batches
                break
            batch.append(req)
            rows += req.n_rows
        return batch
